"""Kubernetes scheduler-extender semantics: /filter and /prioritize.

Implements the stock extender webhook contract against the TPU scoring
core:

- ``/filter``: ExtenderArgs {pod, nodenames} -> ExtenderFilterResult
  {nodenames, failedNodes} using the fused feasibility mask
  (:func:`~..core.score.feasibility_mask`).
- ``/prioritize``: ExtenderArgs -> HostPriorityList [{host, score}]
  with scores scaled to k8s's 0..10 extender convention, from the full
  masked score matrix.
- ``/bind``: ExtenderBindingArgs -> bookkeeping + Binding via the
  cluster client (optional; stock kube-scheduler can also bind itself).

The reference had no such boundary — it *replaced* kube-scheduler
outright (binding directly, scheduler.go:196-206); the extender shape
lets our scorer augment a stock control plane, with its CPU path as
fallback.
"""

from __future__ import annotations

import json
from typing import Any, Mapping, Sequence

import numpy as np

from kubernetesnetawarescheduler_tpu.config import Resource
from kubernetesnetawarescheduler_tpu.core.loop import SchedulerLoop
from kubernetesnetawarescheduler_tpu.core.pallas_score import score_pods_auto
from kubernetesnetawarescheduler_tpu.core.score import NEG_INF
from kubernetesnetawarescheduler_tpu.k8s.types import Binding, Pod

MAX_EXTENDER_PRIORITY = 10  # k8s scheduler extender convention


def _pod_from_k8s(obj: Mapping[str, Any]) -> Pod:
    """Translate a (subset of a) v1.Pod manifest into our Pod.

    Resource requests come from the max over containers' requests
    (scheduling-relevant aggregate); netaware extensions ride in
    annotations: ``netaware/peers`` (JSON {pod: traffic}),
    ``netaware/group``, ``netaware/affinity``, ``netaware/anti``.
    """
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    annotations = meta.get("annotations") or {}
    requests = {"cpu": 0.0, "mem": 0.0, "net_bw": 0.0}
    for ctr in spec.get("containers") or ():
        req = ((ctr.get("resources") or {}).get("requests") or {})
        requests["cpu"] += _parse_cpu(req.get("cpu", "0"))
        requests["mem"] += _parse_mem(req.get("memory", "0"))
        requests["net_bw"] += float(req.get("netaware/bandwidth-gbps", 0.0))
    peers = {}
    if "netaware/peers" in annotations:
        try:
            peers = {str(k): float(v) for k, v in
                     json.loads(annotations["netaware/peers"]).items()}
        except (ValueError, AttributeError):
            peers = {}
    selector = spec.get("nodeSelector") or {}
    tolerations = frozenset(
        str(t.get("key")) for t in spec.get("tolerations") or ()
        if t.get("key"))
    return Pod(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid", "") or meta.get("name", ""),
        scheduler_name=spec.get("schedulerName", ""),
        requests=requests,
        peers=peers,
        tolerations=tolerations,
        node_selector=frozenset(f"{k}={v}" for k, v in selector.items()),
        group=annotations.get("netaware/group", ""),
        affinity_groups=frozenset(
            g for g in annotations.get("netaware/affinity", "").split(",")
            if g),
        anti_groups=frozenset(
            g for g in annotations.get("netaware/anti", "").split(",") if g),
        priority=float(spec.get("priority", 0) or 0),
    )


def _parse_cpu(text: str) -> float:
    text = str(text)
    if text.endswith("m"):
        return float(text[:-1]) / 1000.0
    try:
        return float(text)
    except ValueError:
        return 0.0


_MEM_SUFFIX = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40,
               "K": 1e3, "M": 1e6, "G": 1e9, "T": 1e12}


def _parse_mem(text: str) -> float:
    """Memory quantity -> GiB (our mem resource unit)."""
    text = str(text)
    for suffix, mult in _MEM_SUFFIX.items():
        if text.endswith(suffix):
            try:
                return float(text[: -len(suffix)]) * mult / 2**30
            except ValueError:
                return 0.0
    try:
        return float(text) / 2**30
    except ValueError:
        return 0.0


class ExtenderHandlers:
    """Stateless-per-request handlers bound to a SchedulerLoop."""

    def __init__(self, loop: SchedulerLoop) -> None:
        self._loop = loop

    # -- ops ----------------------------------------------------------

    def handle(self, path: str, body: bytes) -> bytes:
        if path == "/filter":
            return self._json(self.filter(json.loads(body or b"{}")))
        if path == "/prioritize":
            return self._json(self.prioritize(json.loads(body or b"{}")))
        if path == "/bind":
            return self._json(self.bind(json.loads(body or b"{}")))
        if path == "/health":
            return b'{"ok": true}'
        if path == "/metrics":
            # Self-metrics in Prometheus exposition format (SURVEY.md
            # §5 observability row) — the scheduler is scrapeable the
            # same way it scrapes node_exporters.
            from kubernetesnetawarescheduler_tpu.utils.selfmetrics import (
                render_metrics,
            )
            return render_metrics(self._loop).encode()
        raise ValueError(f"unknown op {path!r}")

    @staticmethod
    def _json(obj: Any) -> bytes:
        return json.dumps(obj).encode()

    def _candidate_names(self, args: Mapping[str, Any]) -> list[str]:
        if args.get("nodenames"):
            return list(args["nodenames"])
        nodes = (args.get("nodes") or {}).get("items") or ()
        return [((n.get("metadata") or {}).get("name", "")) for n in nodes]

    def _score_row(self, args: Mapping[str, Any]
                   ) -> tuple[list[str], np.ndarray, np.ndarray]:
        """(names, feasible-mask row, score row) for the args' pod over
        the args' candidate nodes."""
        loop = self._loop
        pod = _pod_from_k8s(args.get("pod") or {})
        names = self._candidate_names(args)
        if not names:
            empty = np.zeros((0,))
            return [], empty.astype(bool), empty
        batch = loop.encoder.encode_pods([pod], node_of=loop._peer_node,
                                         lenient=True)
        state = loop.encoder.snapshot()
        # Kernel choice (dense XLA vs tiled Pallas) follows
        # cfg.score_backend — this Score/Filter service path is where
        # the 5k-node tiled kernel earns its keep.
        scores = np.asarray(score_pods_auto(state, batch, loop.cfg))[0]
        feasible = scores > float(NEG_INF) * 0.5
        idx = []
        for name in names:
            try:
                idx.append(loop.encoder.node_index(name))
            except KeyError:
                idx.append(-1)
        idx_arr = np.asarray(idx, dtype=np.int64)
        ok = np.where(idx_arr >= 0, feasible[np.maximum(idx_arr, 0)], False)
        sc = np.where(ok, scores[np.maximum(idx_arr, 0)], float(NEG_INF))
        return names, ok, sc

    def filter(self, args: Mapping[str, Any]) -> Mapping[str, Any]:
        names, ok, _ = self._score_row(args)
        passed = [n for n, good in zip(names, ok) if good]
        failed = {n: "netaware: infeasible (capacity/taint/affinity)"
                  for n, good in zip(names, ok) if not good}
        return {"nodenames": passed, "failedNodes": failed, "error": ""}

    def prioritize(self, args: Mapping[str, Any]
                   ) -> Sequence[Mapping[str, Any]]:
        names, ok, scores = self._score_row(args)
        if not names:
            return []
        finite = scores[ok]
        lo = float(finite.min()) if finite.size else 0.0
        hi = float(finite.max()) if finite.size else 1.0
        span = max(hi - lo, 1e-9)
        out = []
        for name, good, sc in zip(names, ok, scores):
            score10 = (int(round((sc - lo) / span * MAX_EXTENDER_PRIORITY))
                       if good else 0)
            out.append({"host": name, "score": score10})
        return out

    def bind(self, args: Mapping[str, Any]) -> Mapping[str, Any]:
        pod_name = args.get("podName", "")
        namespace = args.get("podNamespace", "default")
        node = args.get("node", "")
        try:
            self._loop.client.bind(Binding(pod_name=pod_name,
                                           namespace=namespace,
                                           node_name=node))
        except Exception as exc:  # relay the rejection, don't die
            return {"error": str(exc)}
        # Account the REAL resource requests, else extender-path binds
        # would never raise usage and the scorer would overcommit.
        pod = self._loop.client.get_pod(pod_name)
        if pod is None:
            pod = Pod(name=pod_name, namespace=namespace,
                      requests={r: 0.0 for r in Resource.NAMES})
        self._loop.encoder.commit(pod, node)
        return {"error": ""}
