"""gRPC transport for the Score/Filter service (remote/DCN clients).

Real gRPC (HTTP/2, grpcio) without protoc codegen: generic byte-in/
byte-out method handlers carrying the same JSON payloads as the UDS
frames.  Service surface:

    /netaware.Scorer/Filter      ExtenderArgs JSON -> FilterResult JSON
    /netaware.Scorer/Prioritize  ExtenderArgs JSON -> HostPriorityList
    /netaware.Scorer/Bind        BindingArgs JSON  -> {"error": ...}
    /netaware.Scorer/Health      {}                -> {"ok": true}

This is the DCN-side analog of what the reference entirely lacked — its
only transports were HTTP scrapes and kubectl-cp file drops
(scheduler.go:396-407, run.sh:12-14).
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from kubernetesnetawarescheduler_tpu.api.extender import ExtenderHandlers

SERVICE = "netaware.Scorer"
_METHOD_TO_PATH = {
    "Filter": "/filter",
    "Prioritize": "/prioritize",
    "Bind": "/bind",
    "Health": "/health",
}


def make_handler(handlers: "ExtenderHandlers"):
    """A grpc.GenericRpcHandler serving the scorer ops."""
    import grpc

    class Generic(grpc.GenericRpcHandler):
        def service(self, handler_call_details):
            # full method: /netaware.Scorer/<Method>
            _, service, method = handler_call_details.method.split("/")
            if service != SERVICE or method not in _METHOD_TO_PATH:
                return None
            path = _METHOD_TO_PATH[method]

            def unary(request: bytes, context) -> bytes:
                try:
                    return handlers.handle(path, request)
                except Exception as exc:  # surface as gRPC error
                    context.abort(grpc.StatusCode.INTERNAL, str(exc))
                    return b""

            return grpc.unary_unary_rpc_method_handler(
                unary,
                request_deserializer=None,   # raw bytes
                response_serializer=None)

    return Generic()


def serve_grpc(handlers: "ExtenderHandlers", address: str = "127.0.0.1:0",
               max_workers: int = 8):
    """Start a gRPC server; returns ``(server, bound_port)``."""
    import concurrent.futures

    import grpc

    server = grpc.server(
        concurrent.futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((make_handler(handlers),))
    port = server.add_insecure_port(address)
    server.start()
    return server, port


def call_grpc(address: str, method: str, payload: bytes,
              timeout_s: float = 10.0) -> bytes:
    """Client helper: one unary call with raw-bytes (de)serialization."""
    import grpc

    with grpc.insecure_channel(address) as channel:
        fn = channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=None,
            response_deserializer=None)
        return fn(payload, timeout=timeout_s)
