"""Policy training dataset: join decisions with realized outcomes.

The policy learns from REAL decisions, not synthetic ones.  Two
stores the repo already maintains supply everything:

- the flight recorder's explain store (r8): per shipped decision, the
  top-k candidate nodes with the additive score decomposition
  (``base/net/soft/balance/spread``) and feasibility gates;
- the QualityObserver outcome ring (r11): per shipped decision, the
  realized regret vs the best alternative under subsequent probe
  truth, already bind-generation-gated (a pod rebound since commit
  never produces an outcome for the stale placement).

This module performs the uid join OFF the hot path (maintain
cadence): each quality outcome that has an explain record becomes one
training example — the candidate component matrix, the feasibility
mask, and a target label:

- shipped choice, when its realized regret stayed within
  ``cfg.policy_regret_margin`` (the decision was vindicated);
- else the hindsight-best candidate — the feasible candidate with the
  highest recorded net desirability, the same term the observer
  measured the regret in (the decision overpaid on the network and
  hindsight says which candidate would not have).

Outcomes are deduplicated on ``(uid, t_harvest)`` through a bounded
seen-set, so re-reading a stable outcome ring never double-counts an
example; evictions from that set only ever risk re-ingesting an old
example into a ring that samples with replacement anyway.
"""

from __future__ import annotations

import collections
from typing import Any, Mapping, NamedTuple

import numpy as np

from kubernetesnetawarescheduler_tpu.config import SchedulerConfig
from kubernetesnetawarescheduler_tpu.policy.model import (
    NUM_TERMS,
    TERMS,
    _record_arrays,
)


class ExampleBatch(NamedTuple):
    """One harvest's worth of training examples (numpy, host-side)."""

    comps: np.ndarray    # f32[B, K, NUM_TERMS]
    feas: np.ndarray     # f32[B, K]
    target: np.ndarray   # i32[B]
    cls: np.ndarray      # i32[B, K]
    uids: tuple[str, ...]


class PolicyDataset:
    """Bounded, idempotent outcome->example harvester.

    One instance per loop; :meth:`collect` is called from the policy
    maintain tick and by tests/bench directly.  Not thread-safe on
    its own — the caller (the maintain tick) is single-threaded, and
    the stores it reads are themselves thread-safe snapshots."""

    def __init__(self, cfg: SchedulerConfig, k_pad: int) -> None:
        self.cfg = cfg
        self.k_pad = int(k_pad)
        # (uid, t_harvest) pairs already converted to examples; twice
        # the outcome ring so the seen-set always covers everything
        # still resident in it.
        self._seen: collections.OrderedDict[tuple[str, float], None] = (
            collections.OrderedDict())
        self._seen_cap = max(16, 2 * cfg.quality_ring_size)
        self.joined_total = 0        # examples produced
        self.no_explain_total = 0    # outcome without explain record
        self.unlabelable_total = 0   # no feasible/shipped candidate

    def collect(self, flight, quality) -> ExampleBatch | None:
        """Join fresh quality outcomes against the explain store;
        returns the resulting examples (None when nothing new)."""
        if flight is None or quality is None:
            return None
        outcomes = quality.outcomes()
        if not outcomes:
            return None
        rows: list[tuple[np.ndarray, np.ndarray, np.ndarray, int]] = []
        uids: list[str] = []
        margin = self.cfg.policy_regret_margin
        for out in outcomes:
            uid = str(out.get("pod_uid", ""))
            key = (uid, float(out.get("t_harvest", 0.0)))
            if not uid or key in self._seen:
                continue
            self._seen[key] = None
            while len(self._seen) > self._seen_cap:
                self._seen.popitem(last=False)
            rec = flight.get_explain(uid)
            if rec is None or not rec.get("candidates"):
                self.no_explain_total += 1
                continue
            example = self._label(rec, out, margin)
            if example is None:
                self.unlabelable_total += 1
                continue
            rows.append(example)
            uids.append(uid)
        if not rows:
            return None
        self.joined_total += len(rows)
        return ExampleBatch(
            comps=np.stack([r[0] for r in rows]),
            feas=np.stack([r[1] for r in rows]),
            target=np.asarray([r[3] for r in rows], np.int32),
            cls=np.stack([r[2] for r in rows]),
            uids=tuple(uids))

    def _label(self, rec: Mapping[str, Any], out: Mapping[str, Any],
               margin: float) -> tuple[np.ndarray, np.ndarray,
                                       np.ndarray, int] | None:
        cand = rec["candidates"]
        comps, feas, cls = _record_arrays(cand, self.k_pad)
        if not (feas > 0).any():
            return None
        shipped_idx = rec.get("node_index", -1)
        shipped_pos = None
        for i, c in enumerate(cand[:self.k_pad]):
            if (shipped_idx is not None
                    and int(c.get("node_index", -2)) == int(shipped_idx)
                    and feas[i] > 0):
                shipped_pos = i
                break
        regret = float(out.get("regret", 0.0))
        if shipped_pos is not None and regret <= margin:
            target = shipped_pos
        else:
            # Hindsight label: the feasible candidate with the best
            # recorded net desirability.  TERMS.index kept symbolic so
            # a component reorder breaks loudly here, not silently.
            net_col = comps[:, TERMS.index("net")]
            masked = np.where(feas > 0, net_col, -np.inf)
            target = int(np.argmax(masked))
            if not np.isfinite(masked[target]):
                return None
        return comps, feas, cls, int(target)

    def summary(self) -> dict[str, Any]:
        return {
            "joined_total": self.joined_total,
            "no_explain_total": self.no_explain_total,
            "unlabelable_total": self.unlabelable_total,
            "seen_depth": len(self._seen),
        }


__all__ = ["ExampleBatch", "PolicyDataset", "NUM_TERMS"]
