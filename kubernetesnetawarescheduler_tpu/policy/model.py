"""Learned scoring policy: term-level multipliers fit on the
scheduler's own decision/outcome log.

The hand-tuned :class:`~kubernetesnetawarescheduler_tpu.config.ScoreWeights`
constants are inherited from the Go reference's vote weights; nothing
in the repo ever checks whether 3/2/1/1/3/1 (or peer_bw=2 vs
balance=1) is the right trade for THIS cluster.  This module learns
that trade from evidence the system already produces: the r8 explain
store records every decision's top-k candidates WITH the additive
score decomposition, and the r11 QualityObserver joins each shipped
choice against realized probe truth (regret vs the best alternative).

Parameterization — deliberately tiny.  The score is already a sum of
five term groups (``base + net + soft - balance - spread``,
core/score.py), so the policy learns a log-space multiplier per term
group plus an optional per-zone-class additive bias:

    score_k = sum_t exp(theta[t]) * comp[t, k] + class_adj[zone_k]

``theta = 0`` is exactly the incumbent scorer (multiplier 1 per
term), so the identity init means an untrained policy shadow-agrees
with production by construction, and the learned weights stay
interpretable as "how much MORE the outcomes justify weighting the
net term" — directly mappable back onto a concrete ``ScoreWeights``
for promotion (:meth:`ScoringPolicy.to_score_weights`).

Training mirrors netmodel/model.py verbatim: ONE jitted Adam
mini-batch step (static shapes, compiled once per process) over a
bounded host ring of examples, inverse-sqrt lr decay floored at
lr/8, and an EMA/Polyak read for serving so shadow decisions don't
jitter with the mini-batch orbit.  The objective is a masked softmax
cross-entropy over each decision's candidate set: the target is the
shipped choice when its realized regret stayed under
``cfg.policy_regret_margin``, else the hindsight-best candidate (the
feasible one with the highest net desirability — the term the
quality observer measured the regret in).

PROMOTION NEVER HAPPENS HERE.  The policy only ever (a) trains, (b)
shadow-scores recorded decisions and counts disagreement, and (c)
hands candidate weights to :mod:`policy.replay_eval`'s counterfactual
gate.  With ``enable_learned_score`` off the subsystem is never
constructed and scoring is bit-identical to a build without it
(tests/test_policy.py).
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Mapping, NamedTuple, Sequence

import numpy as np

from kubernetesnetawarescheduler_tpu.config import (
    SchedulerConfig,
    ScoreWeights,
)

#: Order of the additive score-term groups the multipliers apply to
#: (matches the ``components`` dict of an explain record; balance and
#: spread are stored there as the SIGNED contribution, so a plain
#: weighted sum reproduces the total).
TERMS = ("base", "net", "soft", "balance", "spread")
NUM_TERMS = len(TERMS)

# Infeasible-candidate mask value: matches core/score.py's NEG_INF
# discipline (large-negative instead of -inf so downstream math never
# produces NaN via inf - inf).
_NEG = np.float32(-1e30)

# Polyak averaging horizon for the serving/shadow read — the same
# constant (and the same reasoning) as netmodel's prediction EMA:
# mini-batch Adam orbits its optimum, and a shadow decision flapping
# with that orbit would read as disagreement churn, not signal.
_EMA_DECAY = 0.998


class PolicyParams(NamedTuple):
    """Learnable parameters (a JAX pytree)."""

    theta: Any       # f32[NUM_TERMS]  log-space term multipliers
    class_adj: Any   # f32[C]          per-zone-class additive bias


def _round_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _candidate_scores(params: PolicyParams, comps, cls):
    """Policy score per candidate: ``comps[..., K, T] @ exp(theta)``
    plus the zone-class bias where the candidate's class is known
    (``cls < 0`` = unknown zone, no adjustment)."""
    import jax.numpy as jnp

    mult = jnp.exp(params.theta)
    z = jnp.sum(comps * mult, axis=-1)
    # Clip keeps an out-of-range class (zone interned past max_zones)
    # from indexing OOB; the where() still zeroes unknown (-1) rows.
    c = jnp.clip(cls, 0, params.class_adj.shape[0] - 1)
    return z + jnp.where(cls >= 0, params.class_adj[c], 0.0)


def _loss(params: PolicyParams, comps, feas, target, cls):
    """Masked softmax cross-entropy of the target candidate, plus a
    light pull of theta toward 0 (multiplier 1): with few examples
    the policy should stay NEAR the incumbent, not wander."""
    import jax.numpy as jnp
    from jax.nn import logsumexp

    z = _candidate_scores(params, comps, cls)
    z = jnp.where(feas > 0, z, _NEG)
    logp = z - logsumexp(z, axis=-1, keepdims=True)
    ce = -jnp.take_along_axis(logp, target[:, None], axis=-1)[:, 0]
    reg = (1e-3 * jnp.sum(jnp.square(params.theta))
           + 1e-4 * jnp.mean(jnp.square(params.class_adj)))
    return jnp.mean(ce) + reg


def _sgd_step(params: PolicyParams, m: PolicyParams, v: PolicyParams,
              t, ema: PolicyParams, comps, feas, target, cls, lr):
    """THE jitted update: one Adam mini-batch step + the shadow-read
    EMA accumulate — the netmodel ``_sgd_step`` shape applied to the
    policy pytree (b1/b2/eps and the bias-corrected moments are
    identical; see netmodel/model.py for why Adam and why the EMA)."""
    import jax as _jax
    import jax.numpy as jnp

    b1, b2, eps = 0.9, 0.999, 1e-8
    grads = _jax.grad(_loss)(params, comps, feas, target, cls)
    t = t + 1
    m = PolicyParams(*(b1 * a + (1 - b1) * g
                       for a, g in zip(m, grads)))
    v = PolicyParams(*(b2 * a + (1 - b2) * g * g
                       for a, g in zip(v, grads)))
    c1 = 1.0 - b1 ** t
    c2 = 1.0 - b2 ** t
    params = PolicyParams(
        *(p - lr * (a / c1) / (jnp.sqrt(b / c2) + eps)
          for p, a, b in zip(params, m, v)))
    ema = PolicyParams(*(_EMA_DECAY * e + (1.0 - _EMA_DECAY) * p
                         for e, p in zip(ema, params)))
    return params, m, v, t, ema


class ScoringPolicy:
    """Policy parameters + example ring + promotion bookkeeping.

    Threading: the maintain tick calls :meth:`add_examples` /
    :meth:`train` / :meth:`shadow_rank`; scrape/debug threads read
    :meth:`summary`; the counterfactual gate reads
    :meth:`to_score_weights`.  One RLock guards all mutable state
    (the policy never calls back into loop/encoder)."""

    def __init__(self, cfg: SchedulerConfig, seed: int = 0) -> None:
        import jax.numpy as jnp

        self.cfg = cfg
        self.seed = int(seed)
        self._lock = threading.RLock()
        # Candidate axis padded to a pow2 of the explain top-k so the
        # jitted step compiles once per process (same static-shape
        # discipline as the netmodel batch).
        self.k_pad = _round_pow2(max(4, cfg.explain_top_k))
        self.num_classes = max(1, cfg.max_zones)
        self._params = PolicyParams(
            theta=jnp.zeros((NUM_TERMS,), jnp.float32),
            class_adj=jnp.zeros((self.num_classes,), jnp.float32))
        self._opt_m = PolicyParams(*(jnp.zeros_like(p)
                                     for p in self._params))
        self._opt_v = PolicyParams(*(jnp.zeros_like(p)
                                     for p in self._params))
        self._opt_t = jnp.zeros((), jnp.float32)
        self._ema = PolicyParams(*(jnp.zeros_like(p)
                                   for p in self._params))
        import jax as _jax

        self._step = _jax.jit(_sgd_step)

        cap = cfg.policy_ring
        self._ring_comps = np.zeros((cap, self.k_pad, NUM_TERMS),
                                    np.float32)
        self._ring_feas = np.zeros((cap, self.k_pad), np.float32)
        self._ring_target = np.zeros((cap,), np.int32)
        self._ring_cls = np.full((cap, self.k_pad), -1, np.int32)
        self._ring_pos = 0
        self._ring_count = 0
        self._batch_rng = np.random.default_rng(seed + 1)

        self.examples_total = 0     # examples ever ingested
        self.steps_total = 0        # Adam steps dispatched
        self.trains_total = 0       # train() calls that stepped
        self.evals_total = 0        # counterfactual gate runs
        self.promotions_total = 0
        self.rejections_total = 0   # gate runs that refused promotion
        self.shadow_agree_total = 0
        self.shadow_disagreement_total = 0
        # Version of the parameters the LAST promotion shipped (0 =
        # hand-tuned weights still live); provenance of that decision
        # rides checkpoint meta via last_promotion.
        self.promoted_version = 0
        self.promoted_weights: ScoreWeights | None = None
        self.last_promotion: dict[str, Any] | None = None
        self._version = 0
        self._np_params: PolicyParams | None = None
        self._refresh_np_locked()

    # -- dataset ring -------------------------------------------------

    def add_examples(self, comps: np.ndarray, feas: np.ndarray,
                     target: np.ndarray, cls: np.ndarray) -> int:
        """Insert harvested examples (``[B, k_pad, T]`` components,
        ``[B, k_pad]`` feasibility/class, ``[B]`` target index) into
        the ring.  Returns examples accepted."""
        b = int(comps.shape[0])
        if b == 0:
            return 0
        if (comps.shape[1:] != (self.k_pad, NUM_TERMS)
                or feas.shape != (b, self.k_pad)
                or cls.shape != (b, self.k_pad)
                or target.shape != (b,)):
            raise ValueError(
                f"example shapes {comps.shape}/{feas.shape}/"
                f"{cls.shape}/{target.shape} do not match "
                f"k_pad={self.k_pad}")
        cap = self._ring_comps.shape[0]
        with self._lock:
            for i in range(b):
                p = self._ring_pos
                self._ring_comps[p] = comps[i]
                self._ring_feas[p] = feas[i]
                self._ring_target[p] = target[i]
                self._ring_cls[p] = cls[i]
                self._ring_pos = (p + 1) % cap
                self._ring_count = min(self._ring_count + 1, cap)
            self.examples_total += b
        return b

    def ring_depth(self) -> int:
        with self._lock:
            return self._ring_count

    # -- training -----------------------------------------------------

    def train(self, steps: int | None = None) -> int:
        """Run ``steps`` (default ``cfg.policy_steps``) Adam steps
        over the example ring; returns steps dispatched.  Below
        ``cfg.policy_min_examples`` harvested examples nothing runs —
        a handful of decisions is noise, not a dataset."""
        cfg = self.cfg
        if steps is None:
            steps = cfg.policy_steps
        with self._lock:
            count = self._ring_count
            if count < cfg.policy_min_examples or steps <= 0:
                return 0
            params, m, v, t, ema = (self._params, self._opt_m,
                                    self._opt_v, self._opt_t,
                                    self._ema)
            lr = max(cfg.policy_lr
                     / float(np.sqrt(1.0 + self.steps_total / 500.0)),
                     cfg.policy_lr / 8.0)
            for _ in range(steps):
                idx = self._batch_rng.integers(0, count,
                                               size=cfg.policy_batch)
                params, m, v, t, ema = self._step(
                    params, m, v, t, ema,
                    self._ring_comps[idx], self._ring_feas[idx],
                    self._ring_target[idx], self._ring_cls[idx], lr)
            self._params = params
            self._opt_m, self._opt_v, self._opt_t = m, v, t
            self._ema = ema
            self.steps_total += steps
            self.trains_total += 1
            self._version += 1
            self._refresh_np_locked()
        return steps

    def _eval_params_locked(self) -> PolicyParams:
        """Bias-corrected EMA read (raw params before the first
        step) — identical discipline to netmodel."""
        t = float(self._opt_t)
        if t <= 0:
            return self._params
        c = 1.0 - _EMA_DECAY ** t
        return PolicyParams(*(e / c for e in self._ema))

    def _refresh_np_locked(self) -> None:
        self._np_params = PolicyParams(
            *(np.asarray(p) for p in self._eval_params_locked()))

    # -- reads --------------------------------------------------------

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def multipliers(self) -> np.ndarray:
        """``exp(theta)`` per TERMS entry, from the EMA read."""
        with self._lock:
            return np.exp(
                np.asarray(self._np_params.theta, np.float64))

    def predict(self, comps: np.ndarray, feas: np.ndarray,
                cls: np.ndarray) -> np.ndarray:
        """Host-side candidate scores ``[..., K]`` under the EMA
        parameters (infeasible candidates masked to -1e30).  Cheap
        numpy math — this is the shadow/replay read, never the
        serving hot path."""
        with self._lock:
            p = self._np_params
        mult = np.exp(p.theta.astype(np.float64))
        z = comps.astype(np.float64) @ mult
        c = np.clip(cls, 0, p.class_adj.shape[0] - 1)
        z = z + np.where(cls >= 0, p.class_adj[c], 0.0)
        return np.where(feas > 0, z, float(_NEG))

    def shadow_rank(self, record: Mapping[str, Any]) -> int | None:
        """The policy's preferred ``node_index`` for one explain
        record (None when the record has no feasible candidates).
        Counts agreement/disagreement against the shipped decision."""
        cand = record.get("candidates") or []
        if not cand:
            return None
        comps, feas, cls = _record_arrays(cand, self.k_pad)
        scores = self.predict(comps[None], feas[None], cls[None])[0]
        if not (feas > 0).any():
            return None
        best = int(np.argmax(scores))
        pick = int(cand[best]["node_index"])
        shipped = record.get("node_index", -1)
        with self._lock:
            if shipped is not None and int(shipped) == pick:
                self.shadow_agree_total += 1
            else:
                self.shadow_disagreement_total += 1
        return pick

    def disagreement_rate(self) -> float:
        with self._lock:
            n = self.shadow_agree_total + self.shadow_disagreement_total
            if n == 0:
                return 0.0
            return self.shadow_disagreement_total / n

    def to_score_weights(self, base: ScoreWeights | None = None
                         ) -> ScoreWeights:
        """Map the learned term multipliers onto a concrete
        :class:`ScoreWeights` (what the counterfactual gate replays
        and what a promotion installs): the base multiplier scales
        every metric-vote channel, net scales both peer terms, and
        soft/balance/spread scale their own knobs.  The zone-class
        bias has no ScoreWeights analog — it only sharpens the
        shadow/label model — so promotion is driven by the term
        multipliers alone."""
        w = base if base is not None else self.cfg.weights
        m = self.multipliers()
        return dataclasses.replace(
            w,
            cpu=w.cpu * m[0], mem=w.mem * m[0],
            net_tx=w.net_tx * m[0], net_rx=w.net_rx * m[0],
            bandwidth=w.bandwidth * m[0], disk=w.disk * m[0],
            peer_bw=w.peer_bw * m[1], peer_lat=w.peer_lat * m[1],
            soft_affinity=w.soft_affinity * m[2],
            balance=w.balance * m[3],
            spread=w.spread * m[4])

    def note_promotion(self, decision: Mapping[str, Any],
                       weights: ScoreWeights) -> None:
        """Record a gate-approved promotion (called by the loop AFTER
        it installed ``weights``); provenance lands in checkpoint
        meta and /debug/policy."""
        with self._lock:
            self.promotions_total += 1
            self.promoted_version = self._version
            self.promoted_weights = weights
            self.last_promotion = dict(decision)

    # -- fleet transfer (r15) -----------------------------------------

    def export_params(self) -> dict[str, np.ndarray]:
        """EMA-read parameters as plain numpy — the fleet transfer
        registry's donor payload (decoupled from this policy's jax
        buffers, so a registry entry outlives the donor tenant)."""
        with self._lock:
            p = self._eval_params_locked()
            return {"theta": np.asarray(p.theta, np.float32).copy(),
                    "class_adj": np.asarray(p.class_adj,
                                            np.float32).copy()}

    def warm_start_from(self, theta: np.ndarray,
                        class_adj: np.ndarray) -> None:
        """Seed parameters from a donor tenant (fleet transfer).

        Optimizer state starts FRESH (``opt_t=0``, so the eval read
        returns the seeded parameters verbatim until this tenant's own
        first train step), and ``class_adj`` is zero-padded/truncated
        to this config's zone-class count — donor and recipient need
        not share ``max_zones``.  Transfer changes only where learning
        STARTS: the seeded policy still serves shadow-only until it
        wins this tenant's own counterfactual-replay gate."""
        import jax.numpy as jnp

        th = np.asarray(theta, np.float32).reshape(-1)
        if th.shape[0] != NUM_TERMS:
            raise ValueError(
                f"donor theta has {th.shape[0]} terms, "
                f"expected {NUM_TERMS}")
        ca = np.zeros((self.num_classes,), np.float32)
        src = np.asarray(class_adj, np.float32).reshape(-1)
        n = min(self.num_classes, src.shape[0])
        ca[:n] = src[:n]
        with self._lock:
            self._params = PolicyParams(theta=jnp.asarray(th),
                                        class_adj=jnp.asarray(ca))
            self._opt_m = PolicyParams(*(jnp.zeros_like(p)
                                         for p in self._params))
            self._opt_v = PolicyParams(*(jnp.zeros_like(p)
                                         for p in self._params))
            self._opt_t = jnp.zeros((), jnp.float32)
            self._ema = PolicyParams(*(jnp.zeros_like(p)
                                       for p in self._params))
            self._version += 1
            self._refresh_np_locked()

    def summary(self) -> dict[str, Any]:
        """One-shot stats block for /debug/policy, /metrics, bench."""
        with self._lock:
            mult = np.exp(np.asarray(self._np_params.theta,
                                     np.float64))
            return {
                "enabled": True,
                "version": self._version,
                "ring_depth": self._ring_count,
                "ring_size": int(self._ring_comps.shape[0]),
                "examples_total": self.examples_total,
                "steps_total": self.steps_total,
                "trains_total": self.trains_total,
                "evals_total": self.evals_total,
                "promotions_total": self.promotions_total,
                "rejections_total": self.rejections_total,
                "promoted_version": self.promoted_version,
                "shadow_agree_total": self.shadow_agree_total,
                "shadow_disagreement_total":
                    self.shadow_disagreement_total,
                "disagreement_rate": (
                    self.shadow_disagreement_total
                    / max(1, self.shadow_agree_total
                          + self.shadow_disagreement_total)),
                "multipliers": {t: float(mult[i])
                                for i, t in enumerate(TERMS)},
                "last_promotion": (dict(self.last_promotion)
                                   if self.last_promotion else None),
            }

    # -- persistence --------------------------------------------------

    def save(self, path: str) -> None:
        """Atomically persist parameters + optimizer + EMA + example
        ring + counters to one ``.npz`` (save -> load -> predict is
        exact; pinned by tests/test_policy.py)."""
        with self._lock:
            arrays = {f"param_{n}": np.asarray(v)
                      for n, v in zip(PolicyParams._fields,
                                      self._params)}
            arrays.update({f"opt_m_{n}": np.asarray(v)
                           for n, v in zip(PolicyParams._fields,
                                           self._opt_m)})
            arrays.update({f"opt_v_{n}": np.asarray(v)
                           for n, v in zip(PolicyParams._fields,
                                           self._opt_v)})
            arrays["opt_t"] = np.asarray(self._opt_t)
            arrays.update({f"ema_{n}": np.asarray(v)
                           for n, v in zip(PolicyParams._fields,
                                           self._ema)})
            arrays.update(
                ring_comps=self._ring_comps.copy(),
                ring_feas=self._ring_feas.copy(),
                ring_target=self._ring_target.copy(),
                ring_cls=self._ring_cls.copy(),
                scalars=np.asarray(
                    [self._ring_pos, self._ring_count,
                     self.examples_total, self.steps_total,
                     self.trains_total, self.evals_total,
                     self.promotions_total, self.rejections_total,
                     self.shadow_agree_total,
                     self.shadow_disagreement_total,
                     self.promoted_version, self._version],
                    np.float64))
            if self.promoted_weights is not None:
                arrays["promoted_weights"] = np.asarray(
                    _weights_to_vector(self.promoted_weights),
                    np.float64)
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            np.savez_compressed(fh, **arrays)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str, cfg: SchedulerConfig,
             seed: int = 0) -> "ScoringPolicy":
        import jax.numpy as jnp

        policy = cls(cfg, seed=seed)
        with np.load(path) as data:
            params = []
            for name, init in zip(PolicyParams._fields,
                                  policy._params):
                stored = data[f"param_{name}"]
                if stored.shape != init.shape:
                    raise ValueError(
                        f"policy checkpoint param {name} has shape "
                        f"{stored.shape}, config expects "
                        f"{init.shape} (max_zones changed — start "
                        "fresh)")
                params.append(jnp.asarray(stored))
            policy._params = PolicyParams(*params)
            policy._opt_m = PolicyParams(
                *(jnp.asarray(data[f"opt_m_{n}"])
                  for n in PolicyParams._fields))
            policy._opt_v = PolicyParams(
                *(jnp.asarray(data[f"opt_v_{n}"])
                  for n in PolicyParams._fields))
            policy._opt_t = jnp.asarray(data["opt_t"])
            policy._ema = PolicyParams(
                *(jnp.asarray(data[f"ema_{n}"])
                  for n in PolicyParams._fields))
            for ring in ("ring_comps", "ring_feas", "ring_target",
                         "ring_cls"):
                stored = data[ring]
                target = getattr(policy, f"_{ring}")
                if stored.shape != target.shape:
                    raise ValueError(
                        f"policy checkpoint {ring} has shape "
                        f"{stored.shape}, config ring is "
                        f"{target.shape}")
                target[...] = stored
            sc = data["scalars"]
            policy._ring_pos = int(sc[0])
            policy._ring_count = int(sc[1])
            policy.examples_total = int(sc[2])
            policy.steps_total = int(sc[3])
            policy.trains_total = int(sc[4])
            policy.evals_total = int(sc[5])
            policy.promotions_total = int(sc[6])
            policy.rejections_total = int(sc[7])
            policy.shadow_agree_total = int(sc[8])
            policy.shadow_disagreement_total = int(sc[9])
            policy.promoted_version = int(sc[10])
            policy._version = int(sc[11])
            if "promoted_weights" in data:
                policy.promoted_weights = _weights_from_vector(
                    data["promoted_weights"])
        policy._refresh_np_locked()
        return policy


# -- ScoreWeights <-> flat vector (canonical order, shared with
#    tools/state_audit.py and the checkpoint meta block) -------------

WEIGHT_FIELDS = ("cpu", "mem", "net_tx", "net_rx", "bandwidth",
                 "disk", "peer_bw", "peer_lat", "balance",
                 "soft_affinity", "spread")


def _weights_to_vector(w: ScoreWeights) -> list[float]:
    return [float(getattr(w, f)) for f in WEIGHT_FIELDS]


def _weights_from_vector(vec: Sequence[float]) -> ScoreWeights:
    return ScoreWeights(**{f: float(v)
                           for f, v in zip(WEIGHT_FIELDS, vec)})


def _record_arrays(candidates: Sequence[Mapping[str, Any]],
                   k_pad: int) -> tuple[np.ndarray, np.ndarray,
                                        np.ndarray]:
    """Pack one explain record's candidate list into the fixed
    ``[k_pad]`` arrays the policy consumes (shared by shadow ranking,
    the dataset builder and the counterfactual gate)."""
    comps = np.zeros((k_pad, NUM_TERMS), np.float32)
    feas = np.zeros((k_pad,), np.float32)
    cls = np.full((k_pad,), -1, np.int32)
    for i, c in enumerate(candidates[:k_pad]):
        cc = c.get("components") or {}
        for t_idx, term in enumerate(TERMS):
            comps[i, t_idx] = float(cc.get(term, 0.0))
        feas[i] = 1.0 if c.get("feasible") else 0.0
        cls[i] = int(c.get("zone", -1))
    return comps, feas, cls
