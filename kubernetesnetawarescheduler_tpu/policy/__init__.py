"""Learned scoring policy (``enable_learned_score``).

Three parts, wired through the serving loop's maintain cadence:

- :mod:`.model` — term-level multiplier model (one jitted Adam step
  over a bounded example ring, EMA read, npz checkpoint);
- :mod:`.dataset` — off-hot-path join of explain records and quality
  outcomes into training examples;
- :mod:`.replay_eval` — the counterfactual promotion gate (recorded
  re-score + seeded scenario replay through the r13 scorecard).

Disabled (the default) the subsystem is never constructed and
scoring is bit-identical to the hand-tuned weights.
"""

from kubernetesnetawarescheduler_tpu.policy.dataset import (
    PolicyDataset,
)
from kubernetesnetawarescheduler_tpu.policy.model import (
    TERMS,
    PolicyParams,
    ScoringPolicy,
)
from kubernetesnetawarescheduler_tpu.policy.replay_eval import (
    PromotionDecision,
    evaluate_candidate,
    rescore_records,
    term_multipliers,
)

__all__ = [
    "PolicyDataset",
    "PolicyParams",
    "PromotionDecision",
    "ScoringPolicy",
    "TERMS",
    "evaluate_candidate",
    "rescore_records",
    "term_multipliers",
]
