"""Counterfactual promotion gate: candidate weights must WIN a
replay before they may touch the live scorer.

A learned policy that merely fits its own training log is not
evidence it schedules better — the classic failure mode of
log-trained policies is confidently reweighting itself into a corner
the log never visited.  So candidate weights are NEVER promoted
directly.  Two counterfactual legs run first:

1. **Recorded-decision re-score** (cheap, always available): every
   retained explain record's candidate set is re-ranked under the
   candidate term multipliers; the candidate policy's winner is
   compared against the incumbent's recorded winner on the NET
   desirability term — the component the QualityObserver measures
   regret in.  The candidate must not regress this hindsight proxy,
   and the fraction of decisions it would have changed is the
   published disagreement rate.

2. **Seeded scenario replay** (authoritative): the same scenario
   trace is replayed through the REAL loop twice — incumbent weights
   vs candidate weights (via :func:`scenario.replay.replay_trace`'s
   ``score_weights`` override) — and the r13 scorecards are compared
   on ``bandwidth.realized_bw_ratio_vs_oracle``.  Promotion requires
   the candidate to beat the incumbent by at least
   ``cfg.policy_promote_margin``.

No trace, no promotion: without the replay leg the gate refuses and
the policy keeps shadow-scoring (disagreement rate still exported) —
the fail-safe default OPERATIONS.md documents.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Mapping, Sequence

import numpy as np

from kubernetesnetawarescheduler_tpu.config import (
    SchedulerConfig,
    ScoreWeights,
)
from kubernetesnetawarescheduler_tpu.policy.model import (
    TERMS,
    _record_arrays,
)

#: ScoreWeights fields per score-term group, aligned with TERMS.
_TERM_GROUPS: dict[str, tuple[str, ...]] = {
    "base": ("cpu", "mem", "net_tx", "net_rx", "bandwidth", "disk"),
    "net": ("peer_bw", "peer_lat"),
    "soft": ("soft_affinity",),
    "balance": ("balance",),
    "spread": ("spread",),
}


def term_multipliers(candidate: ScoreWeights,
                     incumbent: ScoreWeights) -> np.ndarray:
    """Per-TERM multiplier taking incumbent weights to candidate
    weights (mean field ratio per group; a zero incumbent field
    contributes ratio 1 unless the candidate moved it, in which case
    the absolute candidate value stands in — there is no finite
    multiplier from 0)."""
    mult = np.ones((len(TERMS),), np.float64)
    for t_idx, term in enumerate(TERMS):
        ratios = []
        for field in _TERM_GROUPS[term]:
            inc = float(getattr(incumbent, field))
            cand = float(getattr(candidate, field))
            if inc != 0.0:
                ratios.append(cand / inc)
            elif cand != 0.0:
                ratios.append(cand)
        if ratios:
            mult[t_idx] = float(np.mean(ratios))
    return mult


@dataclasses.dataclass(frozen=True)
class PromotionDecision:
    """The gate's verdict — provenance that rides /debug/policy,
    checkpoint meta and the bench artifact."""

    promote: bool
    reason: str
    candidate_weights: ScoreWeights
    incumbent_ratio: float      # replay realized-bw ratio vs oracle
    candidate_ratio: float
    replay_delta: float         # candidate_ratio - incumbent_ratio
    records_delta: float        # mean net-term delta on recorded set
    disagreement_rate: float
    records_evaluated: int
    margin: float
    t_wall: float

    def to_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["candidate_weights"] = dataclasses.asdict(
            self.candidate_weights)
        return d


def rescore_records(explains: Sequence[Mapping[str, Any]],
                    multipliers: np.ndarray,
                    k_pad: int = 8) -> tuple[float, float, int]:
    """Re-rank recorded candidate sets under the candidate term
    multipliers.  Returns ``(disagreement_rate, net_delta, n)``:
    the fraction of decisions whose winner changes and the mean
    net-desirability difference (candidate winner minus incumbent
    winner — positive = candidate picks better-connected nodes on the
    recorded evidence)."""
    net_idx = TERMS.index("net")
    disagree = 0
    deltas: list[float] = []
    n = 0
    for rec in explains:
        cand = rec.get("candidates") or []
        if not cand:
            continue
        comps, feas, _cls = _record_arrays(cand, max(k_pad, len(cand)))
        if not (feas > 0).any():
            continue
        totals = np.asarray(
            [float(c.get("total", 0.0)) for c in cand]
            + [0.0] * (max(k_pad, len(cand)) - len(cand)))
        mask = feas > 0
        inc_winner = int(np.argmax(np.where(mask, totals, -np.inf)))
        cand_scores = comps.astype(np.float64) @ multipliers
        cand_winner = int(np.argmax(
            np.where(mask, cand_scores, -np.inf)))
        n += 1
        if cand_winner != inc_winner:
            disagree += 1
        deltas.append(float(comps[cand_winner, net_idx]
                            - comps[inc_winner, net_idx]))
    if n == 0:
        return 0.0, 0.0, 0
    return disagree / n, float(np.mean(deltas)), n


def _replay_ratio(trace_path: str, weights: ScoreWeights,
                  cfg: SchedulerConfig,
                  replay_kwargs: Mapping[str, Any] | None
                  ) -> tuple[float, dict[str, Any]]:
    """One counterfactual campaign: replay the trace under
    ``weights`` and return the scorecard's realized-bandwidth ratio
    (-1.0 when the replay produced no oracle sample) plus the card."""
    from kubernetesnetawarescheduler_tpu.scenario.replay import (
        replay_trace,
    )
    from kubernetesnetawarescheduler_tpu.scenario.scorecard import (
        build_scorecard,
    )

    kw = dict(replay_kwargs or {})
    kw.setdefault("quality", True)
    res = replay_trace(trace_path, score_weights=weights, **kw)
    card = build_scorecard(res)
    ratio = card.get("bandwidth", {}).get(
        "realized_bw_ratio_vs_oracle")
    if ratio is None or not np.isfinite(ratio):
        return -1.0, card
    return float(ratio), card


def evaluate_candidate(cfg: SchedulerConfig,
                       candidate: ScoreWeights,
                       incumbent: ScoreWeights,
                       explains: Sequence[Mapping[str, Any]],
                       *,
                       trace_path: str | None = None,
                       margin: float | None = None,
                       k_pad: int = 8,
                       replay_kwargs: Mapping[str, Any] | None = None,
                       ) -> PromotionDecision:
    """Run the full gate for one candidate.  Pure function of its
    inputs — the caller (loop eval tick / bench / tests) owns the
    counters and the actual weight swap."""
    if margin is None:
        margin = cfg.policy_promote_margin
    mult = term_multipliers(candidate, incumbent)
    disagreement, records_delta, n_records = rescore_records(
        explains, mult, k_pad=k_pad)
    inc_ratio = cand_ratio = -1.0
    if trace_path is None:
        return PromotionDecision(
            promote=False, reason="no_replay_trace",
            candidate_weights=candidate,
            incumbent_ratio=inc_ratio, candidate_ratio=cand_ratio,
            replay_delta=0.0, records_delta=records_delta,
            disagreement_rate=disagreement,
            records_evaluated=n_records, margin=float(margin),
            t_wall=time.time())
    # Records leg first: a candidate that loses on its OWN training
    # distribution never earns the (much more expensive) replay.
    if n_records > 0 and records_delta < 0.0:
        return PromotionDecision(
            promote=False, reason="records_regression",
            candidate_weights=candidate,
            incumbent_ratio=inc_ratio, candidate_ratio=cand_ratio,
            replay_delta=0.0, records_delta=records_delta,
            disagreement_rate=disagreement,
            records_evaluated=n_records, margin=float(margin),
            t_wall=time.time())
    inc_ratio, _ = _replay_ratio(trace_path, incumbent, cfg,
                                 replay_kwargs)
    cand_ratio, _ = _replay_ratio(trace_path, candidate, cfg,
                                  replay_kwargs)
    delta = cand_ratio - inc_ratio
    if inc_ratio < 0.0 or cand_ratio < 0.0:
        promote, reason = False, "replay_no_oracle_sample"
    elif delta >= margin:
        promote, reason = True, "replay_win"
    else:
        promote, reason = False, "replay_below_margin"
    return PromotionDecision(
        promote=promote, reason=reason,
        candidate_weights=candidate,
        incumbent_ratio=inc_ratio, candidate_ratio=cand_ratio,
        replay_delta=float(delta), records_delta=records_delta,
        disagreement_rate=disagreement,
        records_evaluated=n_records, margin=float(margin),
        t_wall=time.time())


__all__ = [
    "PromotionDecision",
    "evaluate_candidate",
    "rescore_records",
    "term_multipliers",
]
