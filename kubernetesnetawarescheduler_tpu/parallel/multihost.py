"""Multi-host mesh construction: the DCN-scale path.

The reference's only transports were HTTP scrapes and ``kubectl cp``
file drops (scheduler.go:396-407, run.sh:12-14); its scale ceiling was
one process.  Here multi-host is the same SPMD program as single-host
— the mesh just spans every process's devices, and XLA routes
collectives over ICI within a slice and DCN across slices.

Axis placement follows the scaling-book recipe:

- ``tp`` (the node-axis shard of the N×N matrices) stays WITHIN a
  host/slice: the score matmul all-gathers C-row shards over the tp
  axis every batch, which must ride ICI.
- ``dp`` (the pod-axis shard) goes ACROSS hosts: its only collective
  is the winner-per-node reduction (O(P·N) bools, once per conflict
  round), cheap enough for DCN.

``jax.devices()`` in a multi-process program enumerates devices
process-major, so a ``(dp=num_hosts, tp=devices_per_host)`` reshape
lands tp within each host by construction — :func:`global_mesh`
validates exactly that instead of trusting the caller.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh

from kubernetesnetawarescheduler_tpu.parallel.sharding import make_mesh


def init_multihost(coordinator_address: str | None = None,
                   num_processes: int | None = None,
                   process_id: int | None = None) -> None:
    """Join (or bootstrap) the multi-process JAX runtime.

    On TPU pods with standard env (GKE/JobSet), all arguments
    auto-detect and this is ``jax.distributed.initialize()``; pass
    them explicitly for bare-metal DCN clusters.  Idempotent: a second
    call (e.g. serve.py restart paths re-running init) is a no-op
    instead of an error.

    Bootstrap failures PROPAGATE — including auto-detect finding no
    cluster environment.  Silently degrading to single-process here
    would let a transient metadata failure on an N-host pod turn into
    N independent schedulers (each seeing ``process_count() == 1``,
    sailing past every multi-writer guard).  A single-host deployment
    that only wants the local-device mesh should not call this at all
    (serve.py: ``--mesh`` without ``--multihost``).
    """
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None and is_init():
        return  # idempotent no-op, no fragile message matching
    if (getattr(jax.config, "jax_platforms", None) or "").startswith("cpu"):
        # CPU-backend multi-process needs the gloo collectives
        # implementation: the default CPU client raises "Multiprocess
        # computations aren't implemented on the CPU backend" at the
        # first psum.  Must be set BEFORE initialize() (the
        # distributed client binds its collectives at startup).  Gated
        # on the flag existing so newer jax versions that drop it
        # don't break TPU pods (where jax_platforms is unset anyway).
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except (AttributeError, ValueError):
            pass
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
    except RuntimeError as exc:
        # Fallback for jax versions without is_initialized(): the
        # double-init message is version-dependent ("should only be
        # called once." / "already initialized").  Genuine failures
        # (coordinator unreachable, bad ranks) re-raise — and with
        # is_initialized() available above, this branch only ever
        # sees genuine failures.
        msg = str(exc).lower()
        if is_init is None and ("once" in msg or "already" in msg):
            return
        raise


def global_mesh(dp: int | None = None, tp: int | None = None) -> Mesh:
    """A ``(dp, tp)`` mesh over ALL processes' devices, tp-within-host.

    Defaults: ``dp = jax.process_count()``, ``tp = local device
    count`` — one pod-shard per host, the N×N matrices sharded over
    each host's ICI domain.  Any explicit ``(dp, tp)`` is accepted if
    it (a) covers every device and (b) keeps each tp group within one
    process, so the per-batch C-row all-gather never crosses DCN;
    violating (b) raises rather than silently compiling a mesh whose
    hot-loop collective rides the slow network.
    """
    devices = jax.devices()
    per_host = len(jax.local_devices())
    if dp is None and tp is None:
        dp, tp = jax.process_count(), per_host
    if dp is None:
        dp = len(devices) // tp
    if tp is None:
        tp = len(devices) // dp
    if dp * tp != len(devices):
        raise ValueError(
            f"mesh {dp}x{tp} must cover all {len(devices)} devices")
    mesh = make_mesh(dp, tp, devices=devices)
    # tp groups are the rows of the (dp, tp) device grid; every row
    # must live in one process.
    grid = mesh.devices
    for row in grid:
        procs = {d.process_index for d in row}
        if len(procs) > 1:
            raise ValueError(
                f"tp={tp} spans processes {sorted(procs)}: the score "
                "matmul's per-batch all-gather would ride DCN. Pick "
                f"tp <= devices-per-host ({per_host}) with hosts "
                "grouped under dp.")
    return mesh


__all__ = ["init_multihost", "global_mesh"]
