"""Mesh construction and sharding specs for the scheduling step.

Layout choices (see the scaling-book recipe: pick a mesh, annotate
shardings, let XLA insert collectives):

- ``metrics[N, M]``, ``cap/used[N, R]``, node bit vectors: row-sharded
  over ``tp``.
- ``lat/bw[N, N]``: row-sharded over ``tp`` (each device owns the
  links of its node shard).
- pod tensors (``req``, ``peers``, ...): row-sharded over ``dp``.
- The traffic matrix ``T[P, N]`` is built sharded ``(dp, tp)``; the
  network matmul ``T @ C.T`` contracts the full node axis, for which
  GSPMD inserts an all-gather of the C row shards over ICI.
- The assignment argmax runs over the full (replicated-per-dp-group)
  ``P x N`` score matrix; the winner-per-node reduction crosses ``dp``.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetesnetawarescheduler_tpu.config import SchedulerConfig
from kubernetesnetawarescheduler_tpu.core.assign import (
    assign_greedy,
    assign_parallel,
)
from kubernetesnetawarescheduler_tpu.core.state import (
    ClusterState,
    PodBatch,
    commit_assignments,
)


def make_mesh(dp: int, tp: int, devices=None) -> Mesh:
    """A ``(dp, tp)`` mesh over the given (or all) devices."""
    if devices is None:
        devices = jax.devices()
    if dp * tp > len(devices):
        raise ValueError(
            f"mesh {dp}x{tp} needs {dp * tp} devices, have {len(devices)}")
    grid = np.asarray(devices[:dp * tp]).reshape(dp, tp)
    return Mesh(grid, axis_names=("dp", "tp"))


def state_sharding(mesh: Mesh) -> ClusterState:
    """A ClusterState-shaped pytree of NamedShardings (node axis on tp)."""
    def s(*spec):
        return NamedSharding(mesh, P(*spec))
    return ClusterState(
        metrics=s("tp", None),
        metrics_age=s("tp"),
        lat=s("tp", None),
        bw=s("tp", None),
        cap=s("tp", None),
        used=s("tp", None),
        node_valid=s("tp"),
        label_bits=s("tp", None),
        taint_bits=s("tp", None),
        group_bits=s("tp", None),
        resident_anti=s("tp", None),
    )


def pods_sharding(mesh: Mesh) -> PodBatch:
    """A PodBatch-shaped pytree of NamedShardings (pod axis on dp)."""
    def s(*spec):
        return NamedSharding(mesh, P(*spec))
    return PodBatch(
        req=s("dp", None),
        peers=s("dp", None),
        peer_traffic=s("dp", None),
        tol_bits=s("dp", None),
        sel_bits=s("dp", None),
        affinity_bits=s("dp", None),
        anti_bits=s("dp", None),
        group_bit=s("dp", None),
        priority=s("dp"),
        pod_valid=s("dp"),
        soft_sel_bits=s("dp", None, None),
        soft_sel_w=s("dp", None),
        soft_grp_bits=s("dp", None, None),
        soft_grp_w=s("dp", None),
    )


def place(mesh: Mesh, state: ClusterState, pods: PodBatch):
    """Device-put a (state, pods) pair onto the mesh with the canonical
    shardings."""
    state = jax.device_put(state, state_sharding(mesh))
    pods = jax.device_put(pods, pods_sharding(mesh))
    return state, pods


def sharded_schedule_step(cfg: SchedulerConfig, mesh: Mesh,
                          method: str = "parallel"):
    """A jitted full scheduling step (score + assign + commit) with
    dp/tp sharding constraints; GSPMD inserts the ICI collectives.

    Returns ``step(state, pods) -> (assignment, new_state)``.
    """
    assign = {"greedy": assign_greedy, "parallel": assign_parallel}[method]
    cfg = _force_dense(cfg)

    def _step(state: ClusterState, pods: PodBatch):
        assignment = assign(state, pods, cfg)
        return assignment, commit_assignments(state, pods, assignment)

    return jax.jit(
        _step,
        in_shardings=(state_sharding(mesh), pods_sharding(mesh)),
        out_shardings=(NamedSharding(mesh, P()), state_sharding(mesh)),
    )


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def _force_dense(cfg: SchedulerConfig) -> SchedulerConfig:
    """Mesh-sharded paths always use the dense XLA score backend: a
    ``pallas_call`` inside GSPMD-partitioned code needs an explicit
    ``shard_map`` wrapping (plain pjit would all-gather its operands,
    defeating the tp sharding of the N×N matrices).  Dense-under-GSPMD
    is the measured multi-chip recipe; a shard_mapped tiled kernel is
    the future upgrade path."""
    if cfg.score_backend == "pallas":
        import dataclasses
        import warnings

        warnings.warn(
            "score_backend='pallas' is not yet supported on mesh-sharded "
            "paths; running the dense XLA kernel instead",
            RuntimeWarning, stacklevel=2)
        return dataclasses.replace(cfg, score_backend="xla")
    return cfg


def sharded_replay_stream(state, stream, cfg: SchedulerConfig, mesh: Mesh,
                          method: str = "parallel"):
    """Whole-workload device-resident replay over the mesh: the
    multi-chip form of :func:`~..core.replay.replay_stream`.

    One dispatch; the ``lax.scan`` carries the tp-sharded cluster
    state while each step's pod batch is dp-sharded, so every chip
    holds only its node shard of the ``N x N`` matrices (the HBM scale
    path) and GSPMD rides ICI for the all-gathers the score matmul and
    winner-per-node reduction need.  Returns ``(assignment i32[S],
    final_state)`` exactly like the single-chip replay (the equality
    is tested on the 8-virtual-device CPU mesh).
    """
    from kubernetesnetawarescheduler_tpu.core.replay import (
        fold_stream,
        replay_folded,
    )

    # Pre-fold host-side to [NB, batch, ...] and shard the batch axis
    # on dp (the scan walks the leading NB axis; replay_folded keeps
    # the folded layout so the dp sharding survives the whole scan).
    folded = fold_stream(stream, cfg)
    folded = jax.device_put(
        folded, jax.tree_util.tree_map(_fold_spec(mesh), folded))
    state = jax.device_put(state, state_sharding(mesh))
    return sharded_replay_fn(cfg, mesh, method, folded)(state, folded)


def _fold_spec(mesh: Mesh):
    """Sharding for a folded ``[NB, batch, ...]`` stream leaf: batch
    axis on dp.  ONE definition shared by the device_put in
    :func:`sharded_replay_stream` and the jit in_shardings in
    :func:`sharded_replay_fn` — if these disagreed, jax would reshard
    silently at the jit boundary and the compile-only GSPMD test
    would no longer describe what execution does."""
    def spec(x):
        extra = (None,) * (x.ndim - 2)
        return NamedSharding(mesh, P(None, "dp", *extra))
    return spec


def sharded_replay_fn(cfg: SchedulerConfig, mesh: Mesh, method: str,
                      folded):
    """The jitted mesh-sharded replay callable (state, folded) ->
    (assignment, final_state).  Exposed separately from
    :func:`sharded_replay_stream` so tests can ``.lower().compile()``
    it and inspect the GSPMD partitioning (e.g. assert the tp-sharded
    ``N×N`` matrices are never all-gathered whole) without executing
    at scale."""
    from kubernetesnetawarescheduler_tpu.core.replay import replay_folded

    return jax.jit(
        partial(replay_folded, cfg=_force_dense(cfg), method=method),
        in_shardings=(state_sharding(mesh),
                      jax.tree_util.tree_map(_fold_spec(mesh), folded)),
        out_shardings=(replicated(mesh), state_sharding(mesh)),
    )


__all__ = ["make_mesh", "state_sharding", "pods_sharding", "place",
           "sharded_schedule_step", "sharded_replay_stream",
           "sharded_replay_fn", "replicated"]
