"""Mesh construction and sharding specs for the scheduling step.

Layout choices (see the scaling-book recipe: pick a mesh, annotate
shardings, let XLA insert collectives):

- ``metrics[N, M]``, ``cap/used[N, R]``, node bit vectors: row-sharded
  over ``tp``.
- ``lat/bw[N, N]``: row-sharded over ``tp`` (each device owns the
  links of its node shard).
- pod tensors (``req``, ``peers``, ...): row-sharded over ``dp``.
- The traffic matrix ``T[P, N]`` is built sharded ``(dp, tp)``; the
  network matmul ``T @ C.T`` contracts the full node axis, for which
  GSPMD inserts an all-gather of the C row shards over ICI.
- The assignment argmax runs over the full (replicated-per-dp-group)
  ``P x N`` score matrix; the winner-per-node reduction crosses ``dp``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetesnetawarescheduler_tpu.config import SchedulerConfig
from kubernetesnetawarescheduler_tpu.core.assign import (
    assign_greedy,
    assign_parallel,
)
from kubernetesnetawarescheduler_tpu.core.state import (
    ClusterState,
    PodBatch,
    commit_assignments,
)


def make_mesh(dp: int, tp: int, devices=None) -> Mesh:
    """A ``(dp, tp)`` mesh over the given (or all) devices."""
    if devices is None:
        devices = jax.devices()
    if dp * tp > len(devices):
        raise ValueError(
            f"mesh {dp}x{tp} needs {dp * tp} devices, have {len(devices)}")
    grid = np.asarray(devices[:dp * tp]).reshape(dp, tp)
    return Mesh(grid, axis_names=("dp", "tp"))


def state_sharding(mesh: Mesh) -> ClusterState:
    """A ClusterState-shaped pytree of NamedShardings (node axis on tp)."""
    def s(*spec):
        return NamedSharding(mesh, P(*spec))
    return ClusterState(
        metrics=s("tp", None),
        metrics_age=s("tp"),
        lat=s("tp", None),
        bw=s("tp", None),
        cap=s("tp", None),
        used=s("tp", None),
        node_valid=s("tp"),
        label_bits=s("tp", None),
        taint_bits=s("tp", None),
        group_bits=s("tp", None),
        resident_anti=s("tp", None),
        node_zone=s("tp"),
        # Small [G, Z] count matrix: replicated (every device's assign
        # round reads arbitrary rows of it).
        gz_counts=s(None, None),
        az_anti=s(None, None),  # [Z, W], same reasoning
        node_numeric=s("tp", None),
    )


def pods_sharding(mesh: Mesh) -> PodBatch:
    """A PodBatch-shaped pytree of NamedShardings (pod axis on dp)."""
    def s(*spec):
        return NamedSharding(mesh, P(*spec))
    return PodBatch(
        req=s("dp", None),
        peers=s("dp", None),
        peer_traffic=s("dp", None),
        tol_bits=s("dp", None),
        sel_bits=s("dp", None),
        affinity_bits=s("dp", None),
        anti_bits=s("dp", None),
        group_bit=s("dp", None),
        priority=s("dp"),
        pod_valid=s("dp"),
        soft_sel_bits=s("dp", None, None),
        soft_sel_w=s("dp", None),
        soft_grp_bits=s("dp", None, None),
        soft_grp_w=s("dp", None),
        soft_zone_bits=s("dp", None, None),
        soft_zone_w=s("dp", None),
        group_idx=s("dp"),
        spread_maxskew=s("dp"),
        spread_hard=s("dp"),
        ns_anyof=s("dp", None, None, None),
        ns_forbid=s("dp", None, None),
        ns_term_used=s("dp", None),
        ns_num_col=s("dp", None, None),
        ns_num_lo=s("dp", None, None),
        ns_num_hi=s("dp", None, None),
        zaff_bits=s("dp", None),
        zanti_bits=s("dp", None),
    )


def place(mesh: Mesh, state: ClusterState, pods: PodBatch):
    """Device-put a (state, pods) pair onto the mesh with the canonical
    shardings."""
    state = jax.device_put(state, state_sharding(mesh))
    pods = jax.device_put(pods, pods_sharding(mesh))
    return state, pods


def sharded_schedule_step(cfg: SchedulerConfig, mesh: Mesh,
                          method: str = "parallel"):
    """A jitted full scheduling step (score + assign + commit) with
    dp/tp sharding constraints; GSPMD inserts the ICI collectives.

    Returns ``step(state, pods) -> (assignment, new_state)``.
    """
    assign = {"greedy": assign_greedy, "parallel": assign_parallel}[method]
    if cfg.score_backend == "pallas":
        # The single-batch step path has no shard_map wrapping (only
        # the replay does, via pallas_static_builder) — its own
        # message, so users with tiling shapes don't chase a shape
        # problem that isn't one.
        import dataclasses
        import warnings

        warnings.warn(
            "score_backend='pallas' is not supported on the "
            "sharded_schedule_step path (use the sharded replay); "
            "running the dense XLA kernel instead",
            RuntimeWarning, stacklevel=2)
        cfg = dataclasses.replace(cfg, score_backend="xla")

    def _step(state: ClusterState, pods: PodBatch):
        assignment = assign(state, pods, cfg)
        return assignment, commit_assignments(state, pods, assignment)

    return jax.jit(
        _step,
        in_shardings=(state_sharding(mesh), pods_sharding(mesh)),
        out_shardings=(NamedSharding(mesh, P()), state_sharding(mesh)),
    )


def sharded_assign_fn(cfg: SchedulerConfig, mesh: Mesh,
                      method: str = "parallel", state_placer=None):
    """A drop-in for the serving loop's assign callable
    (``(state, pods, cfg) -> assignment``), jitted with the canonical
    mesh shardings — the piece that makes ``--multihost`` serving
    real: every process runs the SAME program, GSPMD splits the node
    axis (and the N×N matrices' HBM) over ``tp`` and the pod axis
    over ``dp``, and the replicated assignment comes back to each
    host's binder.  The cfg argument is accepted for signature parity
    with ``assign_parallel``/``assign_greedy`` but must equal the one
    compiled in."""
    assign = {"greedy": assign_greedy, "parallel": assign_parallel}[method]
    jitted = jax.jit(
        partial(assign, cfg=_force_dense(cfg)),
        in_shardings=(state_sharding(mesh), pods_sharding(mesh)),
        out_shardings=NamedSharding(mesh, P()),
    )
    # Stats variant (parallel only): also returns the replicated
    # conflict-round scalar, so mesh serving feeds the same
    # netaware_conflict_rounds observable as the plain path.
    jitted_stats = None
    if method == "parallel":
        jitted_stats = jax.jit(
            partial(assign, cfg=_force_dense(cfg), with_stats=True),
            in_shardings=(state_sharding(mesh), pods_sharding(mesh)),
            out_shardings=(NamedSharding(mesh, P()),
                           NamedSharding(mesh, P())),
        )
    place_state = state_placer or _leaf_placer(state_sharding(mesh))

    def fn(state, pods, cfg_arg=None, *, with_stats: bool = False):
        placed = place_state(state)
        if with_stats and jitted_stats is not None:
            return jitted_stats(placed, pods)
        return jitted(placed, pods)

    return fn


def serving_fns(cfg: SchedulerConfig, mesh: Mesh,
                method: str = "parallel"):
    """The mesh-sharded serving triple ``(assign_fn, score_fn,
    burst_fn)`` SHARING one state placer: the loop's cycle, the
    extender webhook and the backlog-burst path read the same
    snapshot, and separate placers would transfer (and keep resident)
    the N×N matrices once per path.  All paths use the same
    ``state_sharding(mesh)`` layout — node axis over ``tp``,
    replicated over ``dp`` — so one placement serves them all."""
    place_state = _leaf_placer(state_sharding(mesh))
    return (sharded_assign_fn(cfg, mesh, method,
                              state_placer=place_state),
            sharded_score_fn(cfg, mesh, state_placer=place_state),
            serving_burst_fn(cfg, mesh, method,
                             state_placer=place_state))


def serving_burst_fn(cfg: SchedulerConfig, mesh: Mesh,
                     method: str = "parallel", state_placer=None):
    """Backlog-burst callable for the mesh serving loop:
    ``run(state, stream) -> ((assignment, final_state[, rounds]),
    with_stats)``.

    Folds the stream, dp-shards the batch axis, and scans the same
    sharded per-batch step as :func:`sharded_replay_stream` — one
    dispatch + one replicated assignment fetch per burst.  Unlike
    ``sharded_replay_fn`` (built fresh per bench workload), the jit
    here is constructed ONCE on first use: the serving loop pads
    every burst to a single folded shape, so one compiled program
    serves the daemon's lifetime.  The shared ``state_placer`` keeps
    the single resident copy of the N×N matrices (leaf-identity
    cached, same as the per-batch and webhook paths)."""
    from kubernetesnetawarescheduler_tpu.core.replay import (
        fold_stream,
        replay_folded,
    )

    place_state = state_placer or _leaf_placer(state_sharding(mesh))
    run_cfg, static_builder = _resolve_backend(cfg, mesh)
    with_stats = method == "parallel"
    fold_sh = _fold_spec(mesh)
    jitted: list = [None]

    def run(state, stream):
        folded = fold_stream(stream, run_cfg)
        folded = jax.device_put(
            folded, jax.tree_util.tree_map(fold_sh, folded))
        placed = place_state(state)
        if jitted[0] is None:
            out_sh = (replicated(mesh), state_sharding(mesh))
            if with_stats:
                out_sh = out_sh + (replicated(mesh),)
            jitted[0] = jax.jit(
                partial(replay_folded, cfg=run_cfg, method=method,
                        static_builder=static_builder,
                        with_stats=with_stats),
                in_shardings=(state_sharding(mesh),
                              jax.tree_util.tree_map(fold_sh, folded)),
                out_shardings=out_sh)
        return jitted[0](placed, folded), with_stats

    return run


def _leaf_placer(shardings):
    """A tree-placement closure with a per-leaf transfer cache: the
    encoder's snapshot (and the extender's static cache) reuse array
    OBJECTS while their dirty-group is clean, so re-placing only
    leaves whose identity changed keeps the N×N matrices' ~100 MB
    from crossing to the mesh every call — the serving-path analog of
    replay's one-shot ``place()``.  Keyed by leaf position with a
    strong ref to the source object, so id reuse after GC can't
    alias."""
    flat_shards = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
    cache: dict[int, tuple] = {}

    def _put(leaf, shard):
        if jax.process_count() > 1:
            # Multi-process: device_put runs a cross-process equality
            # assert that compares with ``==`` — the NaN-sentinel
            # ``node_numeric`` plane (NaN = label absent, fail-closed)
            # is equal-by-bits on every process yet NaN != NaN, so the
            # check aborts serving.  make_array_from_callback builds
            # the global array straight from the (identical, broadcast
            # -synchronized) host copy without the check — and without
            # the check's allgather.
            import numpy as _np

            arr = _np.asarray(leaf)
            return jax.make_array_from_callback(
                arr.shape, shard, lambda idx: arr[idx])
        return jax.device_put(leaf, shard)

    def place(tree):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        out = []
        for i, (leaf, shard) in enumerate(zip(leaves, flat_shards)):
            hit = cache.get(i)
            if hit is not None and hit[0] is leaf:
                out.append(hit[1])
            else:
                y = _put(leaf, shard)
                cache[i] = (leaf, y)
                out.append(y)
        return jax.tree_util.tree_unflatten(treedef, out)

    return place


def sharded_score_fn(cfg: SchedulerConfig, mesh: Mesh,
                     state_placer=None):
    """Mesh-sharded full-score callable for the extender webhook path:
    ``fn(state, pods, static) -> scores f32[P, N]``.

    Webhook batches are small (demand-sized, padded to 8) while the
    node axis is the big one, so pods REPLICATE and the node axis --
    state columns AND the batch-invariant static pair (``base[N]``,
    ``ct[N, N]``) -- shards over ``tp`` with the SAME layout the
    serving loop's assign path uses (``state_sharding(mesh)``), so a
    shared ``state_placer`` (see :func:`serving_fns`) keeps ONE copy
    of the N x N matrices on the mesh for both paths.  Static
    transfers are leaf-identity cached too (the batcher reuses its
    static tuple until ``static_version`` bumps).  Dense backend only
    (``_force_dense``): the tiled Pallas kernel's mesh form lives on
    the replay path via ``pallas_static_builder``.
    """
    cfg = _force_dense(cfg)
    from kubernetesnetawarescheduler_tpu.core import score as score_lib

    rep = NamedSharding(mesh, P())
    st_shard = state_sharding(mesh)
    pods_rep = jax.tree_util.tree_map(
        lambda _: rep, pods_sharding(mesh),
        is_leaf=lambda x: isinstance(x, NamedSharding))
    static_shard = (NamedSharding(mesh, P("tp")),       # base[N]
                    NamedSharding(mesh, P(None, "tp")))  # ct columns

    def _score(state, pods, static):
        return score_lib.score_pods(state, pods, cfg, static)

    jitted = jax.jit(
        _score,
        in_shardings=(st_shard, pods_rep, static_shard),
        out_shardings=rep,
    )
    place_state = state_placer or _leaf_placer(st_shard)
    place_static = _leaf_placer(static_shard)

    def fn(state, pods, static):
        return jitted(place_state(state), pods, place_static(static))

    return fn


def sharded_winner_fn(cfg: SchedulerConfig, mesh: Mesh,
                      state_placer=None):
    """Mesh-sharded FUSED winner: ``fn(state, pods, static) ->
    (best f32[P], node i32[P])`` without a replicated ``P x N`` score
    matrix ever leaving the shards.

    The score runs under the same GSPMD layout as
    :func:`sharded_score_fn` (node axis on ``tp``, pods replicated);
    the winner reduction is then a ``shard_map`` over the tp-sharded
    score columns — each shard reduces its own node slice to a local
    ``(best, node)`` pair with GLOBAL node indices
    (``axis_index("tp") * n_shard`` offset), and the cross-shard
    combine is ``pmax`` on the values plus ``pmin`` over the local
    winners that match the global max.  Node indices are global and
    ``pmin`` picks the smallest, so the repo's lowest-index-of-max
    tie-break (core/score.winner_from_scores) survives sharding
    exactly: results are bit-identical to the single-device fused
    winner (pinned on the 8-virtual-device CPU mesh in
    tests/test_winner_fusion.py).  Infeasible rows come back as -1,
    same sentinel contract as the unsharded path.
    """
    cfg = _force_dense(cfg)
    from kubernetesnetawarescheduler_tpu.core import score as score_lib
    from kubernetesnetawarescheduler_tpu.core.pallas_score import (
        _WINNER_SENTINEL,
    )
    from kubernetesnetawarescheduler_tpu.core.score import NEG_INF

    try:
        from jax import shard_map  # jax >= 0.8
        sm_kwargs = {"check_vma": False}
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
        sm_kwargs = {"check_rep": False}

    rep = NamedSharding(mesh, P())
    st_shard = state_sharding(mesh)
    pods_rep = jax.tree_util.tree_map(
        lambda _: rep, pods_sharding(mesh),
        is_leaf=lambda x: isinstance(x, NamedSharding))
    static_shard = (NamedSharding(mesh, P("tp")),
                    NamedSharding(mesh, P(None, "tp")))

    def _combine(s_local):
        n_shard = s_local.shape[1]
        offset = jax.lax.axis_index("tp") * n_shard
        best_l = jnp.max(s_local, axis=1)
        cols = offset + jax.lax.broadcasted_iota(
            jnp.int32, s_local.shape, 1)
        node_l = jnp.min(
            jnp.where(s_local == best_l[:, None], cols,
                      jnp.int32(_WINNER_SENTINEL)), axis=1)
        best = jax.lax.pmax(best_l, "tp")
        node = jax.lax.pmin(
            jnp.where(best_l == best, node_l,
                      jnp.int32(_WINNER_SENTINEL)), "tp")
        feasible = best > NEG_INF * 0.5
        return best, jnp.where(feasible, node,
                               jnp.int32(-1)).astype(jnp.int32)

    combine = shard_map(
        _combine, mesh=mesh, in_specs=P(None, "tp"),
        out_specs=(P(), P()), **sm_kwargs)

    def _winner(state, pods, static):
        scores = score_lib.score_pods(state, pods, cfg, static)
        scores = jax.lax.with_sharding_constraint(
            scores, NamedSharding(mesh, P(None, "tp")))
        return combine(scores)

    jitted = jax.jit(
        _winner,
        in_shardings=(st_shard, pods_rep, static_shard),
        out_shardings=(rep, rep),
    )
    place_state = state_placer or _leaf_placer(st_shard)
    place_static = _leaf_placer(static_shard)

    def fn(state, pods, static):
        return jitted(place_state(state), pods, place_static(static))

    return fn


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def _force_dense(cfg: SchedulerConfig) -> SchedulerConfig:
    """Coerce to the dense XLA score backend: a ``pallas_call`` inside
    GSPMD-partitioned code without a ``shard_map`` wrapping would make
    pjit all-gather its operands, defeating the tp sharding of the N×N
    matrices.  The replay path has the shard_map wrapping
    (:func:`pallas_static_builder`) and only falls back here when the
    shapes don't tile across the mesh."""
    if cfg.score_backend == "pallas":
        import dataclasses
        import warnings

        warnings.warn(
            "score_backend='pallas' requires max_nodes % (tp*128) == 0 "
            "and max_pods % (dp*8) == 0 on mesh-sharded paths; running "
            "the dense XLA kernel instead",
            RuntimeWarning, stacklevel=2)
        return dataclasses.replace(cfg, score_backend="xla")
    return cfg


def pallas_static_builder(cfg: SchedulerConfig, mesh: Mesh):
    """The multi-chip tiled-Pallas static-score path: a
    ``static_builder`` for :func:`~..core.replay.replay_folded`.

    Communication-free by construction — the row-sharded ``lat``/``bw``
    layout gives every device full contraction columns for its own
    output rows: device d computes ``raw[:, shard_d]`` from
    ``bw[shard_d, :]`` / ``lat[shard_d, :]`` with the replicated
    ``T[P, N]``, so the kernel needs NO collectives (the scoring-time
    analog of ring-attention's "my KV shard, everyone's Q" locality,
    minus the ring: the peer axis is resident, not rotated).  Only the
    small global normalizers (``bw_max``/``lat_max``/metric vote) are
    GSPMD reductions outside the shard_map.

    Returns ``None`` when the shapes don't tile across the mesh
    (callers fall back to :func:`_force_dense`): needs
    ``max_nodes % (tp * 128) == 0`` and ``max_pods % dp == 0`` with an
    8-aligned per-device pod count.
    """
    try:
        from jax import shard_map  # jax >= 0.8
        sm_kwargs = {"check_vma": False}  # renamed from check_rep
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
        sm_kwargs = {"check_rep": False}

    from kubernetesnetawarescheduler_tpu.core import pallas_score
    from kubernetesnetawarescheduler_tpu.core.score import (
        peer_traffic_matrix,
    )

    dp = mesh.shape["dp"]
    tp = mesh.shape["tp"]
    n, p = cfg.max_nodes, cfg.max_pods
    if n % (tp * 128) != 0 or p % dp != 0 or (p // dp) % 8 != 0:
        return None
    p_local = p // dp
    bp = min(128, p_local)
    if p_local % bp != 0:
        # The per-shard grid would drop pod rows beyond
        # bp * (p_local // bp) (the single-device path pads; shards
        # cannot without resharding) — e.g. p_local=136 with bp=128.
        return None
    interpret = jax.default_backend() != "tpu"

    def kernel_body(params, t, bw, lat, validk, nodes, nodei, groups,
                    podf, podi):
        n_shard = bw.shape[0]
        offset = jax.lax.axis_index("tp") * n_shard
        params = params.at[7].set(offset.astype(jnp.float32))
        return pallas_score._static_pallas_call(
            params, t, bw, lat, validk, nodes, nodei, groups, podf,
            podi, cfg=cfg, bp=bp, nb=128, kb=128, interpret=interpret)

    sharded_kernel = shard_map(
        kernel_body, mesh=mesh,
        in_specs=(P(), P("dp", None), P("tp", None), P("tp", None),
                  P(None, None), P(None, "tp"), P(None, "tp"),
                  P(None, "tp"), P("dp", None), P("dp", None)),
        out_specs=(P("dp", "tp"), P("dp", "tp")),
        **sm_kwargs)

    def builder(state):
        from kubernetesnetawarescheduler_tpu.core.state import round_up

        # The gate guarantees n % 128 == 0, so static_replay_pack's
        # n_pad == n: the mesh path reuses the single-device pack
        # verbatim (ONE definition of the kernel's array contract).
        mw = cfg.mask_words
        t_soft = cfg.max_soft_terms
        r_res = cfg.num_resources
        params0, bw_m, lat_m, validk, nodes, nodei = \
            pallas_score.static_replay_pack(state, cfg)
        pf_cols = round_up(r_res + 1 + 2 * t_soft, 8)
        pi_cols = round_up((5 + 2 * t_soft) * mw, 8)

        def static_fn(st, pods):
            t = peer_traffic_matrix(pods, n)
            groups = pallas_score.pack_group_rows(st.group_bits, n, mw)
            podf, podi = pallas_score._pack_pod_inputs(
                pods, p, p, r_res, mw, t_soft, pf_cols, pi_cols)
            raw, ok = sharded_kernel(params0, t, bw_m, lat_m, validk,
                                     nodes, nodei, groups, podf, podi)
            # nodeAffinity matchExpressions and the soft zone term
            # join outside the shard_map (plain GSPMD ops; self-gated
            # on their constraints being present), mirroring the
            # single-device static_scores_tiled.
            from kubernetesnetawarescheduler_tpu.core.score import (
                ns_affinity_ok,
                soft_zone_scores,
            )

            return (raw + soft_zone_scores(st, pods, cfg),
                    (ok > 0.5) & ns_affinity_ok(st, pods))

        return static_fn

    return builder


def sharded_replay_stream(state, stream, cfg: SchedulerConfig, mesh: Mesh,
                          method: str = "parallel"):
    """Whole-workload device-resident replay over the mesh: the
    multi-chip form of :func:`~..core.replay.replay_stream`.

    One dispatch; the ``lax.scan`` carries the tp-sharded cluster
    state while each step's pod batch is dp-sharded, so every chip
    holds only its node shard of the ``N x N`` matrices (the HBM scale
    path) and GSPMD rides ICI for the all-gathers the score matmul and
    winner-per-node reduction need.  Returns ``(assignment i32[S],
    final_state)`` exactly like the single-chip replay (the equality
    is tested on the 8-virtual-device CPU mesh).
    """
    from kubernetesnetawarescheduler_tpu.core.replay import (
        fold_stream,
        replay_folded,
    )

    # Pre-fold host-side to [NB, batch, ...] and shard the batch axis
    # on dp (the scan walks the leading NB axis; replay_folded keeps
    # the folded layout so the dp sharding survives the whole scan).
    folded = fold_stream(stream, cfg)
    folded = jax.device_put(
        folded, jax.tree_util.tree_map(_fold_spec(mesh), folded))
    state = jax.device_put(state, state_sharding(mesh))
    return sharded_replay_fn(cfg, mesh, method, folded)(state, folded)


def _resolve_backend(cfg: SchedulerConfig, mesh: Mesh):
    """``(run_cfg, static_builder)`` for a mesh replay/burst: the
    shard_map'd Pallas static builder when the shapes tile, else the
    dense config — ONE fallback rule shared by every sharded scan
    call site (per-batch, bench replay, serving burst)."""
    if cfg.score_backend == "pallas":
        static_builder = pallas_static_builder(cfg, mesh)
        if static_builder is not None:
            return cfg, static_builder
        return _force_dense(cfg), None  # shapes don't tile
    return cfg, None


def _fold_spec(mesh: Mesh):
    """Sharding for a folded ``[NB, batch, ...]`` stream leaf: batch
    axis on dp.  ONE definition shared by the device_put in
    :func:`sharded_replay_stream` and the jit in_shardings in
    :func:`sharded_replay_fn` — if these disagreed, jax would reshard
    silently at the jit boundary and the compile-only GSPMD test
    would no longer describe what execution does."""
    def spec(x):
        extra = (None,) * (x.ndim - 2)
        return NamedSharding(mesh, P(None, "dp", *extra))
    return spec


def sharded_replay_fn(cfg: SchedulerConfig, mesh: Mesh, method: str,
                      folded):
    """The jitted mesh-sharded replay callable (state, folded) ->
    (assignment, final_state).  Exposed separately from
    :func:`sharded_replay_stream` so tests can ``.lower().compile()``
    it and inspect the GSPMD partitioning (e.g. assert the tp-sharded
    ``N×N`` matrices are never all-gathered whole) without executing
    at scale."""
    from kubernetesnetawarescheduler_tpu.core.replay import replay_folded

    cfg, static_builder = _resolve_backend(cfg, mesh)
    return jax.jit(
        partial(replay_folded, cfg=cfg, method=method,
                static_builder=static_builder),
        in_shardings=(state_sharding(mesh),
                      jax.tree_util.tree_map(_fold_spec(mesh), folded)),
        out_shardings=(replicated(mesh), state_sharding(mesh)),
    )


__all__ = ["make_mesh", "state_sharding", "pods_sharding", "place",
           "sharded_schedule_step", "sharded_replay_stream",
           "sharded_replay_fn", "sharded_assign_fn",
           "sharded_winner_fn", "replicated"]
