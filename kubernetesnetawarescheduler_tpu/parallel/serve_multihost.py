"""Multi-host SERVING: a process-0 controller drives the global mesh.

Lifts the round-3 restriction (``serve.py`` refused ``--multihost``
with ``jax.process_count() > 1``; VERDICT r3 next-round #9).  The
design keeps serving SINGLE-CONTROLLER — exactly one informer, queue,
encoder and binder, all on process 0 — because independent control
planes would watch divergent API-server streams and POST duplicate
Bindings.  What is distributed is the COMPUTE: every process joins the
same GSPMD score+assign step over the global ``(dp, tp)`` mesh, so the
N×N network matrices' HBM and the scoring FLOPs split across hosts
(ICI within a slice, DCN across; the collectives are XLA's).

Protocol (all payloads move via
``jax.experimental.multihost_utils.broadcast_one_to_all``, process 0
sending):

1. header ``i32[3] = (opcode, big_sync, seq)``
2. ``OP_SYNC`` payloads, only when ``big_sync``: the topology-scale
   state (N×N lat/bw, capacities, label/taint bits, zones) — re-sent
   only when the encoder's static version moves (metrics/network
   ingest, node lifecycle), never per cycle.
3. the per-cycle payloads: the placement-mutable state columns
   (``used``/``group_bits``/… — O(N), ~0.5 MB at N=5120) and the
   encoded :class:`PodBatch`.
4. every process runs the SAME jitted sharded assign; the replicated
   assignment returns to the controller's binder.  Followers discard
   it (their ledger is process 0's).

``OP_STOP`` shuts followers down.  Followers block inside the header
broadcast while the controller is idle — no polling, no heartbeat.

The host ledger (process 0's encoder) stays the single source of
truth, mirroring the single-process serving loop: device state is
re-derived from broadcast snapshots each cycle, so bind failures,
preemptions and node lifecycle never need distributed reconciliation.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp

from kubernetesnetawarescheduler_tpu.config import SchedulerConfig
from kubernetesnetawarescheduler_tpu.core.state import (
    ClusterState,
    PodBatch,
)

OP_STEP = 0
OP_STOP = 1

# ClusterState leaves that change with topology/ingest cadence (the
# static_version counter), broadcast only on OP_SYNC...
BIG_FIELDS = ("lat", "bw", "cap", "label_bits", "taint_bits",
              "node_zone", "node_numeric", "metrics", "metrics_age",
              "node_valid")
# ...vs the placement-mutable columns, broadcast every cycle.
MUT_FIELDS = ("used", "group_bits", "resident_anti", "gz_counts",
              "az_anti")


def _bcast(tree):
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(tree)


def _np_tree(tree):
    return jax.tree_util.tree_map(np.asarray, tree)


class MultihostController:
    """Wraps the mesh-sharded assign callable with the broadcast
    protocol.  Installed as ``loop._assign`` on process 0, so the
    ordinary :class:`~...core.loop.SchedulerLoop` serving machinery
    (informers, queue, binder, preemption, events) runs unchanged —
    its score/assign dispatch just happens to be joined by every other
    process."""

    def __init__(self, cfg: SchedulerConfig, mesh, assign_fn) -> None:
        self._cfg = cfg
        self._mesh = mesh
        self._assign_fn = assign_fn
        # Last-synced BIG leaves, held by strong reference: the
        # encoder's snapshot returns the SAME array objects while its
        # dirty-group is clean, so identity comparison against the
        # cycle's OWN state detects exactly the changes that cycle
        # consumed.  (A separate version-counter read would race the
        # ingest threads: a bump landing between the cycle's snapshot
        # and the version read would be recorded as synced while its
        # data was never broadcast — followers then diverge forever.)
        self._synced_big: tuple | None = None
        self._seq = 0

    def __call__(self, state: ClusterState, pods: PodBatch, cfg=None,
                 *, with_stats: bool = False):
        big = tuple(getattr(state, f) for f in BIG_FIELDS)
        big_sync = 0 if (self._synced_big is not None
                         and all(a is b for a, b in
                                 zip(big, self._synced_big))) else 1
        self._seq += 1
        _bcast(jnp.asarray([OP_STEP, big_sync, self._seq % (2 ** 31)],
                           jnp.int32))
        if big_sync:
            _bcast(tuple(np.asarray(x) for x in big))
            self._synced_big = big
        _bcast(tuple(np.asarray(getattr(state, f))
                     for f in MUT_FIELDS))
        _bcast(_np_tree(pods))
        # Every process must run the SAME jitted program: followers
        # derive with_stats from their own method (parallel <-> stats,
        # mirroring SchedulerLoop), so forwarding the controller's
        # request keeps the collective consistent.
        return self._assign_fn(state, pods, with_stats=with_stats)

    def stop(self) -> None:
        _bcast(jnp.asarray([OP_STOP, 0, 0], jnp.int32))


def install_controller(loop, cfg: SchedulerConfig, mesh) -> \
        "MultihostController":
    """Swap process 0's serving-loop assign for the broadcasting
    controller (the loop was built with ``mesh=`` so ``loop._assign``
    is already the sharded fn)."""
    ctl = MultihostController(cfg, mesh, loop._assign)
    loop._assign = ctl
    # The extender webhook's sharded score path compiles over the
    # GLOBAL mesh, but followers only join assign-step broadcasts — a
    # webhook request would hang process 0 at its first cross-process
    # collective (holding the batcher's dispatch lock, stranding every
    # later request).  Webhook scoring therefore runs PROCESS-LOCAL
    # (score_pods_auto fallback in api/extender._ScoreBatcher); only
    # the scheduling cycle's assign is distributed.
    loop.sharded_score = None
    # Same reasoning for the backlog burst: followers join PER-BATCH
    # assign-step broadcasts only, and a controller-side burst would
    # run a global-mesh scan the followers never enter — process 0
    # would hang at its first cross-process collective.  Multi-host
    # serving therefore stays per-batch; single-process mesh loops
    # keep their burst.
    loop.burst_batches = 1
    loop._sharded_burst = None
    return ctl


def run_follower(cfg: SchedulerConfig, mesh, method: str = "parallel",
                 max_steps: int | None = None) -> int:
    """Follower loop for processes 1..P-1: receive, assemble, join the
    sharded step, repeat until OP_STOP.  Returns the step count."""
    from kubernetesnetawarescheduler_tpu.parallel.sharding import (
        sharded_assign_fn,
    )

    assign_fn = sharded_assign_fn(cfg, mesh, method)
    big: dict[str, np.ndarray] = {}
    # Broadcast SHAPE templates and the state skeleton are
    # loop-invariant — built once, not per cycle (at N=5120 the
    # ClusterState skeleton alone holds two ~100 MB N×N zero planes).
    big_zeros = _big_zeros(cfg)
    mut_zeros = _mut_zeros(cfg)
    batch_zeros = _batch_zeros(cfg)
    header_zeros = jnp.zeros((3,), jnp.int32)
    from kubernetesnetawarescheduler_tpu.core.state import (
        init_cluster_state,
    )

    template = init_cluster_state(cfg)
    steps = 0
    while max_steps is None or steps < max_steps:
        header = np.asarray(_bcast(header_zeros))
        if int(header[0]) == OP_STOP:
            break
        if int(header[1]):
            vals = _bcast(big_zeros)
            # Restore template dtypes: broadcast_one_to_all rides a
            # psum, which upcasts bool leaves to int32 (values intact).
            # Without the cast-back the follower compiles a DIFFERENT
            # program than the controller (int32 masks vs bool) and the
            # cross-process collective mismatches.
            big = {f: np.asarray(v, dtype=z.dtype)
                   for f, v, z in zip(BIG_FIELDS, vals, big_zeros)}
        mut = _bcast(mut_zeros)
        batch_np = _bcast(batch_zeros)
        state = dataclasses.replace(
            template,
            **{f: jnp.asarray(v) for f, v in big.items()},
            **{f: jnp.asarray(np.asarray(v, dtype=z.dtype))
               for f, v, z in zip(MUT_FIELDS, mut, mut_zeros)})
        pods = jax.tree_util.tree_map(
            lambda v, z: jnp.asarray(
                np.asarray(v, dtype=np.asarray(z).dtype)),
            batch_np, batch_zeros)
        # Same program as the controller: parallel runs the stats
        # variant (SchedulerLoop always asks for rounds with the
        # parallel assigner); a divergent choice here would hang the
        # cross-process collective on mismatched computations.
        out = assign_fn(state, pods, with_stats=(method == "parallel"))
        jax.block_until_ready(out)
        steps += 1
    return steps


def _big_zeros(cfg: SchedulerConfig):
    """Zero-valued pytree with the BIG_FIELDS shapes (broadcast needs
    identical structure on every process)."""
    from kubernetesnetawarescheduler_tpu.core.state import (
        init_cluster_state,
    )

    empty = init_cluster_state(cfg)
    return tuple(np.asarray(getattr(empty, f)) for f in BIG_FIELDS)


def _mut_zeros(cfg: SchedulerConfig):
    from kubernetesnetawarescheduler_tpu.core.state import (
        init_cluster_state,
    )

    empty = init_cluster_state(cfg)
    return tuple(np.asarray(getattr(empty, f)) for f in MUT_FIELDS)


def _batch_zeros(cfg: SchedulerConfig):
    from kubernetesnetawarescheduler_tpu.core.state import (
        init_pod_batch,
    )

    return _np_tree(init_pod_batch(cfg))
