"""Multi-device scaling: meshes, shardings, sharded scheduling steps.

The reference is single-threaded — one ``Schedule()`` goroutine popping
one pod at a time (scheduler.go:139-141, :191).  Here scale comes from
a 2-D ``jax.sharding.Mesh``:

- ``dp`` shards the pending-pod axis (batch data parallelism);
- ``tp`` shards the node axis — the ``N x N`` latency/bandwidth
  matrices, capacity vectors and metric columns split across devices,
  which is what lets the state grow past one chip's HBM comfort at
  5k+ nodes.

Cross-shard reductions (the assignment argmax across node shards, the
network-cost matmul contraction) are XLA collectives over ICI inserted
by GSPMD from the sharding annotations — no hand-written NCCL/MPI
analog (the reference had none either; its only transport was HTTP
scrapes, scheduler.go:396-407).
"""

from kubernetesnetawarescheduler_tpu.parallel.sharding import (  # noqa: F401
    make_mesh,
    pods_sharding,
    sharded_schedule_step,
    state_sharding,
)
from kubernetesnetawarescheduler_tpu.parallel.multihost import (  # noqa: F401
    global_mesh,
    init_multihost,
)
