"""Cluster-axis device step: the fleet's ONE shared program.

Every tenant in a padding bucket shares one ``SchedulerConfig`` shape
(``cfg.max_nodes`` = the bucket's power-of-two node count), so their
whole-state pytrees stack leaf-for-leaf along a NEW leading cluster
axis (``core.state.stack_trees``) and the fused per-batch decision
vmaps over it.  Two entry points:

- :func:`fleet_assign` — the SERVING dispatch: vmapped
  ``assign_parallel`` (score + device-resident conflict resolution),
  no commit.  Mirrors the solo serial path exactly — durable usage
  commits flow through each tenant's bind/watch path, and the batched
  snapshot stack stays encoder-derived — which is what makes the
  per-tenant bit-identity contract provable rather than aspirational.
- :func:`fleet_fused_step` — the vmapped r9 fused
  ``score -> conflict-resolve -> commit`` step with the cluster-stacked
  state DONATED, for state chains the caller owns (bench folds, replay;
  the forward path once a mesh dimension absorbs the cluster axis).

``sharded_winner_fn``'s contract (parallel/sharding.py) is untouched:
the vmap axis is OUTSIDE the per-cluster winner reduction, so a mesh
dimension can later absorb it by sharding the leading axis —
per-cluster semantics are already batch-invariant.

Idle lanes are free: an ``init_pod_batch`` lane has ``pod_valid`` all
False, ``assign_parallel`` maps invalid pods to UNASSIGNED, and
``commit_assignments`` of an UNASSIGNED batch is the identity — a
bucket dispatches at its padded tenant capacity every cycle with one
jit cache entry, whatever subset of tenants has work.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from kubernetesnetawarescheduler_tpu.config import SchedulerConfig
from kubernetesnetawarescheduler_tpu.core.assign import assign_parallel
from kubernetesnetawarescheduler_tpu.core.state import (
    ClusterState,
    PodBatch,
    commit_assignments,
)


def node_bucket(n_nodes: int, floor: int = 64) -> int:
    """The padding bucket for a tenant with ``n_nodes`` nodes: the
    next power of two >= max(n_nodes, floor).  Buckets bound retrace —
    every tenant in a bucket shares one jit cache entry."""
    if n_nodes < 1:
        raise ValueError("n_nodes must be >= 1")
    b = max(int(floor), 1)
    while b < n_nodes:
        b *= 2
    return b


@partial(jax.jit, static_argnames=("cfg",))
def fleet_assign(states: ClusterState, pods: PodBatch, statics,
                 cfg: SchedulerConfig):
    """Vmapped serving dispatch over the leading cluster axis.

    ``states``/``pods``/``statics`` are :func:`~..core.state.stack_trees`
    results (``[K, ...]`` per leaf); returns
    ``(assignment i32[K, P], rounds i32[K])``.  Per-lane results are
    bit-identical to calling ``assign_parallel`` per tenant (the
    fleet isolation property test pins this all the way to
    placements)."""

    def one(st, pd, stc):
        return assign_parallel(st, pd, cfg, stc, with_stats=True)

    return jax.vmap(one)(states, pods, statics)


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def fleet_fused_step(states: ClusterState, pods: PodBatch, statics,
                     cfg: SchedulerConfig):
    """Vmapped fused step: assign + usage commit per lane, the
    cluster-stacked ``states`` DONATED (the caller must own it — a
    bench/replay chain, never the encoder-cached snapshots).  Returns
    ``(new_states, assignment i32[K, P], rounds i32[K])``."""

    def one(st, pd, stc):
        assignment, rounds = assign_parallel(st, pd, cfg, stc,
                                             with_stats=True)
        return commit_assignments(st, pd, assignment), assignment, rounds

    return jax.vmap(one)(states, pods, statics)


@partial(jax.jit, static_argnames=("cfg",))
def fleet_assign_lanes(states, pods, statics, cfg: SchedulerConfig):
    """The serving dispatch as ONE device call per bucket cycle:
    ``states``/``pods``/``statics`` are length-K tuples of per-tenant
    pytrees (K = the bucket's padded tenant capacity), stacked along
    the cluster axis INSIDE the jit — stacking, scoring, and conflict
    resolution for every tenant fuse into a single program, so the
    per-dispatch overhead a solo loop pays K times is paid once.
    Retrace is keyed on K and the bucket config only."""
    st = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *states)
    pd = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *pods)
    stc = stack_statics(statics)

    def one(s, p, c_):
        return assign_parallel(s, p, cfg, c_, with_stats=True)

    return jax.vmap(one)(st, pd, stc)


def stack_statics(statics):
    """Stack per-tenant assign statics (the
    ``compute_assign_static_incremental`` result pytrees) along the
    cluster axis.  Scalar leaves promote to arrays so every leaf gains
    the leading axis the vmap maps over."""
    return jax.tree_util.tree_map(
        lambda *ls: jnp.stack([jnp.asarray(x) for x in ls]), *statics)
