"""Fleet-of-clusters serving (r15).

Many logical clusters (tenants), one device program: every tenant's
PLANES are stacked along a leading cluster axis into one batched
device state, and the fused ``score -> conflict-resolve -> commit``
step is vmapped over that axis — at N=2048 the single-dispatch step
uses a fraction of a v5e core, so the chip's spare capacity becomes
tenant capacity instead of idle silicon.

Layout:

- :mod:`.batch` — cluster-axis device step: tree stacking, the
  vmapped assign dispatch (serving) and the vmapped fused step with
  commit + donation (bench/forward path).
- :mod:`.server` — :class:`FleetServer`: SchedulerLoop-per-tenant
  facade over the shared dispatch, with power-of-two node-count
  padding buckets bounding retrace.
- :mod:`.transfer` — :class:`TransferRegistry`: promoted scoring
  policies warm-start new tenants by size/topology match; promotion
  stays strictly per-tenant through the r14 counterfactual gate.

Isolation contract (property-tested): every tenant's placements are
bit-identical to the same tenant served alone, including under
another tenant's injected state faults.
"""

from kubernetesnetawarescheduler_tpu.fleet.batch import (  # noqa: F401
    fleet_assign,
    fleet_fused_step,
    node_bucket,
)
from kubernetesnetawarescheduler_tpu.fleet.server import (  # noqa: F401
    FleetServer,
    Tenant,
)
from kubernetesnetawarescheduler_tpu.fleet.transfer import (  # noqa: F401
    TransferRegistry,
)
