"""FleetServer: SchedulerLoop-per-tenant facade over one shared
device program.

Each tenant keeps a FULL :class:`~..core.loop.SchedulerLoop` — its own
encoder, queue, checkpoint directory, SLOEngine, QualityObserver,
flight recorder, scoring policy — so every host-side contract (watch
ingest, gang gating, bind/assume, explain capture, span commit) is the
solo loop's own code.  What the fleet changes is ONLY the device
dispatch: per cycle, each tenant's encode half runs through
``SchedulerLoop._cycle_inputs`` (identical to solo), the per-tenant
``(state, pod-batch, static)`` triples are stacked along the cluster
axis, ONE vmapped dispatch scores and conflict-resolves every tenant
(:func:`~.batch.fleet_assign_lanes`), and each tenant's bind half runs
through ``SchedulerLoop._cycle_outputs`` (identical to solo).

Padding buckets: tenants are grouped by power-of-two node count
(:func:`~.batch.node_bucket`, floored at ``cfg.fleet_bucket_min``) and
each bucket's lane count is itself padded to a power of two with inert
filler lanes (empty pod batches — ``assign_parallel`` maps invalid
pods to UNASSIGNED, so fillers are bit-inert), bounding jit retrace to
O(log tenants) per bucket config.

Isolation: tenants share NOTHING mutable but the jit cache.  Lane
``k``'s vmap output depends only on lane ``k``'s inputs, which is why
the per-tenant placements are bit-identical to solo serving — pinned
by the property test in tests/test_fleet.py, including under another
tenant's injected state-chaos faults.

Gangs keep their solo path: a released gang schedules through its own
tenant's ``_schedule_gang`` (the joint-placement kernel is per-tenant
by construction); only the per-pod serial path is batched.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import numpy as np

from kubernetesnetawarescheduler_tpu.config import SchedulerConfig
from kubernetesnetawarescheduler_tpu.core.loop import (
    SchedulerLoop,
    jax_block,
)
from kubernetesnetawarescheduler_tpu.core.state import (
    init_cluster_state,
    init_pod_batch,
)
from kubernetesnetawarescheduler_tpu.fleet.batch import (
    fleet_assign_lanes,
    node_bucket,
)
from kubernetesnetawarescheduler_tpu.fleet.transfer import (
    TransferRegistry,
)


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


@dataclasses.dataclass
class Tenant:
    """One logical cluster served by the fleet."""

    name: str
    loop: SchedulerLoop
    bucket_nodes: int
    checkpoint_dir: str | None = None
    # Donor provenance when this tenant's policy was warm-started
    # (None = cold start); promotion is still gated per-tenant.
    transfer_donor: dict[str, Any] | None = None
    # Donor promoted_version last pushed to the registry (so maintain
    # re-registers only on a NEW promotion).
    _registered_version: int = 0


class _Bucket:
    """All tenants sharing one padded node-count config — and
    therefore one jit cache entry for the batched dispatch."""

    def __init__(self, cfg: SchedulerConfig) -> None:
        self.cfg = cfg
        self.tenants: list[Tenant] = []
        self._filler = None  # (state, batch, static), built lazily

    @property
    def capacity(self) -> int:
        """Lane count of the batched dispatch: tenants padded to the
        next power of two (min 1)."""
        return _pow2(max(1, len(self.tenants)))

    def filler(self):
        """The inert lane: empty state, empty (all-invalid) pod
        batch, and the static computed from the empty state.  Built
        once per bucket; its lane outputs are ignored."""
        if self._filler is None:
            from kubernetesnetawarescheduler_tpu.core.pallas_score import (
                compute_assign_static_incremental,
            )

            state = init_cluster_state(self.cfg)
            batch = init_pod_batch(self.cfg)
            static, _ = compute_assign_static_incremental(
                state, self.cfg, None, None, None)
            self._filler = (state, batch, static)
        return self._filler


class FleetServer:
    """Serve many logical clusters from one batched device program.

    Typical lifecycle::

        fleet = FleetServer()
        fleet.add_tenant("blue", client_a, cfg_a, checkpoint_dir=da)
        fleet.add_tenant("green", client_b, cfg_b, checkpoint_dir=db)
        while serving:
            fleet.step()        # one batched cycle across all buckets
            fleet.maintain()    # per-tenant maintain + donor registry
    """

    def __init__(self, registry: TransferRegistry | None = None
                 ) -> None:
        self._buckets: dict[SchedulerConfig, _Bucket] = {}
        self._tenants: dict[str, Tenant] = {}
        self.registry = registry if registry is not None \
            else TransferRegistry()
        self.cycles_total = 0
        self.dispatches_total = 0
        self.dispatch_lanes_total = 0

    # -- onboarding ---------------------------------------------------

    def add_tenant(self, name: str, client, cfg: SchedulerConfig,
                   *, n_nodes: int | None = None,
                   checkpoint_dir: str | None = None,
                   warm_start: bool = True,
                   **loop_kwargs) -> Tenant:
        """Onboard a logical cluster.

        ``cfg`` is the tenant's OWN config; its ``max_nodes`` is
        rounded up to the power-of-two padding bucket (floored at
        ``cfg.fleet_bucket_min``) so same-sized tenants share one jit
        cache entry — the VirtualFlow-style decoupling of the logical
        spec from its physical packing.  ``n_nodes`` (actual node
        count, default ``cfg.max_nodes``) picks the bucket.

        With ``warm_start`` and a learned-score config, the tenant's
        policy is seeded from the closest promoted donor in the
        transfer registry once its encoder has topology (retried on
        :meth:`maintain` until then); the seeded policy still serves
        shadow-only until it wins this tenant's own gate."""
        if name in self._tenants:
            raise ValueError(f"tenant {name!r} already exists")
        bucket_nodes = node_bucket(
            int(n_nodes if n_nodes is not None else cfg.max_nodes),
            cfg.fleet_bucket_min)
        bcfg = (cfg if cfg.max_nodes == bucket_nodes
                else dataclasses.replace(cfg, max_nodes=bucket_nodes))
        loop = SchedulerLoop(client, bcfg, method="parallel",
                             **loop_kwargs)
        loop.cluster_id = name
        # Surfaced so a tenant's own /debug/fleet (api/extender.py)
        # can render the fleet-level view.
        loop.fleet = self
        tenant = Tenant(name=name, loop=loop,
                        bucket_nodes=bucket_nodes,
                        checkpoint_dir=checkpoint_dir)
        bucket = self._buckets.get(bcfg)
        if bucket is None:
            bucket = self._buckets[bcfg] = _Bucket(bcfg)
        bucket.tenants.append(tenant)
        self._tenants[name] = tenant
        if warm_start and loop.policy is not None:
            self._try_warm_start(tenant)
        return tenant

    def remove_tenant(self, name: str) -> None:
        tenant = self._tenants.pop(name)
        for bucket in self._buckets.values():
            if tenant in bucket.tenants:
                bucket.tenants.remove(tenant)
        tenant.loop.stop_bind_worker()

    def tenant(self, name: str) -> Tenant:
        return self._tenants[name]

    def _try_warm_start(self, tenant: Tenant) -> None:
        """Seed the tenant's policy from the closest promoted donor —
        a no-op until the tenant's encoder has nodes to fingerprint
        (maintain retries) or when the registry has no usable donor
        (cold start)."""
        loop = tenant.loop
        if tenant.transfer_donor is not None or loop.policy is None:
            return
        features = loop.encoder.topology_features()
        if features["nodes"] <= 0:
            return
        rec = self.registry.warm_start(loop.policy, features,
                                       exclude=tenant.name)
        if rec is not None:
            tenant.transfer_donor = rec.to_dict()

    # -- serving ------------------------------------------------------

    def step(self) -> int:
        """One batched cycle across every bucket; returns pods bound
        fleet-wide."""
        self.cycles_total += 1
        bound = 0
        for bucket in self._buckets.values():
            bound += self._step_bucket(bucket)
        return bound

    def _step_bucket(self, bucket: _Bucket) -> int:
        cfg = bucket.cfg
        lanes = []   # (tenant, sb, pods, batch, state, static,
        #              version, node_table)
        gangs = []   # (tenant, ready)
        for tenant in bucket.tenants:
            loop = tenant.loop
            # Same per-cycle prologue as SchedulerLoop.run_once.
            budget = getattr(loop.client, "retry_budget", None)
            if budget is not None:
                budget.begin_cycle()
            if loop._relist_needed:
                loop.relist_audit()
            if loop._parked_binds:
                loop._drain_parked_binds()
            pods = loop.queue.pop_batch(cfg.max_pods, 0.0)
            pods, ready = loop._gang_gate(pods)
            if ready:
                gangs.append((tenant, ready))
            if not pods:
                loop._emit_degraded_events()
                continue
            sb = loop._span_begin("fleet")
            batch, state, version, node_table = \
                loop._cycle_inputs(sb, pods)
            static = loop._static_for(state, version)
            lanes.append((tenant, sb, pods, batch, state, static,
                          version, node_table))
        bound = 0
        if lanes:
            filler = bucket.filler()
            k_pad = bucket.capacity
            states = [w[4] for w in lanes]
            batches = [w[3] for w in lanes]
            statics = [w[5] for w in lanes]
            while len(states) < k_pad:
                states.append(filler[0])
                batches.append(filler[1])
                statics.append(filler[2])
            t0 = time.perf_counter()
            asg_dev, rounds_dev = fleet_assign_lanes(
                tuple(states), tuple(batches), tuple(statics), cfg)
            asg = np.asarray(jax_block(asg_dev))
            rounds = np.asarray(jax_block(rounds_dev))
            dt = time.perf_counter() - t0
            self.dispatches_total += 1
            self.dispatch_lanes_total += len(lanes)
            for k, (tenant, sb, pods, batch, state, static,
                    version, node_table) in enumerate(lanes):
                loop = tenant.loop
                # Every tenant's span carries the SHARED dispatch
                # wall: the whole bucket waits on one device call,
                # so that wall IS each tenant's score_assign cost
                # this cycle (noisy-neighbor analysis reads this
                # across tenants; see OPERATIONS.md).
                sb.add_phase("score_assign", t0, dt)
                loop.timer.record("score_assign", dt)
                cycle_rounds = int(rounds[k])
                with loop._round_lock:
                    loop.round_samples.append(cycle_rounds)
                loop._note_dispatch()
                bound += loop._cycle_outputs(
                    sb, pods, batch, state, static, node_table,
                    asg[k], cycle_rounds, version, path="fleet")
        for tenant, ready in gangs:
            for key, members in ready:
                bound += tenant.loop._schedule_gang(key, members)
        return bound

    # -- maintenance --------------------------------------------------

    def maintain(self) -> None:
        """Per-tenant maintain (policy train/eval ticks, rebalance,
        audits — the solo cadence) plus fleet bookkeeping: pending
        warm starts retried, fresh promotions registered as donors."""
        for tenant in self._tenants.values():
            tenant.loop.maintain()
            self._try_warm_start(tenant)
            self.register_donor(tenant.name)

    def register_donor(self, name: str) -> bool:
        """Push ``name``'s policy into the transfer registry if it
        holds a promotion the registry has not seen."""
        tenant = self._tenants[name]
        policy = tenant.loop.policy
        if policy is None:
            return False
        pv = int(policy.promoted_version)
        if pv <= 0 or pv == tenant._registered_version:
            return False
        rec = self.registry.register(
            name, tenant.loop.encoder.topology_features(), policy)
        if rec is None:
            return False
        tenant._registered_version = pv
        return True

    def save_tenant(self, name: str) -> None:
        """Checkpoint one tenant into ITS OWN directory (sibling dirs
        per tenant; MANIFEST protocol unchanged), stamped with the
        tenant identity via ``extra_meta``."""
        from kubernetesnetawarescheduler_tpu.core.checkpoint import (
            save_checkpoint,
        )

        tenant = self._tenants[name]
        if tenant.checkpoint_dir is None:
            raise ValueError(f"tenant {name!r} has no checkpoint_dir")
        save_checkpoint(tenant.checkpoint_dir, tenant.loop.encoder,
                        policy=tenant.loop.policy,
                        extra_meta={"fleet": {"cluster_id": name}})

    def close(self) -> None:
        for tenant in list(self._tenants.values()):
            tenant.loop.stop_bind_worker()
            tenant.loop.stop_static_refresher()

    # -- observability ------------------------------------------------

    def summary(self) -> dict[str, Any]:
        """One-shot stats block for /debug/fleet and selfmetrics."""
        buckets = {}
        for cfg, bucket in self._buckets.items():
            buckets[str(cfg.max_nodes)] = {
                "capacity": bucket.capacity,
                "tenants": [t.name for t in bucket.tenants],
            }
        tenants = {}
        for name, tenant in self._tenants.items():
            loop = tenant.loop
            tenants[name] = {
                "bucket_nodes": tenant.bucket_nodes,
                "queue_depth": len(loop.queue),
                "scheduled": int(loop.scheduled),
                "transfer_donor": tenant.transfer_donor,
                "slo": (loop.slo.snapshot()
                        if loop.slo is not None else None),
            }
        return {
            "enabled": True,
            "cycles_total": self.cycles_total,
            "dispatches_total": self.dispatches_total,
            "dispatch_lanes_total": self.dispatch_lanes_total,
            "buckets": buckets,
            "tenants": tenants,
            "transfer": self.registry.summary(),
        }
