"""Cross-cluster policy transfer (r15).

A scoring policy that won its promotion gate on one tenant is a
better starting point than zero-init for a SIMILAR tenant — the
continuous-transfer observation from the HPC scheduling literature
(PAPERS.md).  The registry holds promoted donors keyed by the
size/topology fingerprint from ``Encoder.topology_features()``; a new
tenant warm-starts from the CLOSEST donor (normalized feature
distance), then learns on its own data.

The gate stays strictly per-tenant: ``warm_start`` only seeds
``ScoringPolicy`` parameters (fresh optimizer, shadow-only serving) —
the transferred policy is promoted ONLY when it wins the recipient's
own two-leg counterfactual replay, exactly like a cold-started one.
What transfer buys is fewer examples-to-promotion, which the fleet
bench leg measures (warm vs cold on a seeded scenario pair).
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any

import numpy as np


# Per-feature normalization scales for the donor distance: node count
# and fabric stats are compared in LOG space (a 64- vs 128-node tenant
# is "one doubling apart", same for 1 vs 2 GB/s fabrics), zone count
# linearly.
_LOG_FEATURES = ("nodes", "lat_mean", "bw_mean")
_LIN_FEATURES = ("zones",)


def _feature_vector(features: dict[str, float]) -> np.ndarray:
    out = []
    for k in _LOG_FEATURES:
        out.append(math.log1p(max(float(features.get(k, 0.0)), 0.0)))
    for k in _LIN_FEATURES:
        out.append(float(features.get(k, 0.0)))
    return np.asarray(out, np.float64)


@dataclass
class DonorRecord:
    """One promoted policy, frozen at registration time (numpy copies
    — the record outlives the donor tenant)."""

    cluster_id: str
    features: dict[str, float]
    theta: np.ndarray
    class_adj: np.ndarray
    promoted_version: int
    registered_t: float = field(default_factory=time.time)

    def to_dict(self) -> dict[str, Any]:
        return {
            "cluster_id": self.cluster_id,
            "features": dict(self.features),
            "promoted_version": int(self.promoted_version),
            "registered_t": self.registered_t,
        }


class TransferRegistry:
    """Thread-safe registry of promoted donor policies.

    ``register`` is called when a tenant's policy wins its promotion
    gate (the FleetServer does this on its maintain path; benches call
    it directly).  ``closest`` / ``warm_start`` serve onboarding."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._donors: dict[str, DonorRecord] = {}
        self.transfers_total = 0

    def register(self, cluster_id: str, features: dict[str, float],
                 policy) -> DonorRecord | None:
        """Record ``policy`` as a donor iff it has actually been
        promoted (``promoted_version > 0``) — a shadow-only policy has
        never proven itself and must not seed peers.  Re-registration
        replaces the tenant's previous record (latest promotion
        wins)."""
        if getattr(policy, "promoted_version", 0) <= 0:
            return None
        params = policy.export_params()
        rec = DonorRecord(
            cluster_id=str(cluster_id),
            features=dict(features),
            theta=params["theta"],
            class_adj=params["class_adj"],
            promoted_version=int(policy.promoted_version),
        )
        with self._lock:
            self._donors[rec.cluster_id] = rec
        return rec

    def closest(self, features: dict[str, float],
                exclude: str | None = None) -> DonorRecord | None:
        """The donor with the smallest normalized feature distance to
        ``features`` (None when the registry is empty or holds only
        the excluded tenant — self-transfer is meaningless)."""
        target = _feature_vector(features)
        best: DonorRecord | None = None
        best_d = math.inf
        with self._lock:
            donors = list(self._donors.values())
        for rec in donors:
            if exclude is not None and rec.cluster_id == exclude:
                continue
            d = float(np.linalg.norm(
                _feature_vector(rec.features) - target))
            if d < best_d:
                best, best_d = rec, d
        return best

    def warm_start(self, policy, features: dict[str, float],
                   exclude: str | None = None
                   ) -> DonorRecord | None:
        """Seed ``policy`` from the closest donor; returns the donor
        record used (None -> cold start, registry had no usable
        donor).  The seeded policy serves shadow-only until it wins
        the recipient tenant's own counterfactual-replay gate."""
        rec = self.closest(features, exclude=exclude)
        if rec is None:
            return None
        policy.warm_start_from(rec.theta, rec.class_adj)
        with self._lock:
            self.transfers_total += 1
        return rec

    def summary(self) -> dict[str, Any]:
        with self._lock:
            return {
                "donors": {cid: rec.to_dict()
                           for cid, rec in self._donors.items()},
                "transfers_total": self.transfers_total,
            }
