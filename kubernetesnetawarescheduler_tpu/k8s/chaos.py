"""Control-plane chaos: seeded fault schedules over the fake apiserver.

The reference scheduler died the moment its control plane misbehaved
(nil-body read on a failed scrape, scheduler.go:397-405).  This module
makes control-plane misbehaviour a *first-class, reproducible input*:

- :class:`ChaosFault` / :class:`ChaosSchedule` — a declarative,
  seed-generated fault timeline (which fault class, when, how hard).
- :class:`ChaosKubeProxy` — a :class:`ClusterClient` that wraps the
  in-process :class:`FakeCluster` and executes the schedule against
  every API call: 5xx bursts, connection resets, added per-request
  latency (slowloris), watch-stream drops, resourceVersion expiry
  (410 Gone), partial bind-fanout failure, and the nastiest class —
  ``bind_blackhole``, where the bind IS applied server-side but the
  response is lost, so the scheduler's retry collides with its own
  earlier success mid-pipeline-retire.
- :func:`check_invariants` — the post-fault truth audit: no pod bound
  twice, no pod silently lost, usage ledger == server truth.
- :func:`run_chaos_soak` — drives a full :class:`SchedulerLoop` on
  VIRTUAL time through the schedule and emits the ``chaos_soak``
  benchmark document (time-to-recover, throughput-under-brownout,
  invariant counters) consumed by ``tools/bench_check.py``.

Everything is deterministic from the seed: the schedule, the per-call
fault draws, the workload, and therefore the recovery trace.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Sequence

import numpy as np

from kubernetesnetawarescheduler_tpu.k8s.client import ClusterClient
from kubernetesnetawarescheduler_tpu.k8s.kubeclient import (
    ApiServerError,
    CircuitBreaker,
    RetryBudget,
    _brownout_error,
)
from kubernetesnetawarescheduler_tpu.k8s.types import (
    Binding,
    Event,
    Node,
    Pod,
)

#: Every fault class the proxy knows how to inject.  ``watch_410``
#: models resourceVersion expiry (the server compacts history and the
#: watch must relist); ``bind_blackhole`` models an applied-but-
#: unacknowledged bind landing mid-pipeline-retire.
FAULT_CLASSES = ("http_5xx", "conn_reset", "latency", "watch_drop",
                 "watch_410", "bind_partial", "bind_blackhole")

_WATCH_KINDS = ("watch_drop", "watch_410")


@dataclasses.dataclass(frozen=True, slots=True)
class ChaosFault:
    """One fault window on the schedule timeline.

    ``probability`` gates per-request injection for the unary faults
    (a brownout is rarely 100% loss); ``fail_fraction`` plays the same
    role for the per-binding faults; ``latency_s`` is the added
    per-request delay for the ``latency`` class.  Times are seconds on
    the proxy's (virtual) clock.
    """

    kind: str
    start_s: float
    duration_s: float
    probability: float = 1.0
    latency_s: float = 0.0
    fail_fraction: float = 1.0

    def active(self, now: float) -> bool:
        return self.start_s <= now < self.start_s + self.duration_s

    @property
    def end_s(self) -> float:
        return self.start_s + self.duration_s

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True, slots=True)
class ChaosSchedule:
    """A seed-derived fault timeline: one window per requested class,
    spaced so each fault gets a clean recovery runway (overlapping
    windows are legal — hand-build the ``faults`` tuple for that)."""

    seed: int
    faults: tuple[ChaosFault, ...]

    @classmethod
    def generate(cls, seed: int,
                 classes: Sequence[str] = FAULT_CLASSES,
                 start_after_s: float = 2.0,
                 spacing_s: float = 6.0,
                 base_duration_s: float = 2.0) -> "ChaosSchedule":
        unknown = [c for c in classes if c not in FAULT_CLASSES]
        if unknown:
            raise ValueError(f"unknown fault classes: {unknown}")
        rng = np.random.default_rng(seed)
        faults: list[ChaosFault] = []
        t = float(start_after_s)
        for kind in classes:
            dur = float(base_duration_s) * float(rng.uniform(0.75, 1.5))
            faults.append(ChaosFault(
                kind=kind,
                start_s=round(t, 3),
                duration_s=round(dur, 3),
                probability=(float(rng.uniform(0.6, 0.95))
                             if kind in ("http_5xx", "conn_reset")
                             else 1.0),
                latency_s=(float(rng.uniform(0.05, 0.3))
                           if kind == "latency" else 0.0),
                fail_fraction=(float(rng.uniform(0.4, 0.8))
                               if kind in ("bind_partial",
                                           "bind_blackhole")
                               else 1.0)))
            t += float(spacing_s)
        return cls(seed=int(seed), faults=tuple(faults))

    def active(self, now: float) -> list[ChaosFault]:
        return [f for f in self.faults if f.active(now)]

    @property
    def end_s(self) -> float:
        return max((f.end_s for f in self.faults), default=0.0)

    @property
    def classes(self) -> list[str]:
        seen: list[str] = []
        for f in self.faults:
            if f.kind not in seen:
                seen.append(f.kind)
        return seen

    def to_dicts(self) -> list[dict]:
        return [f.to_dict() for f in self.faults]


class ChaosKubeProxy(ClusterClient):
    """A fault-injecting apiserver proxy around :class:`FakeCluster`.

    Sits where the real apiserver would: every read/write the
    scheduler issues passes through :meth:`_unary_fault` (raising
    :class:`ApiServerError` 503s / :class:`ConnectionResetError`
    during active windows), the bind fanout gets per-binding verdicts
    (fail / blackhole / ok), and watch fanout is suppressed during
    ``watch_drop``/``watch_410`` windows with the gap surfaced to
    :meth:`on_watch_gap` handlers when the window ends (a real client
    notices the gap at reconnect).

    The proxy owns the breaker + retry budget the loop reads — the
    same objects a real :class:`KubeClient` would own — fed from the
    *observed* outcome of every call, injected or genuine.  Time is a
    manual virtual clock (:meth:`advance`), shared with the breaker so
    cooldowns elapse deterministically in a soak.
    """

    def __init__(self, inner, schedule: ChaosSchedule,
                 failure_threshold: int = 5, window_s: float = 30.0,
                 cooldown_s: float = 2.0, retry_budget: int = 8) -> None:
        self.inner = inner
        self.schedule = schedule
        self._now = 0.0
        self._time_lock = threading.Lock()
        self.breaker = CircuitBreaker(
            failure_threshold=failure_threshold, window_s=window_s,
            cooldown_s=cooldown_s, clock=self.clock)
        self.retry_budget = RetryBudget(retry_budget)
        # Per-call draws come from a stream derived from (not equal
        # to) the schedule seed, so schedule shape and draw sequence
        # are independent.
        self._rng = np.random.default_rng(
            np.random.SeedSequence(schedule.seed).spawn(1)[0])
        self._rng_lock = threading.Lock()
        # Watch interposition: outer handlers per channel; we register
        # one fan-out shim per channel with the inner cluster.
        self._handlers: dict[str, list] = {
            "pod_added": [], "node_added": [], "pod_deleted": [],
            "node_deleted": [], "pdb_changed": []}
        self._interposed: set[str] = set()
        self._gap_handlers: list[Callable[[str], None]] = []
        self._prev_watch_active: set[ChaosFault] = set()
        # Injection ledger (inspected by tests and the soak doc).
        self.injected: dict[str, int] = {k: 0 for k in FAULT_CLASSES}
        self.injected_latency_s = 0.0
        self.dropped_watch_events = 0
        self.dropped_event_posts = 0
        self.blackholed_binds = 0

    # ---- virtual time --------------------------------------------

    def clock(self) -> float:
        with self._time_lock:
            return self._now

    def advance(self, dt: float) -> None:
        """Advance virtual time and deliver end-of-window effects
        (watch gaps fire when their window closes)."""
        with self._time_lock:
            self._now += float(dt)
        self.tick()

    def tick(self) -> None:
        """Fire watch-gap notifications for watch windows that just
        ended — the moment a reconnecting client would discover its
        resourceVersion no longer resumes."""
        now = self.clock()
        active = {f for f in self.schedule.faults
                  if f.kind in _WATCH_KINDS and f.active(now)}
        ended = self._prev_watch_active - active
        self._prev_watch_active = active
        for fault in sorted(ended, key=lambda f: f.start_s):
            reason = ("watch: 410 Gone (resourceVersion expired)"
                      if fault.kind == "watch_410"
                      else "watch: stream dropped")
            for handler in list(self._gap_handlers):
                try:
                    handler(reason)
                except Exception:
                    pass

    # ---- fault plumbing ------------------------------------------

    def _draw(self) -> float:
        with self._rng_lock:
            return float(self._rng.random())

    def _watch_suppressed(self) -> bool:
        now = self.clock()
        return any(f.kind in _WATCH_KINDS and f.active(now)
                   for f in self.schedule.faults)

    def _unary_fault(self, op: str) -> None:
        """Raise the injected failure for a plain request, if any
        active window draws one; otherwise record the success."""
        now = self.clock()
        for fault in self.schedule.active(now):
            if (fault.kind == "http_5xx"
                    and self._draw() < fault.probability):
                self.injected["http_5xx"] += 1
                self.breaker.record_failure()
                raise ApiServerError(
                    f"injected 503 on {op}", status=503)
            if (fault.kind == "conn_reset"
                    and self._draw() < fault.probability):
                self.injected["conn_reset"] += 1
                self.breaker.record_failure()
                raise ConnectionResetError(
                    f"injected connection reset on {op}")
            if fault.kind == "latency":
                self.injected["latency"] += 1
                self.injected_latency_s += fault.latency_s
        self.breaker.record_success()

    def _bind_verdict(self) -> tuple[str, Exception | None]:
        """Per-binding fate: ``("ok", None)``, ``("fail", exc)`` (not
        applied), or ``("blackhole", None)`` (applied, response
        lost)."""
        now = self.clock()
        for fault in self.schedule.active(now):
            if (fault.kind == "http_5xx"
                    and self._draw() < fault.probability):
                self.injected["http_5xx"] += 1
                return "fail", ApiServerError(
                    "injected 503 on bind", status=503)
            if (fault.kind == "conn_reset"
                    and self._draw() < fault.probability):
                self.injected["conn_reset"] += 1
                return "fail", ConnectionResetError(
                    "injected connection reset on bind")
            if (fault.kind == "bind_partial"
                    and self._draw() < fault.fail_fraction):
                self.injected["bind_partial"] += 1
                return "fail", ApiServerError(
                    "injected 503 mid bind fanout", status=503)
            if (fault.kind == "bind_blackhole"
                    and self._draw() < fault.fail_fraction):
                self.injected["bind_blackhole"] += 1
                return "blackhole", None
            if fault.kind == "latency":
                self.injected["latency"] += 1
                self.injected_latency_s += fault.latency_s
        return "ok", None

    def _record_outcome(self, exc: Exception | None) -> None:
        if exc is None or not _brownout_error(exc):
            self.breaker.record_success()
        else:
            self.breaker.record_failure()

    # ---- watch registration (interposed) -------------------------

    def _interpose(self, channel: str, register) -> None:
        if channel not in self._interposed:
            self._interposed.add(channel)

            def fan(*args, _ch=channel):
                if self._watch_suppressed():
                    self.dropped_watch_events += 1
                    return
                for handler in list(self._handlers[_ch]):
                    handler(*args)

            register(fan)

    def on_pod_added(self, handler) -> None:
        self._handlers["pod_added"].append(handler)
        self._interpose("pod_added", self.inner.on_pod_added)

    def on_node_added(self, handler) -> None:
        self._handlers["node_added"].append(handler)
        self._interpose("node_added", self.inner.on_node_added)

    def on_pod_deleted(self, handler) -> None:
        self._handlers["pod_deleted"].append(handler)
        self._interpose("pod_deleted", self.inner.on_pod_deleted)

    def on_node_deleted(self, handler) -> None:
        self._handlers["node_deleted"].append(handler)
        self._interpose("node_deleted", self.inner.on_node_deleted)

    def on_pdb_changed(self, handler) -> None:
        self._handlers["pdb_changed"].append(handler)
        self._interpose("pdb_changed", self.inner.on_pdb_changed)

    def on_watch_gap(self, handler) -> None:
        self._gap_handlers.append(handler)

    # ---- reads ----------------------------------------------------

    def list_nodes(self) -> Sequence[Node]:
        self._unary_fault("list nodes")
        return self.inner.list_nodes()

    def list_pending_pods(self) -> Sequence[Pod]:
        self._unary_fault("list pending pods")
        return self.inner.list_pending_pods()

    def list_all_pods(self):
        self._unary_fault("list all pods")
        return self.inner.list_all_pods()

    def list_pdbs(self):
        self._unary_fault("list pdbs")
        return self.inner.list_pdbs()

    # node_of / get_pod model warm watch-cache reads (KubeClient
    # serves them from its informer cache, no round trip): no fault.
    def node_of(self, pod_name: str) -> str:
        return self.inner.node_of(pod_name)

    def get_pod(self, pod_name: str):
        return self.inner.get_pod(pod_name)

    # ---- writes ---------------------------------------------------

    def bind(self, binding: Binding) -> None:
        err = self.bind_many([binding])[0]
        if err is not None:
            raise err

    def bind_many(self, bindings: Sequence[Binding]
                  ) -> list[Exception | None]:
        if not bindings:
            return []
        out: list[Exception | None] = [None] * len(bindings)
        apply_idx: list[int] = []
        blackhole_idx: list[int] = []
        for i in range(len(bindings)):
            fate, exc = self._bind_verdict()
            if fate == "fail":
                out[i] = exc
            else:
                apply_idx.append(i)
                if fate == "blackhole":
                    blackhole_idx.append(i)
        inner_out = self.inner.bind_many(
            [bindings[i] for i in apply_idx])
        for i, err in zip(apply_idx, inner_out):
            out[i] = err
        for i in blackhole_idx:
            if out[i] is None:
                # Applied server-side, acknowledgement lost: the
                # caller sees a transport error and will retry into
                # its own earlier success (the 409-heal path).
                self.blackholed_binds += 1
                out[i] = ConnectionResetError(
                    "injected reset after bind applied")
        for err in out:
            self._record_outcome(err)
        return out

    def bind_gang(self, bindings: Sequence[Binding]
                  ) -> list[Exception | None]:
        # The gang bind is one transaction: a drawn fault fails the
        # whole call without applying anything (all-or-nothing holds
        # under chaos too).
        fate, exc = self._bind_verdict()
        if fate == "fail":
            self._record_outcome(exc)
            return [exc] * len(bindings)
        if fate == "blackhole":
            out = self.inner.bind_gang(bindings)
            if all(err is None for err in out):
                self.blackholed_binds += len(bindings)
                lost = ConnectionResetError(
                    "injected reset after gang bind applied")
                out = [lost] * len(bindings)
            for err in out:
                self._record_outcome(err)
            return out
        out = self.inner.bind_gang(bindings)
        for err in out:
            self._record_outcome(err)
        return out

    def create_event(self, event: Event) -> None:
        # Event POSTs are best-effort in KubeClient (never raise); a
        # browned-out server just loses them.
        now = self.clock()
        for fault in self.schedule.active(now):
            if (fault.kind in ("http_5xx", "conn_reset")
                    and self._draw() < fault.probability):
                self.dropped_event_posts += 1
                self.breaker.record_failure()
                return
        self.inner.create_event(event)

    def create_events(self, events: Sequence[Event]) -> None:
        for event in events:
            self.create_event(event)

    def delete_pod(self, name: str, namespace: str = "default",
                   grace_period_seconds: int | None = None) -> None:
        self._unary_fault("delete pod")
        self.inner.delete_pod(
            name, namespace=namespace,
            grace_period_seconds=grace_period_seconds)

    # ---- harness passthrough (test setup, not API traffic) --------

    def add_node(self, node: Node) -> None:
        self.inner.add_node(node)

    def add_pod(self, pod: Pod) -> None:
        self.inner.add_pod(pod)

    def add_pods(self, pods) -> None:
        self.inner.add_pods(pods)

    def delete_node(self, name: str) -> None:
        self.inner.delete_node(name)

    @property
    def bindings(self):
        return self.inner.bindings

    @property
    def events(self):
        return self.inner.events


def check_invariants(loop, cluster) -> dict[str, int]:
    """Audit scheduler state against server truth after the fault
    clears.  All four counters must be zero for a healthy recovery:

    - ``pods_double_bound``: a pod name appears in >1 binding.
    - ``pods_lost``: a pending pod the scheduler is responsible for
      with NO trace — not queued, not parked, not gang-gated, not
      awaiting preemption, and no Warning event telling an operator
      why.  Silent loss is the one unforgivable failure.
    - ``ledger_orphans``: usage committed for a pod not actually
      bound on the server (phantom usage -> under-scheduling).
    - ``ledger_missing``: a bound pod with no committed usage
      (invisible load -> over-scheduling).
    """
    from kubernetesnetawarescheduler_tpu.core.gang import gang_key_of

    names = [b.pod_name for b in cluster.bindings]
    double_bound = len(names) - len(set(names))

    enc = loop.encoder
    with enc._lock:
        committed = set(enc._committed)
    all_pods = cluster.list_all_pods() or []
    bound = {p.uid for p in all_pods if p.node_name}
    ledger_orphans = len(committed - bound)
    ledger_missing = len(bound - committed)

    warned = {e.involved_pod for e in cluster.events
              if e.type == "Warning"}
    queued = set(getattr(loop.queue, "_queued", ()))
    lost = 0
    for pod in cluster.list_pending_pods():
        if pod.scheduler_name != loop.cfg.scheduler_name:
            continue
        if (pod.uid in loop._parked_uids
                or pod.uid in loop._awaiting_preemption
                or f"{pod.namespace}/{pod.name}" in queued
                or pod.name in warned
                or (loop.gangs is not None and gang_key_of(pod))):
            continue
        lost += 1
    return {"pods_double_bound": double_bound,
            "pods_lost": lost,
            "ledger_orphans": ledger_orphans,
            "ledger_missing": ledger_missing}


def run_chaos_soak(seed: int = 0, num_nodes: int = 32,
                   num_pods: int = 192,
                   classes: Sequence[str] = FAULT_CLASSES,
                   cycle_s: float = 0.25,
                   recovery_limit_s: float = 120.0,
                   pipelined: bool = True,
                   spacing_s: float = 6.0,
                   base_duration_s: float = 2.0,
                   state_faults: bool = False) -> dict:
    """Drive a full SchedulerLoop through a seeded fault schedule on
    virtual time and return the ``chaos_soak`` benchmark document.

    Pods arrive in waves across the fault horizon so every brownout
    window sees live traffic; after the last window the loop keeps
    cycling until the backlog drains and the breaker closes (or
    ``recovery_limit_s`` of virtual time elapses — reported, not
    raised, so the artifact shows the failure).

    ``state_faults=True`` layers the r10 state-layer chaos on top of
    the control-plane schedule: a seeded
    :class:`~..core.state_chaos.StateChaosInjector` corrupts the
    device planes mid-soak and an
    :class:`~..core.integrity.IntegrityAuditor` (driven inline every
    maintain interval, not on its own thread — virtual time) must
    detect and repair each one; the counters land in
    ``detail["integrity"]``.
    """
    from kubernetesnetawarescheduler_tpu.bench.fakecluster import (
        ClusterSpec,
        WorkloadSpec,
        build_fake_cluster,
        feed_metrics,
        generate_workload,
    )
    from kubernetesnetawarescheduler_tpu.config import SchedulerConfig
    from kubernetesnetawarescheduler_tpu.core.loop import SchedulerLoop

    cfg = SchedulerConfig(max_nodes=max(num_nodes, 8), max_pods=16,
                          max_peers=4,
                          queue_capacity=num_pods + 64)
    schedule = ChaosSchedule.generate(
        seed, classes=classes, spacing_s=spacing_s,
        base_duration_s=base_duration_s)
    proxy, lat, bw = build_fake_cluster(
        ClusterSpec(num_nodes=num_nodes, seed=seed + 1),
        chaos=schedule)
    loop = SchedulerLoop(proxy, cfg, method="parallel",
                         burst_batches=4, pipelined=pipelined)
    loop.encoder.set_network(lat, bw)
    feed_metrics(proxy.inner, loop.encoder,
                 np.random.default_rng(seed + 2))
    pods = generate_workload(
        WorkloadSpec(num_pods=num_pods, seed=seed + 3, services=8,
                     peer_fraction=0.4, affinity_fraction=0.1,
                     anti_fraction=0.1),
        scheduler_name=cfg.scheduler_name)

    auditor = injector = None
    if state_faults:
        from kubernetesnetawarescheduler_tpu.core.integrity import (
            IntegrityAuditor,
        )
        from kubernetesnetawarescheduler_tpu.core.state_chaos import (
            StateChaosInjector,
        )

        auditor = IntegrityAuditor(loop.encoder, loop)
        injector = StateChaosInjector(loop.encoder, seed=seed + 4,
                                      loop=loop)
        loop.integrity = auditor
        loop.state_chaos = injector

    horizon = schedule.end_s + 1.0
    # Wave arrivals: evenly spread over the horizon so each window
    # browns out live traffic (index by arrival cycle).
    arrivals: dict[int, list] = {}
    total_cycles = max(1, int(horizon / cycle_s))
    for i, pod in enumerate(pods):
        arrivals.setdefault(i * total_cycles // len(pods),
                            []).append(pod)

    healthy_cycles = healthy_assumed = 0
    brownout_cycles = brownout_assumed = 0
    degraded_cycles = 0
    last_fault_end = schedule.end_s
    recovered_at: float | None = None
    cycle = 0
    while True:
        now = proxy.clock()
        if cycle in arrivals:
            proxy.add_pods(arrivals.pop(cycle))
        faulted = bool(schedule.active(now))
        assumed = loop.run_once()
        if loop.degraded:
            degraded_cycles += 1
        if now < horizon:
            if faulted:
                brownout_cycles += 1
                brownout_assumed += assumed
            else:
                healthy_cycles += 1
                healthy_assumed += assumed
        if cycle % 16 == 15:
            loop.maintain()
            if injector is not None and now < horizon:
                # One state fault per maintain interval, audited
                # inline right after — the soak proves repair keeps
                # pace with injection under live traffic.
                injector.inject_random()
                auditor.audit_once()
        proxy.advance(cycle_s)
        cycle += 1
        now = proxy.clock()
        if now >= horizon and not arrivals:
            done = (len(loop.queue) == 0
                    and not loop._parked_binds
                    and loop._pipe_inflight is None
                    and loop.breaker.state == "closed")
            if done:
                # One settling pass: retire anything the bind worker
                # still holds, then confirm nothing reappeared.
                loop.flush_binds()
                loop.run_once()
                if (len(loop.queue) == 0 and not loop._parked_binds
                        and loop._pipe_inflight is None):
                    recovered_at = proxy.clock()
                    break
            if now - horizon > recovery_limit_s:
                break
    # Final settle on healthy control plane.
    loop.flush_binds()
    loop.maintain()
    loop.run_until_drained(max_cycles=50)
    loop.flush_binds()
    loop.stop_bind_worker()

    invariants = check_invariants(loop, proxy.inner)
    time_to_recover = (max(0.0, recovered_at - last_fault_end)
                       if recovered_at is not None else None)
    return {
        "metric": "chaos_soak",
        "seed": int(seed),
        "fault_classes": list(schedule.classes),
        "schedule": schedule.to_dicts(),
        "invariants": invariants,
        "recovered": recovered_at is not None,
        "time_to_recover_s": time_to_recover,
        "detail": {
            "virtual_cycle_s": cycle_s,
            "cycles": cycle,
            "pods": num_pods,
            "nodes": num_nodes,
            "scheduled": loop.scheduled,
            "unschedulable": loop.unschedulable,
            "bound": len(proxy.inner.bindings),
            "healthy": {"cycles": healthy_cycles,
                        "assumed": healthy_assumed,
                        "assumed_per_cycle": (
                            healthy_assumed / healthy_cycles
                            if healthy_cycles else 0.0)},
            "brownout": {"cycles": brownout_cycles,
                         "assumed": brownout_assumed,
                         "assumed_per_cycle": (
                             brownout_assumed / brownout_cycles
                             if brownout_cycles else 0.0)},
            "degraded_cycles": degraded_cycles,
            "binds_parked_total": loop.binds_parked_total,
            "breaker_opens": loop.breaker.opens_total,
            "watch_gaps": loop.watch_gaps,
            "relists": loop.relists,
            "relist_repairs": loop.relist_repairs,
            "parked_dropped": loop.parked_dropped,
            "injected": dict(proxy.injected),
            "injected_latency_s": round(proxy.injected_latency_s, 4),
            "dropped_watch_events": proxy.dropped_watch_events,
            "dropped_event_posts": proxy.dropped_event_posts,
            "blackholed_binds": proxy.blackholed_binds,
            **({"integrity": {
                "state_faults_injected": dict(injector.injected),
                "audits": auditor.audits_total,
                "drift_detected": auditor.drift_detected_total,
                "repairs": dict(auditor.repairs),
                "unrepaired": auditor.unrepaired_total,
            }} if auditor is not None else {}),
        },
    }
