"""Kubernetes boundary: API object types, cluster clients, informers.

The reference holds this boundary with client-go (informers at
scheduler.go:161-187, Bind at :196-206, Events at :214-233).  Here the
same contract is an abstract :class:`~.client.ClusterClient` with an
in-memory :class:`~.client.FakeCluster` used by tests and benchmarks —
the "test multi-node without a real cluster" answer of SURVEY.md 4 —
and the native extender shim holding the real kube-scheduler boundary.
"""

from kubernetesnetawarescheduler_tpu.k8s.types import (  # noqa: F401
    Binding,
    Event,
    Node,
    Pod,
)
from kubernetesnetawarescheduler_tpu.k8s.client import (  # noqa: F401
    ClusterClient,
    FakeCluster,
)
from kubernetesnetawarescheduler_tpu.k8s.informer import (  # noqa: F401
    Informer,
    PodQueue,
)
