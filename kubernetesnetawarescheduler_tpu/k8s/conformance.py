"""Kubernetes wire-contract schemas: the third leg of the triangle.

The kubeclient's wire format was previously validated ONLY against
in-repo fake servers — and the fakes only against the client.  A wrong
shared assumption (a misspelled field, a wrong nesting) would pass
both ways (VERDICT r4 missing #2 / next-round #7).  This module is an
INDEPENDENT authority: JSON Schemas for every body the scheduler
emits and every body it consumes, authored from the upstream
Kubernetes API reference (API docs for core/v1 Binding, Event,
DeleteOptions, Pod, Node; policy/v1 PodDisruptionBudget; the
apimachinery watch framing; and the kube-scheduler extender contract
``k8s.io/kube-scheduler/extender/v1``) — NOT from this repo's client
or fakes.  The conformance tests validate BOTH sides against these
schemas, so a client/fake co-drift now has to also fool a schema
neither of them generated.

Emitted-body schemas are STRICT (``additionalProperties: false``):
everything the scheduler puts on the wire is enumerated, so a typo'd
or hallucinated field fails.  Consumed-body schemas are STRUCTURAL
(extra fields allowed): a real apiserver sends dozens of fields the
scheduler ignores (managedFields, status conditions, ...), and the
schema pins only the shape it actually relies on.

Reference parity notes: Binding POST mirrors scheduler.go:196-206;
Event POST mirrors scheduler.go:214-233 (corev1.Event with
involvedObject/reason/message/source/counts).
"""

from __future__ import annotations

import re
from typing import Any, Mapping


def _jsonschema():
    """Lazy: the schemas themselves are plain dicts and the deploy
    image does not ship jsonschema — importing this module must not
    require it, only VALIDATING does."""
    try:
        import jsonschema
    except ImportError as exc:  # pragma: no cover
        raise RuntimeError(
            "conformance validation requires the 'jsonschema' "
            "package (available in the dev/test environment)") from exc
    return jsonschema

# RFC 1123 DNS label/subdomain as the apiserver enforces for names
# and namespaces.
_DNS_LABEL = r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$"
_DNS_SUBDOMAIN = r"^[a-z0-9]([-a-z0-9.]*[a-z0-9])?$"

# --- core/v1 Binding (the pods/{name}/binding subresource body) -----

BINDING_SCHEMA: dict = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "object",
    "required": ["apiVersion", "kind", "metadata", "target"],
    "additionalProperties": False,
    "properties": {
        "apiVersion": {"const": "v1"},
        "kind": {"const": "Binding"},
        "metadata": {
            "type": "object",
            "required": ["name"],
            "additionalProperties": False,
            "properties": {
                "name": {"type": "string",
                         "pattern": _DNS_SUBDOMAIN},
                "namespace": {"type": "string",
                              "pattern": _DNS_LABEL},
                "uid": {"type": "string"},
            },
        },
        "target": {
            "type": "object",
            "required": ["kind", "name"],
            "additionalProperties": False,
            "properties": {
                "apiVersion": {"const": "v1"},
                "kind": {"const": "Node"},
                "name": {"type": "string",
                         "pattern": _DNS_SUBDOMAIN},
            },
        },
    },
}

# --- core/v1 Event (namespaced POST body) ---------------------------

EVENT_SCHEMA: dict = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "object",
    "required": ["apiVersion", "kind", "metadata", "involvedObject",
                 "reason", "message", "type"],
    "additionalProperties": False,
    "properties": {
        "apiVersion": {"const": "v1"},
        "kind": {"const": "Event"},
        "metadata": {
            "type": "object",
            # The apiserver requires name OR generateName.
            "anyOf": [{"required": ["name"]},
                      {"required": ["generateName"]}],
            "additionalProperties": False,
            "properties": {
                "name": {"type": "string"},
                "generateName": {"type": "string"},
                "namespace": {"type": "string",
                              "pattern": _DNS_LABEL},
                # String-valued annotations (the structured link
                # identity of LinkDegraded/LinkQuarantined rides
                # here; a real apiserver accepts any annotations).
                "annotations": {
                    "type": "object",
                    "additionalProperties": {"type": "string"},
                },
            },
        },
        "involvedObject": {
            "type": "object",
            "required": ["kind", "name"],
            "additionalProperties": False,
            "properties": {
                "apiVersion": {"const": "v1"},
                "kind": {"enum": ["Pod", "Node"]},
                "name": {"type": "string"},
                "namespace": {"type": "string"},
                "uid": {"type": "string"},
            },
        },
        "reason": {"type": "string", "minLength": 1,
                   # UpperCamelCase machine-readable short reason, as
                   # kubectl and controllers expect.
                   "pattern": r"^[A-Z][A-Za-z0-9]*$"},
        "message": {"type": "string"},
        "type": {"enum": ["Normal", "Warning"]},
        "count": {"type": "integer", "minimum": 1},
        "firstTimestamp": {"type": "string",
                           "format": "date-time"},
        "lastTimestamp": {"type": "string", "format": "date-time"},
        "source": {
            "type": "object",
            "additionalProperties": False,
            "properties": {
                "component": {"type": "string"},
                "host": {"type": "string"},
            },
        },
    },
}

# --- meta/v1 DeleteOptions (graceful eviction) ----------------------

DELETE_OPTIONS_SCHEMA: dict = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "object",
    "required": ["apiVersion", "kind"],
    "additionalProperties": False,
    "properties": {
        "apiVersion": {"const": "v1"},
        "kind": {"const": "DeleteOptions"},
        "gracePeriodSeconds": {"type": "integer", "minimum": 0},
        "propagationPolicy": {
            "enum": ["Orphan", "Background", "Foreground"]},
        "preconditions": {"type": "object"},
    },
}

# --- consumed shapes (structural: extra fields allowed) -------------

POD_SCHEMA: dict = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "object",
    "required": ["metadata"],
    "properties": {
        "apiVersion": {"const": "v1"},
        "kind": {"const": "Pod"},
        "metadata": {
            "type": "object",
            "required": ["name"],
            "properties": {
                "name": {"type": "string"},
                "namespace": {"type": "string"},
                "uid": {"type": "string"},
                "labels": {"type": "object",
                           "additionalProperties": {"type": "string"}},
                "annotations": {
                    "type": "object",
                    "additionalProperties": {"type": "string"}},
                "resourceVersion": {"type": "string"},
            },
        },
        "spec": {
            "type": "object",
            "properties": {
                "nodeName": {"type": "string"},
                "schedulerName": {"type": "string"},
                "priority": {"type": "integer"},
                "nodeSelector": {
                    "type": "object",
                    "additionalProperties": {"type": "string"}},
                "containers": {
                    "type": "array",
                    "items": {
                        "type": "object",
                        "properties": {
                            "resources": {
                                "type": "object",
                                "properties": {
                                    "requests": {
                                        "type": "object",
                                        "additionalProperties": {
                                            "type": ["string",
                                                     "number"]}},
                                },
                            },
                        },
                    },
                },
                "tolerations": {"type": "array",
                                "items": {"type": "object"}},
                "affinity": {"type": "object"},
                "topologySpreadConstraints": {
                    "type": "array", "items": {"type": "object"}},
            },
        },
        "status": {
            "type": "object",
            "properties": {
                "phase": {"enum": ["Pending", "Running", "Succeeded",
                                   "Failed", "Unknown"]},
            },
        },
    },
}

NODE_SCHEMA: dict = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "object",
    "required": ["metadata"],
    "properties": {
        "apiVersion": {"const": "v1"},
        "kind": {"const": "Node"},
        "metadata": {
            "type": "object",
            "required": ["name"],
            "properties": {
                "name": {"type": "string"},
                "labels": {"type": "object",
                           "additionalProperties": {"type": "string"}},
            },
        },
        "spec": {
            "type": "object",
            "properties": {
                "taints": {
                    "type": "array",
                    "items": {
                        "type": "object",
                        "required": ["key", "effect"],
                        "properties": {
                            "key": {"type": "string"},
                            "value": {"type": "string"},
                            "effect": {"enum": [
                                "NoSchedule", "PreferNoSchedule",
                                "NoExecute"]},
                        },
                    },
                },
                "unschedulable": {"type": "boolean"},
            },
        },
        "status": {
            "type": "object",
            "properties": {
                "allocatable": {
                    "type": "object",
                    "additionalProperties": {"type": "string"}},
                "capacity": {
                    "type": "object",
                    "additionalProperties": {"type": "string"}},
                "addresses": {
                    "type": "array",
                    "items": {
                        "type": "object",
                        "required": ["type", "address"],
                        "properties": {
                            "type": {"type": "string"},
                            "address": {"type": "string"}},
                    },
                },
            },
        },
    },
}

PDB_SCHEMA: dict = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "object",
    "required": ["metadata"],
    "properties": {
        "apiVersion": {"const": "policy/v1"},
        "kind": {"const": "PodDisruptionBudget"},
        "metadata": {
            "type": "object",
            "required": ["name"],
            "properties": {"name": {"type": "string"},
                           "namespace": {"type": "string"},
                           "uid": {"type": "string"}},
        },
        "spec": {
            "type": "object",
            "properties": {
                "minAvailable": {"type": ["integer", "string"]},
                "maxUnavailable": {"type": ["integer", "string"]},
                "selector": {
                    "type": "object",
                    "properties": {
                        "matchLabels": {
                            "type": "object",
                            "additionalProperties": {
                                "type": "string"}},
                    },
                },
            },
        },
        "status": {
            "type": "object",
            "properties": {
                "disruptionsAllowed": {"type": "integer"},
                "expectedPods": {"type": "integer"},
            },
        },
    },
}

# apimachinery watch framing: one JSON object per chunk/line.
WATCH_EVENT_SCHEMA: dict = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "object",
    "required": ["type", "object"],
    "properties": {
        "type": {"enum": ["ADDED", "MODIFIED", "DELETED",
                          "BOOKMARK", "ERROR"]},
        "object": {"type": "object"},
    },
}

LIST_SCHEMA: dict = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "object",
    "required": ["items"],
    "properties": {
        "items": {"type": "array", "items": {"type": "object"}},
        "metadata": {
            "type": "object",
            "properties": {"resourceVersion": {"type": "string"}},
        },
    },
}

# --- kube-scheduler extender contract (extender/v1) -----------------

EXTENDER_ARGS_SCHEMA: dict = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "object",
    "required": ["pod"],
    "properties": {
        "pod": POD_SCHEMA,
        # Exactly one of nodes / nodenames is set depending on the
        # extender's nodeCacheCapable configuration.
        "nodes": {
            "type": "object",
            "properties": {"items": {"type": "array",
                                     "items": NODE_SCHEMA}},
        },
        "nodenames": {"type": "array", "items": {"type": "string"}},
    },
}

HOST_PRIORITY_LIST_SCHEMA: dict = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "array",
    "items": {
        "type": "object",
        "required": ["host", "score"],
        "additionalProperties": False,
        "properties": {
            "host": {"type": "string"},
            # extender/v1 HostPriority.Score is int64; the stock
            # scheduler expects [0, MaxExtenderPriority=10] unless
            # weighted.
            "score": {"type": "integer"},
        },
    },
}

EXTENDER_FILTER_RESULT_SCHEMA: dict = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "object",
    "additionalProperties": False,
    "properties": {
        "nodes": {
            "type": ["object", "null"],
            "properties": {"items": {"type": "array",
                                     "items": NODE_SCHEMA}},
        },
        "nodenames": {"type": ["array", "null"],
                      "items": {"type": "string"}},
        "failedNodes": {
            "type": ["object", "null"],
            "additionalProperties": {"type": "string"}},
        "failedAndUnresolvableNodes": {
            "type": ["object", "null"],
            "additionalProperties": {"type": "string"}},
        "error": {"type": ["string", "null"]},
    },
}

# --- request-path dispatch ------------------------------------------

# (method, path-regex) -> schema for the REQUEST body.  None means
# the body must be absent.  Route namespaces reuse the ONE _DNS_LABEL
# grammar (anchors stripped) so body schemas and route patterns can
# never drift apart.
_NS = _DNS_LABEL.strip("^$")
_REQUEST_CONTRACTS: list[tuple[str, str, dict | None]] = [
    ("POST",
     rf"^/api/v1/namespaces/{_NS}/pods/[^/]+/binding$",
     BINDING_SCHEMA),
    ("POST",
     rf"^/api/v1/namespaces/{_NS}/events$",
     EVENT_SCHEMA),
    ("DELETE",
     rf"^/api/v1/namespaces/{_NS}/pods/[^/]+$",
     DELETE_OPTIONS_SCHEMA),
    ("GET", r"^/api/v1/nodes(\?.*)?$", None),
    ("GET", r"^/api/v1/pods(\?.*)?$", None),
    ("GET",
     rf"^/api/v1/namespaces/{_NS}/pods(\?.*)?$",
     None),
    ("GET", r"^/apis/policy/v1/poddisruptionbudgets(\?.*)?$", None),
]


class ConformanceError(AssertionError):
    pass


def validate_request(method: str, path: str,
                     body: Mapping[str, Any] | None) -> None:
    """Validate one client-emitted request (method, path, body)
    against the Kubernetes API contract.  Raises ConformanceError on
    an unknown route or a non-conforming body."""
    for m, pat, schema in _REQUEST_CONTRACTS:
        if m == method and re.match(pat, path):
            if schema is None:
                if body not in (None, {}):
                    raise ConformanceError(
                        f"{method} {path}: unexpected body")
                return
            if body is None:
                # DELETE body (DeleteOptions) is optional.
                if method == "DELETE":
                    return
                raise ConformanceError(
                    f"{method} {path}: body required")
            _validate(body, schema, f"{method} {path}")
            return
    raise ConformanceError(f"no contract for {method} {path}")


_FORMAT_CHECKER = None


def _format_checker():
    """A module-OWNED FormatChecker with a guaranteed date-time rule.
    jsonschema's stock FORMAT_CHECKER silently skips formats whose
    optional validator package (rfc3339-validator) is absent — the
    check would then be inert in exactly the quiet way this module
    exists to prevent — so the RFC 3339 shape is enforced here
    unconditionally."""
    global _FORMAT_CHECKER
    if _FORMAT_CHECKER is None:
        js = _jsonschema()
        fc = js.FormatChecker()

        @fc.checks("date-time")
        def _date_time(value) -> bool:  # noqa: ANN001
            return isinstance(value, str) and bool(re.match(
                r"^\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}"
                r"(\.\d+)?(Z|[+-]\d{2}:\d{2})$", value))

        _FORMAT_CHECKER = fc
    return _FORMAT_CHECKER


def _validate(obj: Any, schema: dict, what: str) -> None:
    js = _jsonschema()
    try:
        # format_checker: without it "format": "date-time" is an
        # inert annotation and a malformed Event timestamp would sail
        # through — the exact co-drift class this module exists for.
        js.validate(obj, schema, format_checker=_format_checker())
    except js.ValidationError as exc:
        raise ConformanceError(
            f"{what}: {exc.message} at "
            f"{list(exc.absolute_path)}") from exc


def validate_pod(obj: Mapping[str, Any]) -> None:
    _validate(obj, POD_SCHEMA, "Pod")


def validate_node(obj: Mapping[str, Any]) -> None:
    _validate(obj, NODE_SCHEMA, "Node")


def validate_pdb(obj: Mapping[str, Any]) -> None:
    _validate(obj, PDB_SCHEMA, "PodDisruptionBudget")


def validate_watch_event(obj: Mapping[str, Any]) -> None:
    """Validate the frame AND the carried object.  The object's kind
    is taken from ``kind`` when present (real apiservers set it on
    watch objects) and sniffed structurally otherwise; an object
    whose kind cannot be determined FAILS — a silent skip here would
    hollow out exactly the drift detection this module exists for."""
    _validate(obj, WATCH_EVENT_SCHEMA, "WatchEvent")
    if obj["type"] in ("ERROR", "BOOKMARK"):
        return
    o = obj["object"]
    kind = o.get("kind", "")
    if not kind:
        spec, status = o.get("spec", {}), o.get("status", {})
        if "containers" in spec or "schedulerName" in spec \
                or "nodeName" in spec:
            kind = "Pod"
        elif "allocatable" in status or "capacity" in status \
                or "taints" in spec or "unschedulable" in spec:
            kind = "Node"
    if kind == "Pod":
        validate_pod(o)
    elif kind == "Node":
        validate_node(o)
    elif kind == "PodDisruptionBudget":
        validate_pdb(o)
    else:
        raise ConformanceError(
            "WatchEvent object kind undeterminable: "
            f"{sorted(o.keys())}")


def validate_list(obj: Mapping[str, Any]) -> None:
    _validate(obj, LIST_SCHEMA, "List")


def validate_extender_args(obj: Mapping[str, Any]) -> None:
    _validate(obj, EXTENDER_ARGS_SCHEMA, "ExtenderArgs")


def validate_host_priority_list(obj: Any) -> None:
    _validate(obj, HOST_PRIORITY_LIST_SCHEMA, "HostPriorityList")


def validate_extender_filter_result(obj: Mapping[str, Any]) -> None:
    _validate(obj, EXTENDER_FILTER_RESULT_SCHEMA,
              "ExtenderFilterResult")
