"""Lightweight Kubernetes API object mirrors.

Only the fields the scheduling path actually consumes, mirroring what
the reference touches on client-go objects: pod name/namespace/UID and
``spec.schedulerName`` / ``spec.nodeName`` (scheduler.go:170, :196-206,
:224-229), node names (scheduler.go:182), plus the request/affinity/
toleration surface the reference *should* have consulted but never did
(its ``prioritize`` ignores the pod, scheduler.go:248).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Mapping, Sequence

_uid_counter = itertools.count(1)


def _next_uid(prefix: str) -> str:
    return f"{prefix}-{next(_uid_counter):08x}"


@dataclasses.dataclass(slots=True)
class Node:
    """A schedulable node.

    ``capacity`` maps resource name -> allocatable amount (cpu cores,
    mem GiB, net bandwidth Gbps — the :class:`~..config.Resource` axes).
    ``labels`` and ``taints`` are plain string sets; the encoder interns
    them into the bitmask columns of ``ClusterState``.
    """

    name: str
    capacity: Mapping[str, float] = dataclasses.field(default_factory=dict)
    labels: frozenset[str] = frozenset()
    taints: frozenset[str] = frozenset()
    ready: bool = True
    # Cordoned (kubectl cordon -> spec.unschedulable): running pods
    # stay, no new placements.
    unschedulable: bool = False
    # Optional topology hints used by the fake-cluster network model.
    zone: str = ""
    rack: str = ""


@dataclasses.dataclass(slots=True)
class Pod:
    """A pod to schedule.

    ``peers`` names already-known traffic partners (pod names) with
    relative traffic volumes; the encoder resolves placed peers to node
    indices.  ``group`` is the pod's (anti-)affinity group label —
    the hostname-topology reduction of k8s inter-pod affinity.
    """

    name: str
    namespace: str = "default"
    uid: str = dataclasses.field(default_factory=lambda: _next_uid("pod"))
    scheduler_name: str = "netAwareScheduler"
    node_name: str = ""  # empty = pending (scheduler.go:170)
    requests: Mapping[str, float] = dataclasses.field(default_factory=dict)
    peers: Mapping[str, float] = dataclasses.field(default_factory=dict)
    tolerations: frozenset[str] = frozenset()
    node_selector: frozenset[str] = frozenset()
    # The pod's own labels (``k=v`` strings) — the basis of
    # LABEL-driven group membership: a pod is a member of every
    # registered selector-group its labels satisfy (kube semantics),
    # in addition to its explicit ``group`` annotation below.
    labels: frozenset[str] = frozenset()
    group: str = ""
    affinity_groups: frozenset[str] = frozenset()
    anti_groups: frozenset[str] = frozenset()
    # Selector definitions for group keys referenced by this pod's
    # (anti-)affinity/spread terms: canonical group key -> selector
    # structure ``(matchLabels sorted ((k, v), ...), matchExpressions
    # sorted ((op, key, values), ...))``.  The encoder registers these
    # so OTHER pods' labels can be evaluated for membership — the
    # labelSelector-parity path (no annotation opt-in required).
    selector_defs: Mapping[str, tuple] = dataclasses.field(
        default_factory=dict)
    # Zone-scoped (topologyKey: topology.kubernetes.io/zone) required
    # pod (anti-)affinity: the pod must land in a zone hosting a
    # member of some ``zone_affinity_groups`` group / hosting no
    # member of any ``zone_anti_groups`` group.  The hostname-scoped
    # pair above stays the node-level machinery; kube's symmetric
    # anti-affinity holds at zone scope too (ClusterState.az_anti).
    zone_affinity_groups: frozenset[str] = frozenset()
    zone_anti_groups: frozenset[str] = frozenset()
    # Preferred (soft) affinity, the weighted score-term counterpart of
    # the hard masks above — ``preferredDuringSchedulingIgnoredDuring
    # Execution`` semantics (the reference's own probe server relied on
    # it, netperfScript/deployment.yaml:17-26).  Each term is
    # ``(labels-or-group, weight)``; weight follows the k8s 1-100
    # scale and may be negative for avoidance (soft anti-affinity).
    #
    # - ``soft_node_affinity``: ((frozenset{"k=v", ...}, weight), ...)
    #   — score bonus on nodes carrying ALL labels of the term.
    # - ``soft_group_affinity``: (("group", weight), ...) — score
    #   bonus on nodes already hosting a pod of that group (negative
    #   weight = preferred spreading).
    soft_node_affinity: tuple = ()
    soft_group_affinity: tuple = ()
    # - ``soft_zone_affinity``: (("group", weight), ...) — score bonus
    #   on nodes whose ZONE hosts a member of that group (preferred
    #   podAffinity with topologyKey topology.kubernetes.io/zone);
    #   negative weight = preferred zone-level spreading.
    soft_zone_affinity: tuple = ()
    # Zone-level topologySpreadConstraints: ``spread_maxskew`` 0
    # disables; ``spread_hard`` True = whenUnsatisfiable: DoNotSchedule
    # (mask), False = ScheduleAnyway (score penalty per unit of excess
    # skew).  ``spread_group`` names the COUNTED pod set (the
    # constraint's labelSelector reduced to a group key, with its
    # definition in ``selector_defs``); empty = the pod's own
    # ``group``.
    spread_maxskew: int = 0
    spread_hard: bool = True
    spread_group: str = ""
    # Hard ``requiredDuringSchedulingIgnoredDuringExecution``
    # nodeAffinity (the matchExpressions form the reference's probe
    # Deployment used only in its *preferred* stanza,
    # netperfScript/deployment.yaml:17-26): a tuple of
    # nodeSelectorTerms, OR'd; each term a tuple of expressions,
    # AND'd; each expression ``(op, key, values)`` with op one of
    # "In" / "NotIn" / "Exists" / "DoesNotExist" / "Gt" / "Lt"
    # (numeric operators compare the node label's parsed value via
    # the encoder's numeric label table).  ``node_selector`` (the map
    # form) ANDs with this, matching Kubernetes.
    required_node_affinity: tuple = ()
    priority: float = 0.0
    # Count of hard constraints lost/narrowed at PARSE time (e.g. a
    # required anti-affinity term with an unrepresentable selector
    # dropped open, or an affinity term degraded to the unsatisfiable
    # sentinel).  The encoder folds this into the same per-pod
    # ConstraintDegraded event stream as interner-overflow drops, so
    # parse-time degradation is operator-visible too.
    parse_degraded: int = 0
    # Human-readable descriptions of the parse-time drops above —
    # surfaced verbatim in the ConstraintDegraded event so operators
    # see WHICH term stopped being enforced (an anti-affinity term
    # dropped OPEN is otherwise invisible until a co-location
    # violation bites).
    parse_degraded_detail: tuple = ()
    # Annotation-level PodDisruptionBudget: at least this many members
    # of the pod's ``group`` must stay up — preemption may not disrupt
    # below it.  With no group, a nonzero value protects the pod
    # itself from preemption outright.
    pdb_min_available: int = 0
    # Gang scheduling (multi-host slice jobs): pods sharing a
    # ``pod_group`` are placed all-or-nothing.  ``gang_min_member`` is
    # the gang size the group gates on (the pod-group annotation's
    # minMember); 0 or 1 means the pod schedules independently.
    # ``gang_timeout_s`` bounds how long an incomplete gang may sit
    # gated before its members are released back with a
    # FailedScheduling event (0 = the scheduler config default).
    pod_group: str = ""
    gang_min_member: int = 0
    gang_timeout_s: float = 0.0
    # Elastic gang reshaping (r17): the family of acceptable physical
    # realizations for the pod's gang, as ``((member_count, priority),
    # ...)`` sorted by declared preference.  Empty = the gang is rigid
    # (all-or-nothing at ``gang_min_member``, the pre-r17 behavior).
    # A realization places exactly ``member_count`` of the gang's
    # members; ``priority`` in (0, 1] weights how desirable that shape
    # is relative to the full one (the placer commits the feasible
    # realization maximizing priority-weighted realized desirability).
    gang_shapes: tuple = ()


@dataclasses.dataclass(frozen=True, slots=True)
class PodDisruptionBudget:
    """A ``policy/v1`` PodDisruptionBudget, reduced to what the
    preemption planner consumes: the selector (canonicalized to a
    selector-group, so member counting rides the same label-driven
    machinery as affinity) and the disruption bound.

    Exactly one of the four bound fields is normally set (kube rejects
    specs with both minAvailable and maxUnavailable); percentages are
    resolved against the LIVE member count at planning time (kube
    resolves against the controller's expected scale — a documented
    delta; ceil for minAvailable, floor for maxUnavailable, both the
    conservative direction)."""

    name: str
    namespace: str = "default"
    uid: str = ""
    selector_key: str = ""     # canonical group key of the selector
    selector_def: tuple = ((), ())
    min_available: int | None = None
    min_available_pct: float | None = None
    max_unavailable: int | None = None
    max_unavailable_pct: float | None = None


@dataclasses.dataclass(frozen=True, slots=True)
class Binding:
    """The bind record POSTed on placement (scheduler.go:196-206)."""

    pod_name: str
    namespace: str
    node_name: str


@dataclasses.dataclass(frozen=True, slots=True)
class Event:
    """A ``Scheduled`` event (scheduler.go:214-233)."""

    message: str
    reason: str
    involved_pod: str
    namespace: str
    component: str
    count: int = 1
    type: str = "Normal"
    # Structured link identity for LinkDegraded/LinkQuarantined:
    # ``(src, dst, reason, streak)`` — a stable machine-consumable
    # field the rebalancer (core/rebalance.py) and operators' kubectl
    # filters can key on instead of parsing the human message.
    # Empty for every non-link event; defaulted so existing
    # constructors and wire serializations are unchanged.
    link: tuple = ()


def link_event(src: str, dst: str, reason: str, streak: int,
               message: str, component: str) -> Event:
    """A LinkDegraded/LinkQuarantined Warning carrying the structured
    ``(src, dst, reason, streak)`` payload (ISSUE 12 satellite: the
    human message used to be the ONLY place the link identity lived,
    so no consumer could key on it)."""
    return Event(
        message=message,
        reason=reason,
        involved_pod="",
        namespace="default",
        component=component,
        type="Warning",
        link=(src, dst, reason, int(streak)),
    )


def scheduled_event(pod: Pod, node_name: str, component: str) -> Event:
    """Parity with the reference's event payload: ``Assigned pod X to Y``
    (scheduler.go:211)."""
    return Event(
        message=f"Assigned pod {pod.name} to {node_name}",
        reason="Scheduled",
        involved_pod=pod.name,
        namespace=pod.namespace,
        component=component,
    )


def failed_event(pod: Pod, component: str, why: str) -> Event:
    """Emitted when no feasible node exists — the reference silently
    bound to the empty string in this case (findBestNode returns ""
    when all priorities are 0-valued or the map is empty,
    scheduler.go:384-394)."""
    return Event(
        message=f"Failed to schedule pod {pod.name}: {why}",
        reason="FailedScheduling",
        involved_pod=pod.name,
        namespace=pod.namespace,
        component=component,
        type="Warning",
    )


__all__: Sequence[str] = ("Node", "Pod", "PodDisruptionBudget",
                          "Binding", "Event",
                          "scheduled_event", "failed_event",
                          "link_event")
