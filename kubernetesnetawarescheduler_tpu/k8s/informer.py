"""Informer/watch layer: pending-pod queue fed by ADD events.

Mirrors the reference's informer wiring (scheduler.go:161-187): a pod
ADD handler that enqueues pods with no node assignment and a matching
``spec.schedulerName`` (filter at scheduler.go:170), and a node ADD
handler.  Differences by design:

- bounded queue with an explicit overflow policy (the reference's
  ``chan *v1.Pod, 300`` silently *blocks the informer goroutine* when
  full, scheduler.go:129, :171);
- a resync path (:meth:`Informer.resync`) re-lists pending pods, so a
  restart does not strand pods the way the reference does (ADD-only,
  no UpdateFunc, no re-list; scheduler.go:165-173).
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, Sequence

from kubernetesnetawarescheduler_tpu.k8s.client import ClusterClient
from kubernetesnetawarescheduler_tpu.k8s.types import Node, Pod


class PodQueue:
    """Bounded FIFO of pending pods with batch pop.

    ``pop_batch`` drains up to ``max_batch`` pods — the batching the
    TPU path needs (the reference popped exactly one pod per cycle,
    scheduler.go:191).
    """

    def __init__(self, capacity: int = 300) -> None:
        self._capacity = capacity
        self._dq: collections.deque[Pod] = collections.deque()
        self._queued: set[str] = set()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self.dropped = 0
        self.duplicates = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._dq)

    @staticmethod
    def _key(pod: Pod) -> str:
        # Namespaced: same-named pods in different namespaces are
        # distinct (Binding is namespaced too).
        return f"{pod.namespace}/{pod.name}"

    def push(self, pod: Pod) -> bool:
        """Enqueue; returns False when full (counted as a drop) or when
        the pod is already queued (duplicate ADD delivery / resync
        overlap — counted separately)."""
        with self._not_empty:
            if self._key(pod) in self._queued:
                self.duplicates += 1
                return False
            if len(self._dq) >= self._capacity:
                self.dropped += 1
                return False
            self._dq.append(pod)
            self._queued.add(self._key(pod))
            self._not_empty.notify()
            return True

    def pop_batch(self, max_batch: int, timeout: float | None = None
                  ) -> list[Pod]:
        """Take up to ``max_batch`` pods; blocks up to ``timeout`` for
        the first one (None = non-blocking)."""
        with self._not_empty:
            if not self._dq and timeout:
                self._not_empty.wait(timeout)
            batch: list[Pod] = []
            while self._dq and len(batch) < max_batch:
                pod = self._dq.popleft()
                self._queued.discard(self._key(pod))
                batch.append(pod)
            return batch


class Informer:
    """Subscribes to a :class:`ClusterClient` and maintains the node
    list + pending-pod queue."""

    def __init__(self, client: ClusterClient, queue: PodQueue,
                 scheduler_name: str,
                 on_node: Callable[[Node], None] | None = None,
                 is_parked: Callable[[Pod], bool] | None = None) -> None:
        self._client = client
        self._queue = queue
        self._scheduler_name = scheduler_name
        self._on_node = on_node
        # Pods the scheduler is deliberately holding out of the queue
        # (e.g. preemptors awaiting victim confirmation): resync and
        # watch re-deliveries must not enqueue them early.
        self._is_parked = is_parked
        self._nodes: dict[str, Node] = {}
        self.resyncs = 0  # full pod re-lists (restart + relist audit)
        self._lock = threading.Lock()
        client.on_pod_added(self._handle_pod)
        client.on_node_added(self._handle_node)
        for node in client.list_nodes():
            self._handle_node(node)

    def _wants(self, pod: Pod) -> bool:
        # The reference's filter: unbound + addressed to us
        # (scheduler.go:170).
        if self._is_parked is not None and self._is_parked(pod):
            return False
        return (not pod.node_name
                and pod.scheduler_name == self._scheduler_name)

    def _handle_pod(self, pod: Pod) -> None:
        if self._wants(pod):
            self._queue.push(pod)

    def _handle_node(self, node: Node) -> None:
        with self._lock:
            self._nodes[node.name] = node
        if self._on_node is not None:
            self._on_node(node)

    def nodes(self) -> Sequence[Node]:
        with self._lock:
            return list(self._nodes.values())

    def resync(self) -> int:
        """Re-list pending pods into the queue (restart recovery);
        returns how many were enqueued."""
        count = 0
        for pod in self._client.list_pending_pods():
            if self._wants(pod) and self._queue.push(pod):
                count += 1
        self.resyncs += 1
        return count

    def reconcile_nodes(self, live_names) -> int:
        """Drop cached nodes absent from a full server listing.

        The node cache only ever GROWS through watch events; a
        node-DELETED missed during a watch gap leaves a ghost entry
        that ``nodes()`` keeps serving forever.  The relist audit
        passes the authoritative listing here; returns how many
        ghosts were pruned."""
        live = set(live_names)
        with self._lock:
            ghosts = [n for n in self._nodes if n not in live]
            for name in ghosts:
                del self._nodes[name]
        return len(ghosts)
