"""A real API-server :class:`~.client.ClusterClient` over stdlib HTTP.

The reference reaches Kubernetes through client-go: in-cluster config
(scheduler.go:144), a shared-informer watch on pods/nodes
(scheduler.go:161-187), POST Binding (scheduler.go:196-206) and POST
Event (scheduler.go:214-233).  This module provides the same four
touchpoints as a standalone daemon WITHOUT a kubernetes client
library — just ``http.client`` + ``ssl`` — so the core stays
dependency-free and the daemon runs in any pod with a ServiceAccount.

Scope: exactly what the scheduling path consumes (the contract in
:class:`~.client.ClusterClient`), not a general k8s client.  Watches
are plain ``?watch=true`` chunked streams decoded line-by-line;
reconnect-with-resourceVersion handles the API server closing them.

Pod/Node JSON is mapped into the framework's lightweight types:

- resource requests: sum over containers of ``spec.containers[].
  resources.requests`` (cpu/memory parsed with k8s quantity suffixes);
  net bandwidth from the ``netaware.io/bandwidth-gbps`` annotation.
- network peers: the ``netaware.io/peers`` annotation, a JSON object
  ``{"other-pod": relative_traffic}`` — the declarative replacement
  for the reference's pod-blind scoring (its ``prioritize`` ignored
  the pod entirely, scheduler.go:248).
- affinity groups: ``netaware.io/group``, ``netaware.io/affinity``,
  ``netaware.io/anti-affinity`` annotations (comma-separated), the
  hostname-topology reduction of inter-pod affinity the score kernel
  masks on.
- labels/taints/selectors: flattened to ``key=value`` strings for the
  encoder's interners.
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import ssl
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Mapping, Sequence

from kubernetesnetawarescheduler_tpu.k8s.client import (
    ClusterClient,
    NodeHandler,
    PodHandler,
)
from kubernetesnetawarescheduler_tpu.k8s.types import (
    Binding,
    Event,
    Node,
    Pod,
)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class _NodelayHTTPConnection(http.client.HTTPConnection):
    """``http.client`` leaves Nagle ON; with the request written as
    separate header/body sends and small JSON responses, a keep-alive
    POST round-trip stalls on the 40 ms delayed-ACK interaction —
    measured 22.7 binds/s per connection against an un-tuned Python
    server vs 4,800+ with TCP_NODELAY (tools/bind_budget.py).  Go's
    net/http (client-go AND kube-apiserver) sets TCP_NODELAY on every
    TCP connection, so this also matches the transport the reference
    actually ran on (scheduler.go:196-206 via client-go)."""

    def connect(self) -> None:
        super().connect()
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)


class _NodelayHTTPSConnection(http.client.HTTPSConnection):
    def connect(self) -> None:
        super().connect()
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)


class _StaleConnection(Exception):
    """A pooled keep-alive connection failed mid-request.  ``retryable``
    is False when the request may already have been applied server-side
    (sent non-GET) — the caller re-raises ``cause`` instead of blindly
    replaying."""

    def __init__(self, cause: Exception, retryable: bool) -> None:
        super().__init__(str(cause))
        self.cause = cause
        self.retryable = retryable


class _WatchExpired(Exception):
    """Internal: the server reported the watch resourceVersion stale
    (410 Gone) — reconnect from scratch."""


class ApiServerError(RuntimeError):
    """A non-2xx API-server response that is neither a 404 (KeyError)
    nor a 409 (ValueError).  Subclasses RuntimeError so every existing
    transient-error handler keeps working; ``status`` lets resilience
    code distinguish a browned-out control plane (5xx, 429) from a
    request the server understood and rejected (4xx)."""

    def __init__(self, message: str, status: int = 0) -> None:
        super().__init__(message)
        self.status = status


def _brownout_error(exc: BaseException) -> bool:
    """Does this exception say the control plane itself is unhealthy?
    5xx and 429 (server overloaded) count; connection-level failures
    count; 4xx semantic rejections do NOT — the server answered."""
    if isinstance(exc, ApiServerError):
        return exc.status >= 500 or exc.status == 429
    return isinstance(exc, (OSError, http.client.HTTPException))


class CircuitBreaker:
    """closed -> open -> half_open breaker over API-server health.

    ``record_failure`` within a sliding ``window_s`` trips the breaker
    at ``failure_threshold``; after ``cooldown_s`` the breaker offers
    HALF-OPEN (one probe's worth of traffic); a success there closes
    it, a failure re-opens it.  ``clock`` is injectable so chaos soaks
    can drive it on virtual time.  Thread-safe: the bind worker, watch
    threads and the cycle thread all record into it."""

    _CODES = {"closed": 0, "half_open": 1, "open": 2}

    def __init__(self, failure_threshold: int = 5,
                 window_s: float = 30.0, cooldown_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.failure_threshold = max(1, int(failure_threshold))
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures: list[float] = []
        self._opened_at = 0.0
        self.opens_total = 0
        self.failures_total = 0

    def _state_locked(self) -> str:
        if (self._state == "open"
                and self.clock() - self._opened_at >= self.cooldown_s):
            self._state = "half_open"
        return self._state

    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    @property
    def state_code(self) -> int:
        """0=closed, 1=half_open, 2=open (the gauge encoding)."""
        return self._CODES[self.state]

    def allow(self) -> bool:
        """May a request be attempted right now?  half_open allows
        (that IS the probe); open refuses."""
        return self.state != "open"

    def _open_locked(self, now: float) -> None:
        self._state = "open"
        self._opened_at = now
        self._failures.clear()
        self.opens_total += 1

    def record_success(self) -> None:
        # Successes do NOT erase the failure window while closed: a
        # 50%-failing server is still browned out, and interleaved
        # successes must not keep the breaker from tripping.  Only the
        # half-open probe's success clears state (the server answered
        # after a full cooldown).
        with self._lock:
            if self._state_locked() == "half_open":
                self._state = "closed"
                self._failures.clear()

    def record_failure(self) -> None:
        now = self.clock()
        with self._lock:
            self.failures_total += 1
            state = self._state_locked()
            if state == "open":
                return
            if state == "half_open":
                # The probe failed: straight back to open, fresh
                # cooldown.
                self._open_locked(now)
                return
            self._failures.append(now)
            cutoff = now - self.window_s
            self._failures = [t for t in self._failures if t >= cutoff]
            if len(self._failures) >= self.failure_threshold:
                self._open_locked(now)


class RetryBudget:
    """A shared per-cycle retry allowance: every retry across every
    call path draws from ONE pool, reset by the scheduler cycle via
    :meth:`begin_cycle`.  Bounds the worst-case added latency a
    browned-out API server can inject into one cycle (N retries total,
    not N per request)."""

    def __init__(self, per_cycle: int = 8) -> None:
        self.per_cycle = max(0, int(per_cycle))
        self._left = self.per_cycle
        self._lock = threading.Lock()
        self.retries_total = 0
        self.exhausted_total = 0

    def begin_cycle(self) -> None:
        with self._lock:
            self._left = self.per_cycle

    def take(self) -> bool:
        with self._lock:
            if self._left > 0:
                self._left -= 1
                self.retries_total += 1
                return True
            self.exhausted_total += 1
            return False


def backoff_delay(attempt: int, base_s: float = 0.05,
                  max_s: float = 2.0,
                  rand: Callable[[], float] | None = None) -> float:
    """Jittered exponential backoff: ``base * 2^attempt`` capped at
    ``max_s``, scaled by a uniform [0.5, 1.5) jitter so a fleet of
    retrying clients cannot re-synchronize into thundering herds."""
    if rand is None:
        import random

        rand = random.random
    ceiling = min(max_s, base_s * (2.0 ** max(0, attempt)))
    return ceiling * (0.5 + rand())


ANN_PEERS = "netaware.io/peers"
ANN_GROUP = "netaware.io/group"
ANN_AFFINITY = "netaware.io/affinity"
ANN_ANTI = "netaware.io/anti-affinity"
ANN_BANDWIDTH = "netaware.io/bandwidth-gbps"
ANN_PDB = "netaware.io/pdb-min-available"
ANN_SOFT_AFFINITY = "netaware.io/soft-affinity"


# -- k8s quantity parsing ---------------------------------------------

_SUFFIX = {
    "n": 1e-9, "u": 1e-6, "k": 1e3,
    "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18,
    "Ki": 2 ** 10, "Mi": 2 ** 20, "Gi": 2 ** 30, "Ti": 2 ** 40,
    "Pi": 2 ** 50, "Ei": 2 ** 60,
}


def parse_quantity(q: str | int | float) -> float:
    """Parse a k8s resource quantity (``500m``, ``2``, ``1Gi``,
    ``100n``) to a float in base units (cores for cpu, bytes for
    memory).  Unparseable input yields 0.0 — the watch is
    cluster-wide, and one pod with an exotic quantity must degrade
    only itself, not crash event delivery."""
    if isinstance(q, (int, float)):
        return float(q)
    s = str(q).strip()
    if not s:
        return 0.0
    try:
        if s.endswith("m"):
            return float(s[:-1]) / 1000.0
        for suf in ("Ki", "Mi", "Gi", "Ti", "Pi", "Ei"):
            if s.endswith(suf):
                return float(s[: -len(suf)]) * _SUFFIX[suf]
        if s[-1] in _SUFFIX:
            return float(s[:-1]) * _SUFFIX[s[-1]]
        return float(s)
    except ValueError:
        return 0.0


def _flatten(m: Mapping[str, str] | None) -> frozenset[str]:
    if not m:
        return frozenset()
    return frozenset(f"{k}={v}" for k, v in m.items())


def _preferred_node_terms(spec: Mapping) -> tuple:
    """Extract ``preferredDuringSchedulingIgnoredDuringExecution``
    nodeAffinity terms as ``((frozenset{"k=v", ...}, weight), ...)`` —
    the stanza the reference's own probe deployment used
    (netperfScript/deployment.yaml:17-26).

    Representable shapes (soft semantics, so anything else degrades
    score-neutrally by skipping the term):

    - every matchExpression a single-value ``In`` → one term ANDing
      all ``key=value`` labels (k8s: expressions within a term AND);
    - exactly one multi-value ``In`` expression → one term per value,
      same weight (k8s: values within an expression OR).
    """
    na = (spec.get("affinity") or {}).get("nodeAffinity") or {}
    out = []
    for term in na.get(
            "preferredDuringSchedulingIgnoredDuringExecution") or []:
        try:
            weight = float(term.get("weight", 0) or 0)
        except (TypeError, ValueError):
            continue
        exprs = (term.get("preference") or {}).get("matchExpressions") or []
        if not weight or not exprs:
            continue
        if all(e.get("operator") == "In" and e.get("key")
               and len(e.get("values") or []) == 1 for e in exprs):
            labels = frozenset(
                f"{e['key']}={e['values'][0]}" for e in exprs)
            out.append((labels, weight))
        elif (len(exprs) == 1 and exprs[0].get("operator") == "In"
              and exprs[0].get("key") and exprs[0].get("values")):
            key = exprs[0]["key"]
            out.extend((frozenset({f"{key}={v}"}), weight)
                       for v in exprs[0]["values"])
    return tuple(out)


_NS_OPS = frozenset({"In", "NotIn", "Exists", "DoesNotExist",
                     "Gt", "Lt"})


def _required_node_terms(spec: Mapping) -> tuple:
    """``requiredDuringSchedulingIgnoredDuringExecution`` nodeAffinity
    as ``((("In", key, (v, ...)), ...), ...)`` — OR'd nodeSelectorTerms
    of AND'd matchExpressions, the HARD sibling of
    :func:`_preferred_node_terms` (types.Pod.required_node_affinity).

    All six kube operators are representable: In/NotIn/Exists/
    DoesNotExist through the label-bit machinery, Gt/Lt through the
    encoder's numeric label table (single integer value, kube's
    contract).  Hard semantics, so MALFORMED input degrades CLOSED: a
    bad shape makes its TERM unsatisfiable (``("In", key, ())`` — the
    encoder maps empty-values In to the UNKNOWN sentinel) rather than
    being skipped, which would silently widen where the pod may land.
    ``matchFields`` (metadata.name matching) is unrepresentable."""
    na = (spec.get("affinity") or {}).get("nodeAffinity") or {}
    req = (na.get("requiredDuringSchedulingIgnoredDuringExecution")
           or {})
    out = []
    for term in req.get("nodeSelectorTerms") or []:
        exprs = []
        bad = False
        if term.get("matchFields"):
            bad = True
        for e in term.get("matchExpressions") or []:
            op = e.get("operator")
            key = e.get("key")
            values = tuple(str(v) for v in e.get("values") or ())
            if (op not in _NS_OPS or not key
                    or (op in ("In", "NotIn") and not values)
                    or (op in ("Exists", "DoesNotExist") and values)
                    or (op in ("Gt", "Lt") and len(values) != 1)):
                bad = True
                continue
            exprs.append((op, key, values))
        if bad:
            out.append((("In", "", ()),))  # unsatisfiable term
        elif exprs:
            out.append(tuple(exprs))
        # A term with no expressions at all matches nothing in k8s
        # (empty nodeSelectorTerm selects no objects) — dropping it is
        # OR-equivalent ONLY while another term survives; the
        # all-terms-empty case is handled below.
    if not out and (req.get("nodeSelectorTerms") or []):
        # Every term was empty: k8s semantics are "matches nowhere"
        # (the pod stays Pending), not "no constraint" — returning ()
        # here would degrade a hard constraint OPEN.
        out.append((("In", "", ()),))
    return tuple(out)


def _preferred_group_terms(spec: Mapping, ann: Mapping,
                           namespace: str = "default") -> tuple:
    """Soft pod-(anti-)affinity as ``(host_terms, zone_terms, defs)``
    — term banks of ``(("group", weight), ...)`` plus the selector
    definitions their group keys need registered.

    Two surfaces merge into the host bank: the native annotation
    ``netaware.io/soft-affinity`` (JSON ``{"group": weight}``, negative
    = preferred spreading), and the k8s ``podAffinity``/
    ``podAntiAffinity`` preferred stanzas with ``topologyKey:
    kubernetes.io/hostname``.  Zone-topologyKey preferred stanzas land
    in the zone bank (scored against zone-resident membership,
    ``score.soft_zone_scores``) — a node-scoped term would actively
    misscore them (full spread bonus for a different node in the SAME
    zone).  Arbitrary labelSelectors canonicalize via
    :func:`_selector_key_def` (membership is label-driven); only
    malformed selectors and foreign topologyKeys degrade
    score-neutrally (soft semantics)."""
    out = []
    zone_out = []
    defs: dict[str, tuple] = {}
    if ANN_SOFT_AFFINITY in ann:
        try:
            raw = json.loads(ann[ANN_SOFT_AFFINITY])
            # Built fully before extending: a malformed entry rejects
            # the WHOLE annotation (score-neutral), never half of it.
            # Bare group names are namespace-qualified like every
            # other annotation group surface (pod_from_json _nsq;
            # NS_SEP keeps qualified keys collision-free).
            def _q(g: str) -> str:
                if "/" in g:
                    head, tail = g.split("/", 1)
                    return f"{head}{NS_SEP}{tail}"
                return f"{namespace}{NS_SEP}{g}"

            parsed = [(_q(str(g)), float(v))
                      for g, v in raw.items()
                      if float(v)]  # weight-0 entries are no-ops
            out.extend(parsed)
        except (ValueError, TypeError, AttributeError):
            pass  # malformed annotation degrades score-neutrally
    aff = spec.get("affinity") or {}
    for kind, sign in (("podAffinity", 1.0), ("podAntiAffinity", -1.0)):
        for term in (aff.get(kind) or {}).get(
                "preferredDuringSchedulingIgnoredDuringExecution") or []:
            try:
                weight = float(term.get("weight", 0) or 0)
            except (TypeError, ValueError):
                continue
            pat = term.get("podAffinityTerm") or {}
            tk = pat.get("topologyKey")
            if tk not in (_HOST_KEY, _ZONE_KEY):
                continue
            scope = _term_ns_scope(pat, namespace)
            kd = (None if scope == "unrepresentable" else
                  _selector_key_def(pat.get("labelSelector") or {},
                                    ns_scope=scope))
            if not weight or kd is None:
                # Malformed selector: degrade score-neutrally (soft
                # semantics) — scoring a DIFFERENT group than the k8s
                # selector selects would misdirect the bias.
                continue
            group, sel_def = kd
            defs[group] = sel_def
            (out if tk == _HOST_KEY else zone_out).append(
                (group, sign * weight))
    return tuple(out), tuple(zone_out), defs


_SEL_OPS = frozenset({"In", "NotIn", "Exists", "DoesNotExist"})

# Reserved pseudo-label key carrying the pod's namespace for selector
# evaluation.  Kubernetes label keys are validated server-side to
# ``[A-Za-z0-9._-]`` names (optionally ``dns.prefix/``-qualified), so a
# NUL byte can never collide with — or be spoofed by — a real workload
# label (same trick as :data:`UNSAT_GROUP`).  ``pod_from_json`` injects
# ``\x00ns=<namespace>`` into every parsed pod's label set, and
# namespace-scoped selector defs carry an ``("In", "\x00ns", (...))``
# expression, so namespace scoping rides the ordinary
# ``selector_matches`` path with no schema change.
_NS_KEY = "\x00ns"
# Separator between a namespace qualifier and the group body in
# canonical group keys: ``<ns>\x00/<body>``.  A bare "/" would be
# ambiguous — label KEYS may legally carry a "dns.prefix/" (so the
# cluster-wide key for ``{team/app: x}`` is the string "team/app=x",
# which a "/"-separated qualifier for namespace "team" + ``app=x``
# would collide with, silently merging two different selectors into
# one group bit); no legal label key, value, or namespace contains a
# NUL byte.
NS_SEP = "\x00/"


def _selector_key_def(sel: Mapping, ns_scope: tuple | None = None
                      ) -> tuple[str, tuple] | None:
    """Canonicalize an ARBITRARY labelSelector to ``(group_key,
    selector_def)``, or ``None`` when malformed (an operator outside
    In/NotIn/Exists/DoesNotExist, a missing key, or a value list that
    contradicts the operator's arity).

    ``selector_def`` is the structure :func:`~...core.encode.
    selector_matches` evaluates against pod labels — the
    labelSelector-parity path: membership is decided by LABELS, no
    annotation opt-in (kube semantics; VERDICT.md round 2, missing #3
    and ADVICE.md medium #1).

    ``ns_scope`` is the namespace scope of the term this selector came
    from (VERDICT r3 missing #2 / ADVICE r3 medium): ``None`` means
    cluster-wide (all namespaces — kube's ``namespaceSelector: {}``),
    a tuple of names restricts membership to pods of those namespaces
    by injecting an ``In`` expression on :data:`_NS_KEY`.  Distinct
    scopes therefore canonicalize to DISTINCT group keys: a ``team-a``
    pod's term never shares a bit with the same labels in ``team-b``.

    Key convention: selectors reducible to an exact-label conjunction
    (``matchLabels`` plus single-value non-conflicting ``In``
    expressions) keep the sorted ``k=v[,k=v]`` key — cluster-wide
    scope keeps the legacy bare string (the SAME key the
    ``netaware.io/group`` annotation convention uses, so both
    membership surfaces share one bit); a single-namespace scope
    prefixes it as ``ns\\x00/k=v[,k=v]`` (:data:`NS_SEP` — a bare "/"
    would collide with cluster-wide keys whose label key carries a
    ``dns.prefix/``), matching how ``pod_from_json``
    namespace-qualifies annotation group names — so the bit sharing
    survives scoping.  Richer selectors and multi-namespace scopes get
    a canonical ``sel:`` key (the repr covers the ns expression).  An
    empty selector matches every pod (kube's empty-LabelSelector rule)
    under ``sel:any`` / ``ns\\x00/sel:any``."""
    match = dict(sel.get("matchLabels") or {})
    exprs = []
    for e in sel.get("matchExpressions") or []:
        op = e.get("operator")
        key = e.get("key")
        values = tuple(sorted(str(v) for v in e.get("values") or ()))
        if (op not in _SEL_OPS or not key
                or (op in ("In", "NotIn") and not values)
                or (op in ("Exists", "DoesNotExist") and values)):
            return None
        if (op == "In" and len(values) == 1
                and match.get(key, values[0]) == values[0]):
            match[key] = values[0]  # exact-match expression: fold
            continue
        exprs.append((str(op), str(key), values))
    ml = tuple(sorted((str(k), str(v)) for k, v in match.items()))
    exprs_t = tuple(sorted(exprs))
    ns_exprs = ()
    prefix = ""
    if ns_scope is not None:
        ns_t = tuple(sorted(str(n) for n in ns_scope))
        if not ns_t:
            return None  # empty scope selects nothing representable
        ns_exprs = (("In", _NS_KEY, ns_t),)
        if len(ns_t) == 1:
            prefix = f"{ns_t[0]}{NS_SEP}"
    if not exprs_t and (ns_scope is None or prefix):
        if not ml:
            return f"{prefix}sel:any", ((), ns_exprs)
        return (prefix + ",".join(f"{k}={v}" for k, v in ml),
                (ml, ns_exprs))
    full = (ml, tuple(sorted(exprs_t + ns_exprs)))
    return f"sel:{full!r}", full


def _term_ns_scope(term: Mapping, own_ns: str):
    """Resolve a ``podAffinityTerm``'s namespace scope, kube
    semantics (pkg/scheduler ``GetNamespaceLabelsSnapshot`` rules):

    - neither ``namespaces`` nor ``namespaceSelector`` → the pod's OWN
      namespace (the default the reference's probe placement leaned
      on, deployment.yaml:17-26, by delegating to stock kube);
    - ``namespaces: [...]`` → exactly those names;
    - ``namespaceSelector: {}`` (empty object) → ALL namespaces
      (returns ``None`` = cluster-wide, the pre-round-4 behavior);
    - a non-empty ``namespaceSelector`` needs Namespace-object labels
      this framework does not watch → ``"unrepresentable"`` (callers
      degrade per the affinity/anti contract).  A ``namespaces`` list
      alongside it would union with the selector's matches, which we
      cannot compute either.
    """
    nsel = term.get("namespaceSelector")
    if nsel is not None:
        if nsel.get("matchLabels") or nsel.get("matchExpressions"):
            return "unrepresentable"
        return None  # empty selector = all namespaces
    names = term.get("namespaces") or []
    if names:
        return tuple(sorted(str(n) for n in names))
    return (own_ns,)


_ZONE_KEY = "topology.kubernetes.io/zone"
_HOST_KEY = "kubernetes.io/hostname"
# Group name no real pod can carry (ANN_GROUP annotations are UTF-8
# text; a NUL byte never survives the API server): interning it yields
# a group bit that is present on no node/zone, so a required-affinity
# term we cannot represent makes the pod unschedulable (degrade
# CLOSED) instead of silently widening placement.
UNSAT_GROUP = "\x00unrepresentable"


def _required_group_terms(spec: Mapping, namespace: str = "default"
                          ) -> tuple:
    """``requiredDuringSchedulingIgnoredDuringExecution`` podAffinity /
    podAntiAffinity terms → ``(host_aff, host_anti, zone_aff,
    zone_anti)`` frozensets of group keys (the ``labelSelector
    .matchLabels`` reduction to the canonical sorted ``k=v[,k=v]``
    group string, matching ``netaware.io/group``).

    Scope/degradation contract:
    - Terms are NAMESPACE-scoped per kube semantics
      (:func:`_term_ns_scope`): default own-namespace, widened by
      ``namespaces:``/``namespaceSelector: {}``; a non-empty
      ``namespaceSelector`` is unrepresentable (no Namespace watch)
      and degrades like a malformed selector.
    - ``topologyKey: kubernetes.io/hostname`` terms land in the
      host-scoped sets, ``topology.kubernetes.io/zone`` in the
      zone-scoped ones.
    - ARBITRARY labelSelectors are representable: each canonicalizes
      to a selector-group (:func:`_selector_key_def`) whose membership
      the encoder evaluates against pod LABELS — no annotation opt-in
      (kube semantics).  Only malformed selectors and topologyKeys
      other than hostname/zone remain unrepresentable.
    - AFFINITY terms degrade CLOSED: an unrepresentable term
      contributes :data:`UNSAT_GROUP`, whose bit no resident carries —
      the pod stays unschedulable exactly where kube-scheduler could
      not have verified the constraint either.  Terms AND (the kernel
      subset-tests the union of term bits against resident groups),
      matching kube's all-terms join — so an UNSAT term keeps its
      CLOSED degradation even beside satisfiable terms.
    - ANTI-affinity terms are exact for any term count (every listed
      group is forbidden); an unrepresentable anti term drops OPEN,
      mirroring the interner-overflow direction for anti constraints
      (forbidding *everything* would be far harsher than kube).
    - Both degradations are counted in the returned ``degraded`` so
      the encoder emits the per-pod ConstraintDegraded event.
    - The first pod of a group with no live member gets kube's
      special-case waiver at ENCODE time (encoder
      ``_apply_first_pod_escape``) — required self-affinity no longer
      deadlocks the first replica.

    Returns ``(host_aff, host_anti, zone_aff, zone_anti, degraded,
    defs, detail)`` — ``defs`` maps each referenced group key to its
    selector definition for encoder registration; ``detail`` holds
    human-readable descriptions of each dropped term for the
    ConstraintDegraded event.
    """
    aff = spec.get("affinity") or {}
    host_aff, host_anti = set(), set()
    zone_aff, zone_anti = set(), set()
    degraded = 0
    detail: list[str] = []
    defs: dict[str, tuple] = {}
    for kind, is_anti in (("podAffinity", False), ("podAntiAffinity", True)):
        for term in (aff.get(kind) or {}).get(
                "requiredDuringSchedulingIgnoredDuringExecution") or []:
            tk = term.get("topologyKey")
            scope = _term_ns_scope(term, namespace)
            kd = (None if scope == "unrepresentable" else
                  _selector_key_def(term.get("labelSelector") or {},
                                    ns_scope=scope))
            if tk not in (_HOST_KEY, _ZONE_KEY) or kd is None:
                degraded += 1
                why = ("non-empty namespaceSelector (no Namespace "
                       "watch)" if scope == "unrepresentable"
                       else "malformed labelSelector" if kd is None
                       else f"unsupported topologyKey {tk!r}")
                detail.append(
                    f"required {kind} term dropped "
                    + ("OPEN (NOT enforced)" if is_anti
                       else "CLOSED (unsatisfiable)")
                    + f": {why}")
                if not is_anti:
                    (host_aff if tk != _ZONE_KEY else zone_aff).add(
                        UNSAT_GROUP)
                continue  # anti: degrade open (counted above)
            group, sel_def = kd
            defs[group] = sel_def
            target = {
                (False, _HOST_KEY): host_aff,
                (False, _ZONE_KEY): zone_aff,
                (True, _HOST_KEY): host_anti,
                (True, _ZONE_KEY): zone_anti,
            }[(is_anti, tk)]
            target.add(group)
    return (frozenset(host_aff), frozenset(host_anti),
            frozenset(zone_aff), frozenset(zone_anti), degraded, defs,
            tuple(detail))


def _spread_constraint(spec: Mapping, namespace: str = "default"
                       ) -> tuple[int, bool, str, dict]:
    """First zone-level ``topologySpreadConstraint`` as
    ``(maxSkew, hard, spread_group, defs)``; ``(0, True, "", {})`` =
    none.

    Scope notes: only ``topology.kubernetes.io/zone`` constraints are
    representable (hostname-level spreading is anti-affinity's job in
    this framework).  The counted pod set is the constraint's
    labelSelector, canonicalized to a selector-group
    (:func:`_selector_key_def`) scoped to the pod's OWN namespace —
    kube counts topology-spread members per namespace, always (no
    ``namespaces`` widening field exists on the constraint); a
    constraint WITHOUT a selector (or with a malformed one) falls
    back to the pod's own group (``spread_group == ""``).
    Unrepresentable constraints are skipped (degrade open)."""
    for c in spec.get("topologySpreadConstraints") or []:
        if c.get("topologyKey") != "topology.kubernetes.io/zone":
            continue
        try:
            skew = int(c.get("maxSkew", 0) or 0)
        except (TypeError, ValueError):
            continue
        if skew <= 0:
            continue
        hard = c.get("whenUnsatisfiable",
                     "DoNotSchedule") != "ScheduleAnyway"
        sel = c.get("labelSelector")
        if sel:
            kd = _selector_key_def(sel, ns_scope=(namespace,))
            if kd is not None:
                return skew, hard, kd[0], {kd[0]: kd[1]}
        return skew, hard, "", {}
    return 0, True, "", {}


def pod_from_json(obj: Mapping) -> Pod:
    """Map a v1.Pod JSON object to the framework :class:`Pod`."""
    meta = obj.get("metadata", {})
    spec = obj.get("spec", {})
    ann = meta.get("annotations") or {}

    # Effective pod request, kube-scheduler semantics:
    # max(sum(containers), max(initContainers)) + pod overhead.
    # Init containers run SEQUENTIALLY before the main ones, so the
    # node must fit whichever phase is larger, not their sum; sidecar
    # (restartable) init containers count like main containers.
    # spec.overhead is the RuntimeClass surcharge the scheduler must
    # reserve (kube adds it to the pod's effective request).
    cpu = mem = 0.0
    init_cpu = init_mem = 0.0
    sidecar_cpu = sidecar_mem = 0.0
    for c in spec.get("containers", []) or []:
        req = (c.get("resources") or {}).get("requests") or {}
        cpu += parse_quantity(req.get("cpu", 0))
        mem += parse_quantity(req.get("memory", 0))
    for c in spec.get("initContainers", []) or []:
        req = (c.get("resources") or {}).get("requests") or {}
        c_cpu = parse_quantity(req.get("cpu", 0))
        c_mem = parse_quantity(req.get("memory", 0))
        if c.get("restartPolicy") == "Always":  # sidecar: runs forever
            sidecar_cpu += c_cpu
            sidecar_mem += c_mem
        else:
            init_cpu = max(init_cpu, c_cpu + sidecar_cpu)
            init_mem = max(init_mem, c_mem + sidecar_mem)
    cpu = max(cpu + sidecar_cpu, init_cpu)
    mem = max(mem + sidecar_mem, init_mem)
    overhead = spec.get("overhead") or {}
    cpu += parse_quantity(overhead.get("cpu", 0))
    mem += parse_quantity(overhead.get("memory", 0))
    requests: dict[str, float] = {}
    if cpu:
        requests["cpu"] = cpu
    if mem:
        requests["mem"] = mem / 2 ** 30  # GiB, the Resource axis unit
    if ANN_BANDWIDTH in ann:
        try:
            requests["net"] = float(ann[ANN_BANDWIDTH])
        except ValueError:
            pass

    peers: dict[str, float] = {}
    if ANN_PEERS in ann:
        try:
            raw = json.loads(ann[ANN_PEERS])
            peers = {str(k): float(v) for k, v in raw.items()}
        except (ValueError, TypeError, AttributeError):
            peers = {}  # malformed annotation degrades to pod-blind

    tolerations = frozenset(
        f"{t.get('key', '')}={t.get('value', '')}"
        for t in spec.get("tolerations", []) or [] if t.get("key"))

    def _csv(key: str) -> frozenset[str]:
        v = ann.get(key, "")
        return frozenset(x.strip() for x in v.split(",") if x.strip())

    namespace = meta.get("namespace", "default")
    spread_skew, spread_hard, spread_group, spread_defs = \
        _spread_constraint(spec, namespace)
    (host_aff, host_anti, zone_aff, zone_anti, parse_degraded,
     req_defs, degraded_detail) = _required_group_terms(spec, namespace)
    soft_host_terms, soft_zone_terms, soft_defs = \
        _preferred_group_terms(spec, ann, namespace)
    selector_defs = {**req_defs, **soft_defs, **spread_defs}
    # Qualify peer references with the pod's own namespace (unless the
    # annotation already says "ns/name"): the pod cache and node_of()
    # are namespace-keyed, and a bare name would collide across
    # namespaces (same-named pods in staging/prod are routine).
    peers = {(k if "/" in k else f"{namespace}/{k}"): v
             for k, v in peers.items()}

    def _nsq(group: str) -> str:
        """Namespace-qualify a bare annotation group name (explicit
        ``ns/name`` opts into cross-namespace grouping, same
        convention as peers above; the canonical internal form uses
        :data:`NS_SEP` so the key can never collide with a
        cluster-wide key whose label carries a ``dns.prefix/``).
        Keeps the annotation surface and the namespace-scoped
        selector keys sharing one bit: selector ``app=db`` in team-a
        and annotation group ``app=db`` on a team-a pod both intern
        as ``team-a\\x00/app=db``."""
        if "/" in group:
            head, tail = group.split("/", 1)
            return f"{head}{NS_SEP}{tail}"
        return f"{namespace}{NS_SEP}{group}"

    return Pod(
        name=meta.get("name", ""),
        namespace=namespace,
        uid=meta.get("uid", "") or meta.get("name", ""),
        scheduler_name=spec.get("schedulerName", ""),
        node_name=spec.get("nodeName", "") or "",
        requests=requests,
        peers=peers,
        tolerations=tolerations,
        node_selector=_flatten(spec.get("nodeSelector")),
        # The \x00ns pseudo-label makes namespace scope visible to
        # selector_matches (see _NS_KEY) without a schema change.
        labels=(_flatten(meta.get("labels"))
                | frozenset({f"{_NS_KEY}={namespace}"})),
        required_node_affinity=_required_node_terms(spec),
        group=_nsq(ann.get(ANN_GROUP, "")) if ann.get(ANN_GROUP) else "",
        affinity_groups=frozenset(map(_nsq, _csv(ANN_AFFINITY)))
        | host_aff,
        anti_groups=frozenset(map(_nsq, _csv(ANN_ANTI))) | host_anti,
        zone_affinity_groups=zone_aff,
        zone_anti_groups=zone_anti,
        selector_defs=selector_defs,
        soft_node_affinity=_preferred_node_terms(spec),
        soft_group_affinity=soft_host_terms,
        soft_zone_affinity=soft_zone_terms,
        spread_maxskew=spread_skew,
        spread_hard=spread_hard,
        spread_group=spread_group,
        priority=float(spec.get("priority", 0) or 0),
        pdb_min_available=int(ann.get(ANN_PDB, 0) or 0),
        parse_degraded=parse_degraded,
        parse_degraded_detail=degraded_detail,
    )


def pdb_from_json(obj: Mapping):
    """Map a ``policy/v1`` PodDisruptionBudget JSON object to the
    framework type (``None`` for a malformed selector — an
    unenforceable PDB must not silently protect nothing; callers log
    it).  ``minAvailable``/``maxUnavailable`` accept ints and
    percentage strings, kube's two forms."""
    from kubernetesnetawarescheduler_tpu.k8s.types import (
        PodDisruptionBudget,
    )

    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    # A PDB protects pods of its OWN namespace only (policy/v1
    # semantics) — without the scope, same-labeled pods in other
    # namespaces would inflate the member count and let the preemption
    # planner evict below a real PDB's bound (ADVICE r3 medium).
    kd = _selector_key_def(
        spec.get("selector") or {},
        ns_scope=(meta.get("namespace", "default"),))
    if kd is None:
        return None

    def _bound(value):
        """(absolute, percent) from an int or "N%" string."""
        if value is None:
            return None, None
        if isinstance(value, str) and value.endswith("%"):
            try:
                return None, float(value[:-1])
            except ValueError:
                return None, None
        try:
            return int(value), None
        except (TypeError, ValueError):
            return None, None

    min_abs, min_pct = _bound(spec.get("minAvailable"))
    max_abs, max_pct = _bound(spec.get("maxUnavailable"))
    return PodDisruptionBudget(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid", "") or meta.get("name", ""),
        selector_key=kd[0],
        selector_def=kd[1],
        min_available=min_abs,
        min_available_pct=min_pct,
        max_unavailable=max_abs,
        max_unavailable_pct=max_pct,
    )


def node_from_json(obj: Mapping) -> Node:
    meta = obj.get("metadata", {})
    spec = obj.get("spec", {})
    status = obj.get("status", {})
    alloc = status.get("allocatable") or status.get("capacity") or {}
    labels = meta.get("labels") or {}
    capacity = {
        "cpu": parse_quantity(alloc.get("cpu", 0)),
        "mem": parse_quantity(alloc.get("memory", 0)) / 2 ** 30,
    }
    if ANN_BANDWIDTH in (meta.get("annotations") or {}):
        try:
            capacity["net"] = float(meta["annotations"][ANN_BANDWIDTH])
        except ValueError:
            pass
    ready = True
    for cond in status.get("conditions", []) or []:
        if cond.get("type") == "Ready":
            ready = cond.get("status") == "True"
    taints = frozenset(
        f"{t.get('key', '')}={t.get('value', '')}"
        for t in spec.get("taints", []) or [] if t.get("key"))
    return Node(
        name=meta.get("name", ""),
        capacity=capacity,
        labels=_flatten(labels),
        taints=taints,
        ready=ready,
        unschedulable=bool(spec.get("unschedulable", False)),
        zone=labels.get("topology.kubernetes.io/zone", ""),
        rack=labels.get("topology.kubernetes.io/rack", ""),
    )


# -- the client -------------------------------------------------------


class KubeClient(ClusterClient):
    """Standalone-daemon API-server client (stdlib HTTP only).

    ``base_url`` like ``https://10.0.0.1:443``; ``token``/``ca_file``
    default to the in-cluster ServiceAccount mount — the stdlib
    equivalent of ``rest.InClusterConfig()`` (scheduler.go:144).
    """

    def __init__(self, base_url: str | None = None,
                 token: str | None = None,
                 ca_file: str | None = None,
                 insecure: bool = False,
                 timeout: float = 30.0,
                 pool_size: int = 6) -> None:
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "not in-cluster (KUBERNETES_SERVICE_HOST unset) and "
                    "no base_url given")
            base_url = f"https://{host}:{port}"
        self.base_url = base_url.rstrip("/")
        # Bound ServiceAccount tokens rotate (~1h expiry; the kubelet
        # rewrites the mounted file): when no explicit token is given,
        # remember the path and re-read periodically instead of
        # pinning the boot-time value (client-go re-reads per request).
        self._token_path = ""
        self._token_read_at = 0.0
        if token is None:
            self._token_path = os.path.join(SA_DIR, "token")
            token = (open(self._token_path).read().strip()
                     if os.path.exists(self._token_path) else "")
        self._token = token
        scheme, rest = self.base_url.split("://", 1)
        self._host = rest
        self._tls = scheme == "https"
        if self._tls:
            if insecure:
                self._ctx = ssl._create_unverified_context()
            else:
                ca = ca_file or os.path.join(SA_DIR, "ca.crt")
                self._ctx = ssl.create_default_context(
                    cafile=ca if os.path.exists(ca) else None)
        else:
            self._ctx = None
        self._timeout = timeout
        self._lock = threading.RLock()
        # Pods are cached under "namespace/name" — bare names collide
        # across namespaces (PodQueue._key namespaces for the same
        # reason), and pod_from_json qualifies peer references to
        # match.
        self._pods: dict[str, Pod] = {}
        self._pod_handlers: list[PodHandler] = []
        self._node_handlers: list[NodeHandler] = []
        self._deleted_handlers: list[PodHandler] = []
        self._node_deleted_handlers: list[NodeHandler] = []
        self._pdb_handlers: list = []
        # At-most-once pod-gone delivery: a pod that reached a terminal
        # phase (MODIFIED) is released then, and its later DELETED
        # event must not release again.  Entries are removed when the
        # DELETED event arrives, so the set is bounded by pods that
        # completed but are not yet deleted from etcd.
        self._released_uids: set[str] = set()
        self._watchers: list[threading.Thread] = []
        self._stop = threading.Event()
        # A small pool of persistent keep-alive connections for
        # request/response calls (watches stream on their own
        # connections): fresh TCP+TLS handshakes per bind would undo
        # the batched-bind amortization, and round 1's SINGLE shared
        # connection serialized the whole batch — bind_p99 was
        # host-side wire latency x batch size.  bind_many/create_events
        # fan out over the pool with a persistent executor.
        self._pool_size = max(1, pool_size)
        self._pool_lock = threading.Lock()
        self._idle_conns: list[http.client.HTTPConnection] = []
        self._conn_sem = threading.BoundedSemaphore(self._pool_size)
        self._executor: ThreadPoolExecutor | None = None
        # Control-plane brownout resilience: list GETs retry with
        # jittered exponential backoff under a shared per-cycle budget;
        # every call path records outcomes into the breaker, whose
        # state the SchedulerLoop reads to enter degraded mode (binds
        # parked, scoring continues).  serve.py re-tunes these from
        # SchedulerConfig via configure_resilience.
        self.breaker = CircuitBreaker()
        self.retry_budget = RetryBudget()
        self._backoff_base_s = 0.05
        self._backoff_max_s = 2.0
        self._sleep = time.sleep  # injectable for tests
        self._gap_handlers: list[Callable[[str], None]] = []
        self.watch_gaps = 0
        # Bind POST concurrency, measured at the wire (r16): the
        # loop's bind_max_inflight bounds worker threads, the pool
        # bounds connections — this gauge proves the bound held where
        # the POSTs actually leave (bench bind_split.max_inflight).
        self.bind_posts_inflight = 0
        self.bind_posts_peak = 0
        self._bind_gauge_lock = threading.Lock()

    def configure_resilience(self, failure_threshold: int = 5,
                             window_s: float = 30.0,
                             cooldown_s: float = 5.0,
                             retry_budget: int = 8,
                             backoff_base_s: float = 0.05,
                             backoff_max_s: float = 2.0) -> None:
        """Re-tune breaker/backoff knobs (SchedulerConfig's
        breaker_* / api_* fields); replaces the default objects, so
        call before serving starts."""
        self.breaker = CircuitBreaker(failure_threshold, window_s,
                                      cooldown_s)
        self.retry_budget = RetryBudget(retry_budget)
        self._backoff_base_s = backoff_base_s
        self._backoff_max_s = backoff_max_s

    @staticmethod
    def pod_key(namespace: str, name: str) -> str:
        return f"{namespace}/{name}"

    # -- transport ----------------------------------------------------

    def _conn(self, timeout: float | None = None
              ) -> http.client.HTTPConnection:
        t = self._timeout if timeout is None else timeout
        if self._tls:
            return _NodelayHTTPSConnection(
                self._host, timeout=t, context=self._ctx)
        return _NodelayHTTPConnection(self._host, timeout=t)

    def _headers(self, extra: Mapping[str, str] | None = None) -> dict:
        if self._token_path:
            now = time.monotonic()
            if now - self._token_read_at > 60.0:
                self._token_read_at = now
                try:
                    self._token = open(self._token_path).read().strip()
                except OSError:
                    pass  # keep the last-known token
        h = {"Accept": "application/json"}
        if self._token:
            h["Authorization"] = f"Bearer {self._token}"
        if extra:
            h.update(extra)
        return h

    def _acquire_conn(self) -> http.client.HTTPConnection:
        self._conn_sem.acquire()
        with self._pool_lock:
            if self._idle_conns:
                return self._idle_conns.pop()
        return self._conn()

    def _release_conn(self,
                      conn: http.client.HTTPConnection | None) -> None:
        if conn is not None:
            with self._pool_lock:
                self._idle_conns.append(conn)
        self._conn_sem.release()

    def _ensure_executor(self) -> ThreadPoolExecutor:
        with self._pool_lock:
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=self._pool_size,
                    thread_name_prefix="kube-pool")
            return self._executor

    def _request(self, method: str, path: str, body: Mapping | None = None
                 ) -> Mapping:
        """One request over a pooled keep-alive connection.  Up to
        ``pool_size`` requests run concurrently; excess callers block
        on the semaphore."""
        conn = self._acquire_conn()
        try:
            try:
                return self._exchange(conn, method, path, body)
            except _StaleConnection as stale:
                # Keep-alive connection went stale (server closed it):
                # rebuild and retry.  Safe whenever the request never
                # left (send-phase failure) or the method is
                # idempotent; an already-SENT POST may have been
                # applied, and replaying it blind would dodge the
                # server's conflict detection — raise instead (the
                # bind path requeues and heals 409s against the watch
                # cache, core/loop.py _bind_all).
                try:
                    conn.close()
                except OSError:
                    pass
                conn = None
                if not stale.retryable:
                    raise stale.cause
                conn = self._conn()
                try:
                    return self._exchange(conn, method, path, body)
                except _StaleConnection as again:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    conn = None
                    raise again.cause
        finally:
            self._release_conn(conn)

    def _exchange(self, conn: http.client.HTTPConnection, method: str,
                  path: str, body: Mapping | None) -> Mapping:
        payload = json.dumps(body) if body is not None else None
        headers = self._headers(
            {"Content-Type": "application/json"} if payload else None)
        sent = False
        try:
            conn.request(method, path, body=payload, headers=headers)
            sent = True
            resp = conn.getresponse()
            data = resp.read()
        except (http.client.HTTPException, OSError) as exc:
            raise _StaleConnection(
                cause=exc,
                retryable=not (sent and method != "GET")) from exc
        if resp.status == 404:
            raise KeyError(f"{method} {path}: 404 {data[:200]!r}")
        if resp.status == 409:
            raise ValueError(f"{method} {path}: 409 {data[:200]!r}")
        if resp.status >= 300:
            raise ApiServerError(
                f"{method} {path}: {resp.status} {data[:200]!r}",
                status=resp.status)
        return json.loads(data) if data else {}

    def _get_with_retry(self, path: str) -> Mapping:
        """A list/read GET with brownout handling: outcomes feed the
        breaker; brownout-class failures (5xx/429/network) retry with
        jittered exponential backoff while the shared per-cycle budget
        and the breaker allow; semantic rejections propagate
        immediately.  GETs are idempotent, so replays are always
        safe."""
        attempt = 0
        while True:
            try:
                out = self._request("GET", path)
            except Exception as exc:  # noqa: BLE001 — classified below
                if not _brownout_error(exc):
                    # The server answered (404/409/other 4xx): healthy
                    # control plane, unhealthy request.
                    self.breaker.record_success()
                    raise
                self.breaker.record_failure()
                if not self.breaker.allow() \
                        or not self.retry_budget.take():
                    raise
                self._sleep(backoff_delay(attempt,
                                          self._backoff_base_s,
                                          self._backoff_max_s))
                attempt += 1
                continue
            self.breaker.record_success()
            return out

    def _record_write_outcome(self, exc: Exception | None) -> None:
        """Feed a write's outcome into the breaker.  Writes are never
        blindly replayed here (a sent POST may have been applied —
        the loop's requeue/409-heal machinery owns retries); the
        breaker only needs to LEARN from them."""
        if exc is None or not _brownout_error(exc):
            self.breaker.record_success()
        else:
            self.breaker.record_failure()

    # -- ClusterClient ------------------------------------------------

    def list_nodes(self) -> Sequence[Node]:
        obj = self._get_with_retry("/api/v1/nodes")
        return [node_from_json(it) for it in obj.get("items", [])]

    def list_pending_pods(self) -> Sequence[Pod]:
        obj = self._get_with_retry(
            "/api/v1/pods?fieldSelector=spec.nodeName%3D")
        pods = [pod_from_json(it) for it in obj.get("items", [])]
        with self._lock:
            for p in pods:
                self._pods[self.pod_key(p.namespace, p.name)] = p
        return pods

    def list_all_pods(self) -> Sequence[Pod]:
        obj = self._get_with_retry("/api/v1/pods")
        return [pod_from_json(it) for it in obj.get("items", [])]

    @staticmethod
    def _binding_body(binding: Binding) -> dict:
        return {
            "apiVersion": "v1",
            "kind": "Binding",
            "metadata": {"name": binding.pod_name},
            "target": {"apiVersion": "v1", "kind": "Node",
                       "name": binding.node_name},
        }

    def _record_bound(self, binding: Binding) -> None:
        with self._lock:
            pod = self._pods.get(
                self.pod_key(binding.namespace, binding.pod_name))
            if pod is not None:
                pod.node_name = binding.node_name

    def bind(self, binding: Binding) -> None:
        """POST the Binding subresource — the reference's exact call
        shape (scheduler.go:196-206)."""
        try:
            self._request(
                "POST",
                f"/api/v1/namespaces/{binding.namespace}/pods/"
                f"{binding.pod_name}/binding",
                body=self._binding_body(binding))
        except Exception as exc:
            self._record_write_outcome(exc)
            raise
        self._record_write_outcome(None)
        self._record_bound(binding)

    def _bind_one(self, binding: Binding) -> Exception | None:
        with self._bind_gauge_lock:
            self.bind_posts_inflight += 1
            if self.bind_posts_inflight > self.bind_posts_peak:
                self.bind_posts_peak = self.bind_posts_inflight
        try:
            self._request(
                "POST",
                f"/api/v1/namespaces/{binding.namespace}/pods/"
                f"{binding.pod_name}/binding",
                body=self._binding_body(binding))
            self._record_write_outcome(None)
            return None
        except Exception as exc:  # noqa: BLE001 — per-pod outcome
            self._record_write_outcome(exc)
            return exc
        finally:
            with self._bind_gauge_lock:
                self.bind_posts_inflight -= 1

    def bind_many(self, bindings: Sequence[Binding]
                  ) -> list[Exception | None]:
        """Batched bind fanned out over the connection pool: up to
        ``pool_size`` POSTs in flight at once on persistent keep-alive
        connections, per-pod outcomes in input order.  Round 1
        serialized the batch on one connection — bind latency scaled
        with batch size and was the dominant host-side cost at
        batch=128 (BENCH_r01 bind_p99 ~191 ms)."""
        if not bindings:
            return []
        if len(bindings) == 1 or self._pool_size == 1:
            out = [self._bind_one(b) for b in bindings]
        else:
            ex = self._ensure_executor()
            out = list(ex.map(self._bind_one, bindings))
        for binding, exc in zip(bindings, out):
            if exc is None:
                self._record_bound(binding)
        return out

    @staticmethod
    def _event_body(event: Event) -> dict:
        now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        metadata: dict = {"generateName": f"{event.involved_pod}."}
        link = getattr(event, "link", ())
        if link:
            # Structured link identity (LinkDegraded/LinkQuarantined):
            # a stable annotation consumers filter on (jsonpath /
            # field selectors) instead of parsing the human message.
            src, dst, reason, streak = link
            metadata["annotations"] = {
                "netaware.dev/link-src": str(src),
                "netaware.dev/link-dst": str(dst),
                "netaware.dev/link-reason": str(reason),
                "netaware.dev/link-streak": str(int(streak)),
            }
        return {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": metadata,
            "involvedObject": {
                "apiVersion": "v1", "kind": "Pod",
                "name": event.involved_pod,
                "namespace": event.namespace},
            "reason": event.reason,
            "message": event.message,
            "type": event.type,
            "count": event.count,
            "firstTimestamp": now,
            "lastTimestamp": now,
            "source": {"component": event.component},
        }

    def create_event(self, event: Event) -> None:
        """POST a v1.Event (scheduler.go:214-233); failures are
        swallowed — events are best-effort, never worth failing a
        bind over."""
        try:
            self._request(
                "POST", f"/api/v1/namespaces/{event.namespace}/events",
                body=self._event_body(event))
            self._record_write_outcome(None)
        except Exception as exc:  # noqa: BLE001 — best-effort, but a
            # 5xx here is still brownout evidence the breaker wants.
            self._record_write_outcome(exc)

    def create_events(self, events: Sequence[Event]) -> None:
        """Batched events over the connection pool, best-effort."""
        if not events:
            return
        if len(events) == 1 or self._pool_size == 1:
            for event in events:
                self.create_event(event)
            return
        ex = self._ensure_executor()
        list(ex.map(self.create_event, events))

    def delete_pod(self, name: str, namespace: str = "default",
                   grace_seconds: int | None = None) -> None:
        """DELETE the pod — the preemption eviction primitive.
        ``grace_seconds`` becomes DeleteOptions.gracePeriodSeconds so
        the kubelet can stop the victim cleanly (the watch delivers
        DELETED once termination completes)."""
        body = None
        if grace_seconds is not None:
            body = {"apiVersion": "v1", "kind": "DeleteOptions",
                    "gracePeriodSeconds": int(grace_seconds)}
        self._request(
            "DELETE", f"/api/v1/namespaces/{namespace}/pods/{name}",
            body=body)

    def node_of(self, pod_name: str) -> str:
        """``pod_name`` is a "namespace/name" key (pod_from_json
        qualifies peer references); a bare name falls back to the
        default namespace."""
        key = pod_name if "/" in pod_name else f"default/{pod_name}"
        with self._lock:
            pod = self._pods.get(key)
        if pod is None:
            raise KeyError(pod_name)
        return pod.node_name

    def get_pod(self, pod_name: str) -> Pod | None:
        key = pod_name if "/" in pod_name else f"default/{pod_name}"
        with self._lock:
            return self._pods.get(key)

    # -- watches (informer layer) -------------------------------------

    def on_watch_gap(self, handler: Callable[[str], None]) -> None:
        """Register ``handler(reason)`` for watch-gap detection: a
        stream whose resourceVersion had to be RESET (410 Gone /
        ERROR event, or a non-2xx watch response) may have lost
        events between the last delivery and the fresh watch — the
        SchedulerLoop answers with a full relist audit."""
        with self._lock:
            self._gap_handlers.append(handler)

    def _notify_watch_gap(self, reason: str) -> None:
        self.watch_gaps += 1
        with self._lock:
            handlers = list(self._gap_handlers)
        for h in handlers:
            try:
                h(reason)
            except Exception:  # noqa: BLE001 — a handler must not
                pass  # kill the watch thread

    def on_pod_added(self, handler: PodHandler) -> None:
        with self._lock:
            self._pod_handlers.append(handler)
        # Watch ALL pods (not just pending): completion/deletion of
        # bound pods must reach on_pod_deleted so usage accounting can
        # release — a pending-only field selector would hide those.
        self._ensure_watcher("/api/v1/pods?watch=true",
                             self._deliver_pod, name="pod-watch")

    def on_pod_deleted(self, handler: PodHandler) -> None:
        """Register for pod-gone events (DELETED, or MODIFIED into a
        terminal phase): the usage-release path the reference never
        had (it tracked no usage at all, scheduler.go:248)."""
        with self._lock:
            self._deleted_handlers.append(handler)
        self._ensure_watcher("/api/v1/pods?watch=true",
                             self._deliver_pod, name="pod-watch")

    def on_node_added(self, handler: NodeHandler) -> None:
        with self._lock:
            self._node_handlers.append(handler)
        self._ensure_watcher("/api/v1/nodes?watch=true",
                             self._deliver_node, name="node-watch")

    def on_node_deleted(self, handler: NodeHandler) -> None:
        """Node DELETED events (scale-down): round 1 dropped these,
        leaving deleted nodes node_valid=True forever — the scheduler
        kept binding pods to them (the API server accepts Bindings to
        nonexistent node names; the pods never run)."""
        with self._lock:
            self._node_deleted_handlers.append(handler)
        self._ensure_watcher("/api/v1/nodes?watch=true",
                             self._deliver_node, name="node-watch")

    def _deliver_pod(self, kind: str, obj: Mapping) -> None:
        if kind == "DELETED":
            pod = pod_from_json(obj)
            with self._lock:
                cached = self._pods.pop(
                    self.pod_key(pod.namespace, pod.name), None)
                gone = cached if cached is not None else pod
                already = gone.uid in self._released_uids
                self._released_uids.discard(gone.uid)
                handlers = list(self._deleted_handlers)
            # Prefer the cached view: a DELETED payload may already be
            # stripped, but release needs node_name + requests.
            if gone.node_name and not already:
                for h in handlers:
                    h(gone)
            return
        if kind not in ("ADDED", "MODIFIED"):
            return
        pod = pod_from_json(obj)
        phase = (obj.get("status") or {}).get("phase", "")
        terminal = phase in ("Succeeded", "Failed")
        with self._lock:
            self._pods[self.pod_key(pod.namespace, pod.name)] = pod
            pod_handlers = list(self._pod_handlers)
            deleted_handlers = list(self._deleted_handlers)
            if terminal and pod.node_name:
                if pod.uid in self._released_uids:
                    return  # already released on an earlier MODIFIED
                self._released_uids.add(pod.uid)
        if terminal and pod.node_name:
            # Terminal-but-not-yet-deleted: its usage is already free.
            for h in deleted_handlers:
                h(pod)
        elif not pod.node_name:
            for h in pod_handlers:
                h(pod)

    def on_pdb_changed(self, handler) -> None:
        """Watch ``policy/v1`` PodDisruptionBudgets:
        ``handler(pdb, deleted)`` per ADDED/MODIFIED/DELETED event —
        the real-PDB surface of the preemption planner (the
        annotation surface needs no watch)."""
        with self._lock:
            self._pdb_handlers.append(handler)
        self._ensure_watcher(
            "/apis/policy/v1/poddisruptionbudgets?watch=true",
            self._deliver_pdb, name="pdb-watch")

    def list_pdbs(self):
        doc = self._get_with_retry(
            "/apis/policy/v1/poddisruptionbudgets")
        out = []
        for item in doc.get("items", []) or []:
            pdb = pdb_from_json(item)
            if pdb is not None:
                out.append(pdb)
        return out

    def _deliver_pdb(self, kind: str, obj: Mapping) -> None:
        if kind not in ("ADDED", "MODIFIED", "DELETED"):
            return
        pdb = pdb_from_json(obj)
        if pdb is None:
            return  # malformed selector: unenforceable, skip
        with self._lock:
            handlers = list(self._pdb_handlers)
        for h in handlers:
            h(pdb, kind == "DELETED")

    def _deliver_node(self, kind: str, obj: Mapping) -> None:
        if kind == "DELETED":
            node = node_from_json(obj)
            with self._lock:
                handlers = list(self._node_deleted_handlers)
            for h in handlers:
                h(node)
            return
        if kind not in ("ADDED", "MODIFIED"):
            return
        node = node_from_json(obj)
        with self._lock:
            handlers = list(self._node_handlers)
        for h in handlers:
            h(node)

    def _ensure_watcher(self, path: str,
                        deliver: Callable[[str, Mapping], None],
                        name: str) -> None:
        with self._lock:
            if any(t.name == name and t.is_alive()
                   for t in self._watchers):
                return
            t = threading.Thread(target=self._watch_loop,
                                 args=(path, deliver), name=name,
                                 daemon=True)
            self._watchers.append(t)
            t.start()

    def _watch_loop(self, path: str,
                    deliver: Callable[[str, Mapping], None]) -> None:
        """One ``?watch=true`` chunked stream, reconnecting with the
        last seen resourceVersion — the client-go reflector's job
        (scheduler.go:161-187), minus the full re-list (the scheduler
        loop's periodic ``list_pending_pods`` resync covers missed
        events)."""
        rv = ""
        while not self._stop.is_set():
            conn = None
            try:
                # Watches idle legitimately between cluster events: a
                # request-sized read timeout would kill every quiet
                # stream.  ~5 min matches the API server's own watch
                # window; close() still interrupts via _stop checks.
                conn = self._conn(timeout=330.0)
                sep = "&" if "?" in path else "?"
                url = path + (f"{sep}resourceVersion={rv}" if rv else "")
                conn.request("GET", url, headers=self._headers())
                resp = conn.getresponse()
                if resp.status >= 300:
                    conn.close()
                    self._stop.wait(1.0)
                    if rv:
                        # Events between the tracked rv and the fresh
                        # watch may be lost — a gap, not a mere retry.
                        self._notify_watch_gap(
                            f"watch {path}: HTTP {resp.status}")
                    rv = ""  # stale resourceVersion: start fresh
                    continue
                buf = b""
                while not self._stop.is_set():
                    chunk = resp.read1(65536)
                    if not chunk:
                        break
                    buf += chunk
                    while b"\n" in buf:
                        line, buf = buf.split(b"\n", 1)
                        if not line.strip():
                            continue
                        try:
                            evt = json.loads(line)
                        except ValueError:
                            continue
                        kind = evt.get("type", "")
                        obj = evt.get("object", {})
                        if kind == "ERROR":
                            # Usually a 410 Gone Status after etcd
                            # compaction: the rv is stale.  Reset it so
                            # the reconnect starts a fresh watch
                            # instead of hot-looping on the same
                            # stale version forever.  This IS a gap:
                            # everything between the compacted rv and
                            # the fresh list is unseen.
                            rv = ""
                            self._notify_watch_gap(
                                f"watch {path}: ERROR/410 "
                                f"{obj.get('code', '')}")
                            raise _WatchExpired()
                        rv = (obj.get("metadata", {})
                              .get("resourceVersion", rv))
                        try:
                            deliver(kind, obj)
                        except Exception:  # noqa: BLE001 — one poison
                            continue  # object must not drop the rest
                conn.close()
                # Clean EOF: brief pause so a server that instantly
                # closes idle watches cannot drive a hot reconnect
                # loop.
                self._stop.wait(0.2)
            except _WatchExpired:
                pass  # reconnect immediately with a fresh rv
            except Exception:  # noqa: BLE001 — reconnect
                self._stop.wait(1.0)
            finally:
                if conn is not None:
                    try:
                        conn.close()
                    except OSError:
                        pass

    def close(self) -> None:
        self._stop.set()
        with self._pool_lock:
            executor, self._executor = self._executor, None
            idle, self._idle_conns = self._idle_conns, []
        if executor is not None:
            executor.shutdown(wait=False)
        for conn in idle:
            try:
                conn.close()
            except OSError:
                pass
