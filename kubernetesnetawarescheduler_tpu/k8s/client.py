"""Cluster clients: the API-server boundary.

:class:`ClusterClient` is the contract the scheduler core needs from
Kubernetes — the same four touchpoints the reference uses through
client-go: watch pods (scheduler.go:164-174), list nodes (:240), bind
(:196-206), create event (:214-233).

:class:`FakeCluster` is the in-memory implementation used by tests and
the benchmark harness (SURVEY.md 4: "a fake cluster state generator …
this is how we test multi-node without a cluster").  A real-cluster
client would speak to the API server via the extender shim; the core
never imports kubernetes libraries.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Sequence

from kubernetesnetawarescheduler_tpu.k8s.types import (
    Binding,
    Event,
    Node,
    Pod,
)

PodHandler = Callable[[Pod], None]
NodeHandler = Callable[[Node], None]


class ClusterClient:
    """Abstract API-server boundary."""

    def list_nodes(self) -> Sequence[Node]:
        raise NotImplementedError

    def on_pod_added(self, handler: PodHandler) -> None:
        """Register a pod ADD handler (informer AddFunc,
        scheduler.go:165-173)."""
        raise NotImplementedError

    def on_node_added(self, handler: NodeHandler) -> None:
        raise NotImplementedError

    def on_pod_deleted(self, handler: PodHandler) -> None:
        """Register for pod-gone notifications (deletion or terminal
        phase) so committed usage can be released.  Optional: the
        default is no signal (callers must then rely on periodic
        reconciliation)."""

    def on_node_deleted(self, handler: NodeHandler) -> None:
        """Register for node DELETED events (scale-down, decommission)
        so the encoder can free the slot.  Optional, like
        :meth:`on_pod_deleted`; callers must also reconcile against
        :meth:`list_nodes` periodically for events missed while
        down."""

    def on_pdb_changed(self, handler) -> None:
        """Register for PodDisruptionBudget changes:
        ``handler(pdb, deleted: bool)``.  Optional — the default is no
        signal (clients without policy/v1 access simply never feed the
        planner real PDB objects; the annotation surface still
        works)."""

    def on_watch_gap(self, handler) -> None:
        """Register ``handler(reason: str)`` for watch-gap detection —
        a dropped stream, a 410 Gone resourceVersion expiry, or any
        reconnect that could not resume from the last seen rv.  The
        scheduler answers a gap with a full relist audit
        (SchedulerLoop.relist_audit).  Optional, like
        :meth:`on_pod_deleted`: the default is no signal, and callers
        then rely on periodic reconciliation alone."""

    def list_pdbs(self):
        """All policy/v1 PodDisruptionBudgets, or ``None`` when the
        client cannot provide them (initial sync for restarts — watch
        events missed while down)."""
        return None

    def bind(self, binding: Binding) -> None:
        raise NotImplementedError

    def create_event(self, event: Event) -> None:
        raise NotImplementedError

    def bind_many(self, bindings: Sequence[Binding]
                  ) -> list[Exception | None]:
        """Batched bind: one outcome per binding (None = bound).

        Default delegates to :meth:`bind` per binding; implementations
        with per-call overhead (a lock, an HTTP round-trip) override to
        pay it once per batch.  A failure never aborts the batch."""
        out: list[Exception | None] = []
        for b in bindings:
            try:
                self.bind(b)
                out.append(None)
            except Exception as exc:  # noqa: BLE001 — per-pod outcome
                out.append(exc)
        return out

    def create_events(self, events: Sequence[Event]) -> None:
        """Batched event creation (best-effort, like the reference's
        fire-and-forget Events().Create, scheduler.go:214-233)."""
        for e in events:
            self.create_event(e)

    def bind_gang(self, bindings: Sequence[Binding]
                  ) -> list[Exception | None]:
        """All-or-nothing bind of one gang's bindings: on success every
        outcome is None; on ANY failure NO binding is left applied and
        each failed slot carries its exception (succeeded-then-undone
        slots carry None — the caller treats any non-None as a whole-
        gang failure).

        Default implementation for transports without a transactional
        surface (the real API server has none): bind sequentially and,
        on first failure, COMPENSATE by deleting the already-bound
        members (best-effort — kube cannot unbind, so deletion +
        controller-recreate is the rollback primitive).  In-memory
        clients override with a true validate-all-then-apply-all
        transaction."""
        out: list[Exception | None] = [None] * len(bindings)
        done: list[int] = []
        failed = False
        for i, b in enumerate(bindings):
            if failed:
                out[i] = RuntimeError("gang aborted: earlier member "
                                      "failed to bind")
                continue
            try:
                self.bind(b)
                done.append(i)
            except Exception as exc:  # noqa: BLE001 — per-slot outcome
                out[i] = exc
                failed = True
        if failed:
            for i in done:
                try:
                    self.delete_pod(bindings[i].pod_name,
                                    bindings[i].namespace)
                except Exception:  # noqa: BLE001 — best-effort undo
                    pass
        return out

    def list_pending_pods(self) -> Sequence[Pod]:
        """Re-listable pending pods — the recovery path the reference
        lacks (queued pods are lost on restart; it only ever enqueues
        on ADD events, scheduler.go:165-173)."""
        raise NotImplementedError

    def list_all_pods(self) -> Sequence[Pod] | None:
        """Every pod the API server knows (any phase), or None when
        the client cannot provide it.  Drives usage-ledger
        reconciliation: pods deleted while the daemon was down emit no
        watch event, so their committed usage must be detected by
        comparison against this listing."""
        return None

    def node_of(self, pod_name: str) -> str:
        """Node a pod is bound to ("" if pending).  Part of the core
        contract: peer-traffic scoring resolves placed peers through
        this (raises ``KeyError`` for unknown pods)."""
        raise NotImplementedError

    def get_pod(self, pod_name: str) -> Pod | None:
        """Full pod object (None if unknown) — the /bind path needs the
        real resource requests to account usage."""
        raise NotImplementedError

    def delete_pod(self, name: str, namespace: str = "default",
                   grace_seconds: int | None = None) -> None:
        """Delete a pod (the preemption eviction primitive).
        ``grace_seconds`` maps to DeleteOptions.gracePeriodSeconds
        where the transport supports it.  Raises ``KeyError`` when the
        pod is unknown."""
        raise NotImplementedError


class FakeCluster(ClusterClient):
    """In-memory cluster: nodes, pods, bindings, events.

    Thread-safe; pod/node additions fan out synchronously to registered
    handlers, mimicking informer delivery.
    """

    def __init__(self, bind_latency_s: float = 0.0,
                 api_concurrency: int = 8) -> None:
        # bind_latency_s emulates the API server round-trip per bind
        # POST; api_concurrency caps how many such calls proceed at
        # once (an API server handles concurrent requests — this is
        # what makes a pooled/concurrent client measurably faster than
        # a serial one in benchmarks).
        self.bind_latency_s = bind_latency_s
        self._api_sem = threading.BoundedSemaphore(max(1, api_concurrency))
        self._lock = threading.RLock()
        self._nodes: dict[str, Node] = {}
        self._pods: dict[str, Pod] = {}
        self.bindings: list[Binding] = []
        self.events: list[Event] = []
        self._pod_handlers: list[PodHandler] = []
        self._node_handlers: list[NodeHandler] = []
        self._deleted_handlers: list[PodHandler] = []
        self._node_deleted_handlers: list[NodeHandler] = []
        self._pdbs: dict[str, object] = {}
        self._pdb_handlers: list = []

    # -- population ---------------------------------------------------

    def add_node(self, node: Node) -> None:
        with self._lock:
            self._nodes[node.name] = node
            handlers = list(self._node_handlers)
        for h in handlers:
            h(node)

    def add_pod(self, pod: Pod) -> None:
        with self._lock:
            self._pods[pod.name] = pod
            handlers = list(self._pod_handlers)
        for h in handlers:
            h(pod)

    def add_pods(self, pods: Iterable[Pod]) -> None:
        for pod in pods:
            self.add_pod(pod)

    def delete_pod(self, name: str, namespace: str = "default",
                   grace_seconds: int | None = None) -> None:
        """Remove a pod and fan out to on_pod_deleted handlers.
        Real watches deliver DELETED for PENDING pods too (kubeclient
        does), and the loop's lifecycle cleanup — parked-queue purge,
        assume-cache eviction — depends on seeing them; round 5
        aligned this fake with that semantic (bound-only delivery let
        deleted-but-parked pods linger).  For never-bound pods the
        usage-release half is a no-op (uid-keyed ledger).
        ``grace_seconds`` is accepted for interface parity (deletion
        is immediate here)."""
        with self._lock:
            pod = self._pods.pop(name, None)
            handlers = list(self._deleted_handlers)
        if pod is None:
            raise KeyError(name)
        for h in handlers:
            h(pod)

    def add_pdb(self, pdb) -> None:
        """Upsert a PodDisruptionBudget (keyed by uid or name); fans
        out to on_pdb_changed handlers like a watch ADDED/MODIFIED."""
        with self._lock:
            self._pdbs[pdb.uid or pdb.name] = pdb
            handlers = list(self._pdb_handlers)
        for h in handlers:
            h(pdb, False)

    def remove_pdb(self, uid: str) -> None:
        with self._lock:
            pdb = self._pdbs.pop(uid, None)
            handlers = list(self._pdb_handlers)
        if pdb is not None:
            for h in handlers:
                h(pdb, True)

    def on_pdb_changed(self, handler) -> None:
        with self._lock:
            self._pdb_handlers.append(handler)

    def list_pdbs(self):
        with self._lock:
            return list(self._pdbs.values())

    def delete_node(self, name: str) -> None:
        """Remove a node (scale-down); fans out to on_node_deleted
        handlers.  Pods bound there are deleted too (the kubelet is
        gone; mirrors the API server's garbage collection)."""
        with self._lock:
            node = self._nodes.pop(name, None)
            node_handlers = list(self._node_deleted_handlers)
            doomed = [p.name for p in self._pods.values()
                      if p.node_name == name]
        if node is None:
            raise KeyError(name)
        for pod_name in doomed:
            try:
                self.delete_pod(pod_name)
            except KeyError:
                pass
        for h in node_handlers:
            h(node)

    # -- ClusterClient ------------------------------------------------

    def list_nodes(self) -> Sequence[Node]:
        with self._lock:
            return list(self._nodes.values())

    def on_pod_added(self, handler: PodHandler) -> None:
        with self._lock:
            self._pod_handlers.append(handler)

    def on_node_added(self, handler: NodeHandler) -> None:
        with self._lock:
            self._node_handlers.append(handler)

    def on_pod_deleted(self, handler: PodHandler) -> None:
        with self._lock:
            self._deleted_handlers.append(handler)

    def on_node_deleted(self, handler: NodeHandler) -> None:
        with self._lock:
            self._node_deleted_handlers.append(handler)

    def _bind_locked(self, binding: Binding) -> None:
        """Single-binding validation + apply; caller holds the lock.
        Shared by :meth:`bind` and :meth:`bind_many` so the two paths
        cannot drift."""
        pod = self._pods.get(binding.pod_name)
        if pod is None:
            raise KeyError(f"unknown pod {binding.pod_name}")
        if binding.node_name not in self._nodes:
            raise KeyError(f"unknown node {binding.node_name}")
        if pod.node_name:
            raise ValueError(
                f"pod {pod.name} already bound to {pod.node_name}")
        pod.node_name = binding.node_name
        self.bindings.append(binding)

    def _simulate_latency(self) -> None:
        if self.bind_latency_s > 0:
            import time

            with self._api_sem:
                time.sleep(self.bind_latency_s)

    def bind(self, binding: Binding) -> None:
        self._simulate_latency()
        with self._lock:
            self._bind_locked(binding)

    def create_event(self, event: Event) -> None:
        with self._lock:
            self.events.append(event)

    def bind_many(self, bindings: Sequence[Binding]
                  ) -> list[Exception | None]:
        if self.bind_latency_s > 0 and len(bindings) > 1:
            # Emulated-latency mode: per-binding round-trips proceed
            # concurrently up to api_concurrency, like a real API
            # server in front of a pooled client.
            from concurrent.futures import ThreadPoolExecutor

            def one(binding: Binding) -> Exception | None:
                self._simulate_latency()
                try:
                    with self._lock:
                        self._bind_locked(binding)
                    return None
                except (KeyError, ValueError) as exc:
                    return exc

            with ThreadPoolExecutor(max_workers=8) as ex:
                return list(ex.map(one, bindings))
        out: list[Exception | None] = []
        with self._lock:
            for binding in bindings:
                try:
                    self._bind_locked(binding)
                    out.append(None)
                except (KeyError, ValueError) as exc:
                    out.append(exc)
        return out

    def create_events(self, events: Sequence[Event]) -> None:
        with self._lock:
            self.events.extend(events)

    def bind_gang(self, bindings: Sequence[Binding]
                  ) -> list[Exception | None]:
        """True all-or-nothing transaction: validate EVERY binding
        under the lock, apply only when all pass.  On any failure
        nothing is mutated — no compensating deletes, no pod ever
        observable bound to a strict subset of its gang (the atomicity
        invariant the gang tests pin).  Duplicate pod names within one
        gang are rejected as a conflict (the second apply would
        double-bind)."""
        self._simulate_latency()
        with self._lock:
            out: list[Exception | None] = [None] * len(bindings)
            seen: set[str] = set()
            failed = False
            for i, b in enumerate(bindings):
                try:
                    pod = self._pods.get(b.pod_name)
                    if pod is None:
                        raise KeyError(f"unknown pod {b.pod_name}")
                    if b.node_name not in self._nodes:
                        raise KeyError(f"unknown node {b.node_name}")
                    if pod.node_name:
                        raise ValueError(
                            f"pod {pod.name} already bound to "
                            f"{pod.node_name}")
                    if b.pod_name in seen:
                        raise ValueError(
                            f"duplicate pod {b.pod_name} in gang")
                    seen.add(b.pod_name)
                except (KeyError, ValueError) as exc:
                    out[i] = exc
                    failed = True
            if failed:
                return out
            for b in bindings:
                self._bind_locked(b)
            return out

    def list_pending_pods(self) -> Sequence[Pod]:
        with self._lock:
            return [p for p in self._pods.values() if not p.node_name]

    def list_all_pods(self) -> Sequence[Pod]:
        with self._lock:
            return list(self._pods.values())

    # -- introspection ------------------------------------------------

    def pod(self, name: str) -> Pod:
        with self._lock:
            return self._pods[name]

    def get_pod(self, pod_name: str) -> Pod | None:
        with self._lock:
            return self._pods.get(pod_name)

    def node_of(self, pod_name: str) -> str:
        with self._lock:
            return self._pods[pod_name].node_name
