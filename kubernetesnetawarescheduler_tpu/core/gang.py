r"""Gang scheduling: all-or-nothing, network-topology-aware placement
of pod groups (TPU slice jobs).

The reference scheduler — and this repo until now — places pods one at
a time, which deadlocks multi-host slice jobs: a 16-pod job that gets
8 members placed hoards capacity forever while the other 8 wait for
nodes the first 8 are blocking.  Gang scheduling treats the JOB as the
placement unit (cf. arXiv:2208.12738, arXiv:2009.09523):

- Pods annotated with a pod-group (name + minMember + optional
  timeout) are GATED in :class:`GangRegistry` instead of scheduled —
  they leave the pending queue but bind nothing until every member
  has arrived.
- A complete gang is scored JOINTLY: a first pass places members with
  the normal batched kernel, a second pass re-scores every member row
  with a co-placement bias derived from the ``C[N, N]`` pairwise
  net-desirability matrix (:func:`gang_bias` — mean C column over the
  tentative member nodes, a vectorized gather; no Python loop over
  members), and whichever pass wins the group objective
  (:func:`intra_gang_pair_score` — members placed first, pairwise
  bandwidth second) is committed.
- The commit is ATOMIC: assume-all (encoder usage committed up front)
  then bind-all through :meth:`ClusterClient.bind_gang`; ANY member
  failure (409, node vanished, timeout) rolls back EVERY member, so
  the API server never holds a bound strict subset of a gang.

State machine (docs/ARCHITECTURE.md "Gang scheduling"):

    Pending -> Gated -> Assumed -> Bound
                  \         \-> RolledBack (-> Gated on retry)
                   \-> TimedOut (members requeued)

Host-side module: the registry is plain-Python bookkeeping on the
scheduler loop's cycle thread (plus watch-thread ``pod_gone`` calls,
hence the lock); the only device work is the two scoring helpers.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Sequence

import numpy as np

from kubernetesnetawarescheduler_tpu.config import SchedulerConfig
from kubernetesnetawarescheduler_tpu.k8s.types import Pod

# Gang phases (strings, not an Enum: they travel through JSON in the
# extender's /gangs response and the checkpoint meta unchanged).
PENDING = "Pending"          # first member seen, below minMember
GATED = "Gated"              # complete, waiting for a scheduling cycle
ASSUMED = "Assumed"          # usage committed, binds in flight
BOUND = "Bound"              # every member bound
ROLLED_BACK = "RolledBack"   # a member failed; every commit reversed
TIMED_OUT = "TimedOut"       # minMember never arrived in time


def gang_key_of(pod: Pod) -> str:
    """Canonical gang identity, ``namespace/pod-group`` — or "" for
    pods that schedule independently (no group, or minMember <= 1:
    a gang of one is just a pod)."""
    group = getattr(pod, "pod_group", "") or ""
    if not group:
        return ""
    if int(getattr(pod, "gang_min_member", 0) or 0) <= 1:
        return ""
    return f"{getattr(pod, 'namespace', 'default') or 'default'}/{group}"


@dataclasses.dataclass
class Gang:
    """One pod group's gate state."""

    key: str
    min_member: int
    deadline: float                 # monotonic; gate expiry
    members: dict[str, Pod] = dataclasses.field(default_factory=dict)
    phase: str = PENDING
    created: float = 0.0            # monotonic; first member arrival

    @property
    def complete(self) -> bool:
        return len(self.members) >= self.min_member


class GangRegistry:
    """Aggregates annotated pods into gangs and gates them until the
    whole group is admissible.

    Threading: ``admit``/``pop_ready``/``flush_timeouts`` run on the
    scheduling cycle thread; ``pod_gone`` arrives from the watch
    thread — all structural access holds ``_lock``.  Phase history for
    released gangs is kept (bounded) so the extender can answer phase
    queries about gangs that already resolved.
    """

    _HISTORY_MAX = 1024

    def __init__(self, cfg: SchedulerConfig,
                 now=time.monotonic) -> None:
        self.cfg = cfg
        self._now = now
        self._gangs: dict[str, Gang] = {}
        self._phase_history: dict[str, str] = {}
        self._lock = threading.Lock()
        # Observability counters (exposed via the extender /gangs).
        self.admitted = 0        # gangs that reached minMember
        self.bound = 0           # gangs fully bound
        self.rolled_back = 0     # gangs rolled back after a failure
        self.timed_out = 0       # gangs whose gate expired

    # -- gating ---------------------------------------------------------

    def admit(self, pod: Pod) -> list[Pod] | None:
        """Gate one annotated pod.  Returns the full member list when
        this pod COMPLETES its gang (the gang leaves the registry's
        gate and the caller owns scheduling it), else None (pod
        absorbed; not a gang pod is the caller's check via
        :func:`gang_key_of`)."""
        key = gang_key_of(pod)
        if not key:
            raise ValueError(f"pod {pod.name} carries no gang key")
        with self._lock:
            gang = self._gangs.get(key)
            if gang is None:
                timeout = (float(getattr(pod, "gang_timeout_s", 0.0)
                                 or 0.0)
                           or self.cfg.gang_timeout_s)
                now = self._now()
                gang = Gang(key=key,
                            min_member=int(pod.gang_min_member),
                            deadline=now + timeout, created=now)
                self._gangs[key] = gang
            # minMember may legitimately differ across members during
            # a rolling spec update; the LARGEST seen wins (gating on
            # the smaller could bind a subset of the new size).
            gang.min_member = max(gang.min_member,
                                  int(pod.gang_min_member))
            gang.members[pod.uid] = pod
            if not gang.complete:
                self._phase_history.pop(key, None)
                return None
            del self._gangs[key]
            gang.phase = GATED
            self._record_phase(key, GATED)
            self.admitted += 1
            return list(gang.members.values())

    def flush_timeouts(self) -> list[tuple[str, list[Pod]]]:
        """Expire incomplete gangs whose gate deadline passed.
        Returns ``(key, members)`` per expired gang; the caller emits
        FailedScheduling events and requeues the members (they re-gate
        with a fresh deadline on re-delivery)."""
        now = self._now()
        expired: list[tuple[str, list[Pod]]] = []
        with self._lock:
            for key, gang in list(self._gangs.items()):
                if now >= gang.deadline:
                    del self._gangs[key]
                    self._record_phase(key, TIMED_OUT)
                    self.timed_out += 1
                    expired.append((key, list(gang.members.values())))
        return expired

    def pod_gone(self, pod: Pod) -> None:
        """A gated member was deleted before its gang completed:
        drop it (and the gang entirely when it was the last member)."""
        key = gang_key_of(pod)
        if not key:
            return
        with self._lock:
            gang = self._gangs.get(key)
            if gang is None:
                return
            gang.members.pop(pod.uid, None)
            if not gang.members:
                del self._gangs[key]
                self._phase_history.pop(key, None)

    # -- phase bookkeeping (scheduling-side transitions) ---------------

    def note_assumed(self, key: str) -> None:
        self._record_phase(key, ASSUMED, lock=True)

    def note_bound(self, key: str) -> None:
        with self._lock:
            self._record_phase(key, BOUND)
            self.bound += 1

    def note_rolled_back(self, key: str) -> None:
        with self._lock:
            self._record_phase(key, ROLLED_BACK)
            self.rolled_back += 1

    def _record_phase(self, key: str, phase: str,
                      lock: bool = False) -> None:
        if lock:
            with self._lock:
                self._record_phase(key, phase)
            return
        self._phase_history[key] = phase
        while len(self._phase_history) > self._HISTORY_MAX:
            self._phase_history.pop(next(iter(self._phase_history)))

    def phase_of(self, key: str) -> str:
        """Current phase of a gang by ``namespace/name`` key, or ""
        for a gang this scheduler has never seen."""
        with self._lock:
            gang = self._gangs.get(key)
            if gang is not None:
                return gang.phase
            return self._phase_history.get(key, "")

    def snapshot(self) -> dict:
        """Extender/observability view: gated gangs + counters."""
        with self._lock:
            gated = {
                key: {"members": len(g.members),
                      "min_member": g.min_member,
                      "phase": g.phase,
                      "age_s": round(self._now() - g.created, 3)}
                for key, g in self._gangs.items()
            }
            return {
                "gated": gated,
                "recent": dict(self._phase_history),
                "counters": {"admitted": self.admitted,
                             "bound": self.bound,
                             "rolled_back": self.rolled_back,
                             "timed_out": self.timed_out},
            }


# ---------------------------------------------------------------------------
# Group objective: intra-gang pairwise net desirability via C[N, N].
# ---------------------------------------------------------------------------


def _net_normalizers(state):
    """The max-over-valid-pairs normalizers ``(bw_max, lat_max)`` —
    the SAME span :func:`core.score.net_cost_matrix` uses, so the
    gang bias is on the per-pod network term's scale."""
    import jax.numpy as jnp

    from kubernetesnetawarescheduler_tpu.core.score import _EPS

    pair_valid = (state.node_valid[:, None]
                  & state.node_valid[None, :])
    bw_max = jnp.maximum(
        jnp.max(jnp.where(pair_valid, state.bw, 0.0)), _EPS)
    lat_max = jnp.maximum(
        jnp.max(jnp.where(pair_valid, state.lat, 0.0)), _EPS)
    return bw_max, lat_max


def gang_bias(state, member_nodes: Sequence[int],
              cfg: SchedulerConfig):
    """Co-placement bias ``f32[N]`` for the joint re-scoring pass:
    ``gang_weight * mean_j C[n, m_j]`` over the gang's tentative
    member nodes ``m_j`` — how net-desirable node ``n`` is as a
    placement for ONE member given where the others currently sit.

    Computed as a column gather of the (never materialized) C matrix:
    ``C[:, idx] = w_bw * bw[:, idx]/bw_max - w_lat * lat[:, idx]/
    lat_max`` with the loopback pin (rows equal to a member's node
    get ``w_bw``) — linear in bw/lat, so gathering columns first is
    exact.  O(N * M) work and memory; no Python loop over members.
    """
    import jax.numpy as jnp

    idx = jnp.asarray(np.asarray(member_nodes, np.int32))
    bw_max, lat_max = _net_normalizers(state)
    cols_bw = state.bw[:, idx]                         # [N, M]
    cols_lat = state.lat[:, idx]
    c = (cfg.weights.peer_bw * cols_bw / bw_max
         - cfg.weights.peer_lat * cols_lat / lat_max)
    n = state.bw.shape[0]
    same = jnp.arange(n, dtype=jnp.int32)[:, None] == idx[None, :]
    c = jnp.where(same, cfg.weights.peer_bw, c)
    c = jnp.where(state.node_valid[:, None], c, 0.0)
    return jnp.float32(cfg.gang_weight) * jnp.mean(c, axis=1)


def intra_gang_pair_score(state, member_nodes: Sequence[int],
                          cfg: SchedulerConfig) -> float:
    """The group objective: ``sum_{i != j} C[n_i, n_j]`` over the
    chosen member nodes — the total pairwise net desirability of the
    gang's placement.  Member pairs sharing a node score the loopback
    pin (``w_bw``); only the self pair ``i == j`` is excluded.
    Vectorized [M, M] gather; unplaced members (index < 0) are
    skipped.  Returns a host float (used for pass selection, the
    oracle test, and the bench report)."""
    import jax.numpy as jnp

    nodes = np.asarray(member_nodes, np.int64)
    nodes = nodes[nodes >= 0]
    m = len(nodes)
    if m < 2:
        return 0.0
    idx = jnp.asarray(nodes.astype(np.int32))
    bw_max, lat_max = _net_normalizers(state)
    sub_bw = state.bw[idx][:, idx]                     # [M, M]
    sub_lat = state.lat[idx][:, idx]
    c = (cfg.weights.peer_bw * sub_bw / bw_max
         - cfg.weights.peer_lat * sub_lat / lat_max)
    same_node = idx[:, None] == idx[None, :]
    c = jnp.where(same_node, cfg.weights.peer_bw, c)
    off_diag = ~jnp.eye(m, dtype=bool)
    return float(jnp.sum(jnp.where(off_diag, c, 0.0)))


def mean_intra_gang_bw(bw: np.ndarray,
                       member_nodes: Sequence[int]) -> float:
    """Mean raw pairwise bandwidth (the bench's achieved-bandwidth
    metric) over a gang's member placements, against a GROUND-TRUTH
    bandwidth matrix (e.g. the one ``build_fake_cluster`` returns).
    Same-node member pairs talk over loopback, counted as the
    matrix's best link; unplaced members are skipped."""
    nodes = np.asarray(member_nodes, np.int64)
    nodes = nodes[nodes >= 0]
    m = len(nodes)
    if m < 2:
        return 0.0
    sub = np.asarray(bw)[np.ix_(nodes, nodes)].astype(np.float64)
    loop = float(np.max(bw))
    same = nodes[:, None] == nodes[None, :]
    sub = np.where(same, loop, sub)
    off = ~np.eye(m, dtype=bool)
    return float(sub[off].mean())


# ---------------------------------------------------------------------------
# Elastic realizations (r17): a gang may declare a FAMILY of acceptable
# physical shapes instead of one rigid member count.
# ---------------------------------------------------------------------------


def parse_gang_shapes(raw: str) -> tuple:
    """Parse a ``netaware/pod-group-shapes`` annotation into the
    canonical ``((member_count, priority), ...)`` family.

    Grammar: comma-separated ``count[:priority]`` terms, e.g.
    ``"8,4:0.5,2:0.2"`` — place all 8 members if feasible, else 4 at
    half desirability, else 2.  Priority defaults to 1.0 and must land
    in (0, 1]; counts must be positive integers.  Malformed input
    degrades to ``()`` (the rigid pre-r17 gang), matching how the
    extender treats malformed numeric gang annotations — never an
    exception on the watch path."""
    if not raw or not isinstance(raw, str):
        return ()
    out: dict[int, float] = {}
    try:
        for term in raw.split(","):
            term = term.strip()
            if not term:
                continue
            if ":" in term:
                cs, ps = term.split(":", 1)
                count, prio = int(cs), float(ps)
            else:
                count, prio = int(term), 1.0
            if count < 1 or not (0.0 < prio <= 1.0):
                return ()
            # Duplicate counts keep the HIGHEST declared priority.
            out[count] = max(out.get(count, 0.0), prio)
    except (ValueError, TypeError):
        return ()
    return tuple(sorted(out.items(), key=lambda kv: (-kv[0], kv[1])))


def gang_shapes_of(members: Sequence[Pod]) -> tuple:
    """The gang-level realization family: the union of every member's
    declared shapes (highest priority wins per count), clipped to the
    arrived member count, with the FULL shape always present at
    priority 1.0.  Returns ``((count, priority), ...)`` sorted by
    count descending — ``()``-equivalent families (only the full
    shape) return a 1-tuple the caller may treat as rigid."""
    n = len(members)
    fam: dict[int, float] = {n: 1.0}
    for pod in members:
        for count, prio in getattr(pod, "gang_shapes", ()) or ():
            count = int(count)
            if 1 <= count <= n and count != n:
                fam[count] = max(fam.get(count, 0.0), float(prio))
    return tuple(sorted(fam.items(), key=lambda kv: (-kv[0], kv[1])))


_REAL_JIT_CACHE: dict = {}


def realization_scores(state, nodes_stack: np.ndarray,
                       valid_stack: np.ndarray,
                       cfg: SchedulerConfig) -> np.ndarray:
    """Score S candidate realizations in ONE padded/vmapped dispatch.

    ``nodes_stack`` is ``i32[S, M]`` member node indices (padded with
    -1), ``valid_stack`` ``bool[S, M]`` marking live members.  Returns
    ``f64[S]`` — each row's :func:`intra_gang_pair_score` (identical
    math: pairwise C over valid members, loopback pin for co-placed
    pairs, self-pairs excluded), so per-shape and cross-shape
    comparisons share one scale.  The kernel is jitted once per padded
    ``(S, M)`` shape; S and M are padded to powers of two to bound
    retraces across gangs of different widths."""
    import jax
    import jax.numpy as jnp

    from kubernetesnetawarescheduler_tpu.core.score import _EPS

    s, m = nodes_stack.shape
    sp = 1 << max(0, (s - 1).bit_length())
    mp = 1 << max(1, (m - 1).bit_length())
    nodes = np.full((sp, mp), -1, np.int32)
    valid = np.zeros((sp, mp), bool)
    nodes[:s, :m] = nodes_stack
    valid[:s, :m] = valid_stack & (nodes_stack >= 0)

    key = (sp, mp)
    fn = _REAL_JIT_CACHE.get(key)
    if fn is None:
        def impl(bw, lat, node_valid, nodes, valid, w_bw, w_lat):
            pair_valid = node_valid[:, None] & node_valid[None, :]
            bw_max = jnp.maximum(
                jnp.max(jnp.where(pair_valid, bw, 0.0)), _EPS)
            lat_max = jnp.maximum(
                jnp.max(jnp.where(pair_valid, lat, 0.0)), _EPS)
            eye = jnp.eye(nodes.shape[1], dtype=bool)

            def one(nd, vd):
                idx = jnp.clip(nd, 0, bw.shape[0] - 1)
                sub_bw = bw[idx][:, idx]
                sub_lat = lat[idx][:, idx]
                c = (w_bw * sub_bw / bw_max
                     - w_lat * sub_lat / lat_max)
                same = idx[:, None] == idx[None, :]
                c = jnp.where(same, w_bw, c)
                ok = vd[:, None] & vd[None, :] & ~eye
                return jnp.sum(jnp.where(ok, c, 0.0))

            return jax.vmap(one)(nodes, valid)

        fn = jax.jit(impl)
        _REAL_JIT_CACHE[key] = fn
    scores = np.asarray(_block(fn(
        state.bw, state.lat, state.node_valid,
        jnp.asarray(nodes), jnp.asarray(valid),
        jnp.float32(cfg.weights.peer_bw),
        jnp.float32(cfg.weights.peer_lat))), np.float64)
    return scores[:s]


def realization_key(target: int, placed: int, priority: float,
                    score: float) -> tuple:
    """The realized-desirability ordering every shape decision uses:
    feasibility first (all ``target`` members placed), then
    priority-weighted placed count, then the pairwise net score.
    Strict ``>`` between keys is the "strictly improves" bar the
    reshape property test pins."""
    return (1 if placed == target else 0,
            float(priority) * placed, float(score))


def place_gang_shaped(state, batch, cfg: SchedulerConfig, static,
                      assign_fn, num_members: int, shapes: Sequence):
    """Shape-aware joint placement: run the two-pass C-matrix
    placement once per declared realization (each with the member rows
    beyond that shape's count masked infeasible through the assigner's
    ``{"raw", "ok"}`` static seam — same compiled executable every
    time, only mask values change), then score ALL candidate
    realizations in one padded/vmapped :func:`realization_scores`
    dispatch and return the winner under :func:`realization_key`.

    A realization of count ``k`` places the FIRST ``k`` members of the
    batch (members arrive name-sorted from the loop, so the prefix is
    deterministic).  Returns ``(assignment, chosen_count, info)``:
    the host assignment array for the whole batch, how many members
    the winning realization targets (0 = nothing feasible at any
    declared shape), and a debug dict for explain/flight records.
    With a single declared shape equal to the full member count this
    reduces EXACTLY to :func:`place_gang` (the bit-identical pre-r17
    path)."""
    import jax.numpy as jnp

    from kubernetesnetawarescheduler_tpu.core import assign as assign_lib

    shapes = [(int(c), float(p)) for c, p in shapes
              if 1 <= int(c) <= num_members]
    if not shapes:
        shapes = [(num_members, 1.0)]
    if len(shapes) == 1 and shapes[0][0] == num_members:
        a = place_gang(state, batch, cfg, static, assign_fn,
                       num_members)
        placed = int(np.sum(a[:num_members] >= 0))
        return a, (num_members if placed == num_members else 0), {
            "shapes_scored": 1, "chosen": num_members,
            "priority": shapes[0][1], "rigid": True}

    raw, ok = assign_lib._static_parts(state, batch, cfg, static)
    raw = jnp.asarray(raw)
    ok = jnp.asarray(ok)
    width = int(ok.shape[0])

    # candidates: (shape_idx, count, priority, assignment)
    candidates: list[tuple[int, int, float, np.ndarray]] = []
    for si, (count, prio) in enumerate(shapes):
        row_mask = np.zeros((width,), bool)
        row_mask[:count] = True
        okm = ok & jnp.asarray(row_mask)[:, None]
        st0 = {"raw": raw, "ok": okm}
        a0 = np.asarray(_block(assign_fn(state, batch, cfg, st0)))
        candidates.append((si, count, prio, a0))
        placed0 = a0[:count]
        if cfg.gang_weight > 0 and np.any(placed0 >= 0):
            bias = gang_bias(state, placed0[placed0 >= 0], cfg)
            st1 = {"raw": raw + bias[None, :].astype(raw.dtype),
                   "ok": okm}
            a1 = np.asarray(_block(assign_fn(state, batch, cfg, st1)))
            candidates.append((si, count, prio, a1))

    mmax = max(c for _, c, _, _ in candidates)
    nodes_stack = np.full((len(candidates), mmax), -1, np.int32)
    valid_stack = np.zeros((len(candidates), mmax), bool)
    for ci, (_, count, _, a) in enumerate(candidates):
        nodes_stack[ci, :count] = a[:count]
        valid_stack[ci, :count] = True
    scores = realization_scores(state, nodes_stack, valid_stack, cfg)

    best = None
    best_key = None
    for ci, (si, count, prio, a) in enumerate(candidates):
        placed = int(np.sum(a[:count] >= 0))
        key = realization_key(count, placed, prio, float(scores[ci]))
        # Strict >: ties keep the earlier candidate (declaration
        # order, pass 1 before pass 2) — same tie shape place_gang
        # uses between its two passes.
        if best_key is None or key > best_key:
            best, best_key = (ci, si, count, prio, a, placed), key
    ci, si, count, prio, a, placed = best
    chosen = count if placed == count else 0
    info = {"shapes_scored": len(shapes),
            "candidates": len(candidates), "chosen": chosen,
            "priority": prio, "score": float(scores[ci]),
            "rigid": False}
    return a, chosen, info


def place_gang(state, batch, cfg: SchedulerConfig, static, assign_fn,
               num_members: int):
    """Joint two-pass placement of one gang's member batch.

    Pass 1 places members with the normal assigner.  Pass 2 re-scores
    every member's row with :func:`gang_bias` built from pass 1's
    placements — injected through the assigner's ``{"raw", "ok"}``
    static seam, so conflict resolution (capacity, affinity, spread)
    still runs in full — and re-assigns.  The pass that wins the
    group objective (members placed first, then
    :func:`intra_gang_pair_score`) is returned.

    ``static`` is the caller's batch-invariant prep (may be None);
    ``assign_fn`` is the loop's jitted assigner.  Returns a host
    ``np.ndarray`` assignment for the batch (padded entries included;
    only the first ``num_members`` are the gang).
    """
    from kubernetesnetawarescheduler_tpu.core import assign as assign_lib

    a0 = np.asarray(_block(assign_fn(state, batch, cfg, static)))
    placed0 = a0[:num_members]
    if cfg.gang_weight <= 0 or not np.any(placed0 >= 0):
        return a0
    raw, ok = assign_lib._static_parts(state, batch, cfg, static)
    bias = gang_bias(state, placed0[placed0 >= 0], cfg)
    import jax.numpy as jnp

    biased = {"raw": raw + bias[None, :].astype(raw.dtype),
              "ok": jnp.asarray(ok)}
    a1 = np.asarray(_block(assign_fn(state, batch, cfg, biased)))
    placed1 = a1[:num_members]
    key0 = (int(np.sum(placed0 >= 0)),
            intra_gang_pair_score(state, placed0, cfg))
    key1 = (int(np.sum(placed1 >= 0)),
            intra_gang_pair_score(state, placed1, cfg))
    return a1 if key1 > key0 else a0


def _block(x):
    try:
        return x.block_until_ready()
    except AttributeError:
        return x
