"""Tiled Pallas score/filter kernel: streaming node tiles through VMEM.

This is the 5k-node scale path sketched in SURVEY.md §5 ("blockwise/
tiled Pallas kernel over the N axis, ring-attention-style streaming of
node tiles through VMEM").  The dense XLA kernel in
:mod:`~kubernetesnetawarescheduler_tpu.core.score` materializes the
``C[N, N]`` network-desirability matrix in HBM before the ``T @ C.T``
contraction; at N=5k that is an extra 100 MB write + read per cycle.
Here ``C`` never exists: each grid step loads one ``(bn, bk)`` tile of
the raw ``lat``/``bw`` matrices (the state the netperf pipeline
maintains — the reference's per-pair iperf3 files, scheduler.go:503-530,
generalized), forms the desirability tile in VMEM, feeds the MXU, and
accumulates into a VMEM scratch block.  The epilogue fuses everything
the reference did in separate passes — the metric vote
(scheduler.go:360-365), capacity fit, taint/selector/affinity
feasibility (delegated to stock k8s by the reference,
deployment.yaml:17-31) — into the final tile write, so the masked
``P×N`` score matrix is produced in a single HBM pass.

Numerics match :func:`~.score.score_pods` (same formula, f32
accumulation); tests compare the two on the CPU interpreter.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubernetesnetawarescheduler_tpu.config import SchedulerConfig
from kubernetesnetawarescheduler_tpu.core import score as score_lib
from kubernetesnetawarescheduler_tpu.core.score import NEG_INF, _EPS
from kubernetesnetawarescheduler_tpu.core.state import ClusterState, PodBatch

# Row layout of the packed per-node float array ``nodef[(2R + 2 padded
# to a multiple of 8), N]``: used[0..R), cap[R..2R), base score,
# node_valid.  Column layout of the packed per-pod arrays (bit fields
# are W-word masks, W = cfg.mask_words; each field occupies W
# consecutive slots; T = cfg.max_soft_terms):
#   podf[P, >=R+1+2T] = req[0..R), pod_valid, soft_sel_w[T],
#                       soft_grp_w[T], pad  (soft weights pre-zeroed
#                       for empty-bit terms, so the kernel never needs
#                       a nonempty check)
#   podi[P, >=(5+2T)W] = tol_bits[W], sel_bits[W], affinity_bits[W],
#                      anti_bits[W], group_bit[W],
#                      soft_sel_bits[T*W], soft_grp_bits[T*W], pad
# Row layout of the packed per-node int array ``nodei[>=4W, N]``:
#   taint_bits[W], label_bits[W], group_bits[W], resident_anti[W], pad.
_PARAMS = 8  # wbw, wlat, inv_bwmax, inv_latmax, wbal, eps, wsoft,
# row_offset (global node index of output row 0 — nonzero only inside
# the shard_map'd tp path, where each device owns a row shard)

from kubernetesnetawarescheduler_tpu.core.state import round_up as _round_up


def _net_accum(params_ref, t_ref, bw_ref, lat_ref, validk_ref, acc_ref,
               *, block_n: int, block_k: int, use_bfloat16: bool) -> None:
    """Shared per-grid-step net-score accumulation (both kernels).

    Builds the network-desirability tile C[j_tile, k_tile] in VMEM from
    the raw lat/bw tiles (C is never materialized in HBM — the point of
    the tiled path), diagonal pinned to the loopback optimum wbw (see
    score.net_cost_matrix), invalid peer columns zeroed (their T
    entries are zero too — belt & braces), then contracts the peer-node
    axis on the MXU into the accumulator.  bf16 inputs / f32
    accumulation is the standard MXU recipe; the exact path asks for
    HIGHEST so f32 isn't silently truncated to bf16 passes."""
    j = pl.program_id(1)
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    wbw = params_ref[0]
    wlat = params_ref[1]
    inv_bw = params_ref[2]
    inv_lat = params_ref[3]

    c = wbw * bw_ref[:] * inv_bw - wlat * lat_ref[:] * inv_lat
    # The diagonal pin compares GLOBAL node indices: row_offset shifts
    # output rows when this kernel instance owns only a tp shard of
    # the node axis (params[7] is 0 on the single-device path; node
    # counts stay far below f32's 2^24 exact-integer ceiling).
    row_offset = params_ref[7].astype(jnp.int32)
    rows = row_offset + j * block_n + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, block_k), 0)
    cols = k * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, block_k), 1)
    c = jnp.where(rows == cols, wbw, c)
    c = c * validk_ref[:]

    t_blk = t_ref[:]
    if use_bfloat16:
        t_blk, c = t_blk.astype(jnp.bfloat16), c.astype(jnp.bfloat16)
        precision = None
    else:
        precision = jax.lax.Precision.HIGHEST
    acc_ref[:] += jax.lax.dot_general(
        t_blk, c, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision)


def _soft_bonus(label_at, group_at, podf_ref, podi_ref, like, *,
                r_res: int, mw: int, soft_terms: int):
    """Shared soft-affinity epilogue term (score.soft_affinity_scores
    semantics; packers zero the weights of empty-bit terms).
    ``label_at(w)``/``group_at(w)`` abstract the two kernels' different
    node-side layouts; returns the UNscaled weighted sum."""
    soft = jnp.zeros_like(like)
    for t in range(soft_terms):
        sel_match = jnp.full(like.shape, True)
        grp_hit = jnp.full(like.shape, False)
        for w in range(mw):
            sbits = podi_ref[:, (5 + t) * mw + w:(5 + t) * mw + w + 1]
            gbits = podi_ref[
                :, (5 + soft_terms + t) * mw + w:
                (5 + soft_terms + t) * mw + w + 1]
            sel_match = sel_match & ((label_at(w) & sbits) == sbits)
            grp_hit = grp_hit | ((group_at(w) & gbits) != 0)
        wsel = podf_ref[:, r_res + 1 + t:r_res + 2 + t]
        wgrp = podf_ref[:, r_res + 1 + soft_terms + t:
                        r_res + 2 + soft_terms + t]
        soft += (jnp.where(sel_match, wsel, 0.0)
                 + jnp.where(grp_hit, wgrp, 0.0))
    return soft


def _tile_scores(params_ref, nodef_ref, nodei_ref, podf_ref, podi_ref,
                 acc, *, num_resources: int, mask_words: int,
                 soft_terms: int):
    """Final-tile masked score computation shared by :func:`_kernel`
    (which writes the (bp, bn) tile to HBM) and :func:`_winner_kernel`
    (which reduces it into the running per-pod winner pair WITHOUT the
    HBM write).  One implementation guarantees the fused winner is
    numerically identical to the unfused tile, not merely close."""
    r_res = num_resources
    eps = params_ref[5]
    wbal = params_ref[4]
    base = nodef_ref[2 * r_res:2 * r_res + 1, :]            # (1, bn)
    nvalid = nodef_ref[2 * r_res + 1:2 * r_res + 2, :] > 0.5
    pvalid = podf_ref[:, r_res:r_res + 1] > 0.5             # (bp, 1)

    fits = nvalid & pvalid
    bal = jnp.zeros_like(acc)
    for r in range(r_res):
        used_r = nodef_ref[r:r + 1, :]                      # (1, bn)
        cap_r = nodef_ref[r_res + r:r_res + r + 1, :]
        req_r = podf_ref[:, r:r + 1]                        # (bp, 1)
        fits = fits & (req_r <= cap_r - used_r + eps)
        bal = jnp.maximum(
            bal, (used_r + req_r) / jnp.maximum(cap_r, eps))

    # W-word bit fields: subset/overlap tests accumulate over the
    # static word loop (unrolled at trace time).  Required affinity
    # is a subset test (terms AND, kube semantics) like the node
    # selector.
    mw = mask_words
    ok = fits
    for w in range(mw):
        taint = nodei_ref[w:w + 1, :]                    # (1, bn)
        label = nodei_ref[mw + w:mw + w + 1, :]
        group = nodei_ref[2 * mw + w:2 * mw + w + 1, :]
        ranti = nodei_ref[3 * mw + w:3 * mw + w + 1, :]
        tol = podi_ref[:, w:w + 1]                       # (bp, 1)
        sel = podi_ref[:, mw + w:mw + w + 1]
        aff = podi_ref[:, 2 * mw + w:2 * mw + w + 1]
        anti = podi_ref[:, 3 * mw + w:3 * mw + w + 1]
        gbit = podi_ref[:, 4 * mw + w:4 * mw + w + 1]
        ok = ok & ((taint & ~tol) == 0)
        ok = ok & ((label & sel) == sel)
        ok = ok & ((group & anti) == 0)
        ok = ok & ((ranti & gbit) == 0)
        ok = ok & ((group & aff) == aff)

    # Soft (preferred) affinity: weighted bonuses, fused into the
    # same tile write.
    soft = _soft_bonus(
        lambda w: nodei_ref[mw + w:mw + w + 1, :],
        lambda w: nodei_ref[2 * mw + w:2 * mw + w + 1, :],
        podf_ref, podi_ref, acc,
        r_res=r_res, mw=mw, soft_terms=soft_terms)

    return jnp.where(
        ok, acc + base + params_ref[6] * soft - wbal * bal,
        jnp.float32(float(NEG_INF)))


def _kernel(params_ref, t_ref, bw_ref, lat_ref, validk_ref, nodef_ref,
            nodei_ref, podf_ref, podi_ref, out_ref, acc_ref, *,
            block_n: int, block_k: int, num_resources: int,
            mask_words: int, soft_terms: int, use_bfloat16: bool):
    k = pl.program_id(2)
    nk = pl.num_programs(2)
    _net_accum(params_ref, t_ref, bw_ref, lat_ref, validk_ref, acc_ref,
               block_n=block_n, block_k=block_k,
               use_bfloat16=use_bfloat16)

    @pl.when(k == nk - 1)
    def _epilogue():
        out_ref[:] = _tile_scores(
            params_ref, nodef_ref, nodei_ref, podf_ref, podi_ref,
            acc_ref[:], num_resources=num_resources,
            mask_words=mask_words, soft_terms=soft_terms)


# Sentinel node index for the fused winner's min-index-of-max: larger
# than any global node index (row_offset included), so an all-masked
# tile can never contribute a real-looking index.
_WINNER_SENTINEL = 2 ** 30


def _winner_kernel(params_ref, t_ref, bw_ref, lat_ref, validk_ref,
                   nodef_ref, nodei_ref, podf_ref, podi_ref,
                   best_ref, node_ref, acc_ref, *,
                   block_n: int, block_k: int, num_resources: int,
                   mask_words: int, soft_terms: int,
                   use_bfloat16: bool):
    """:func:`_kernel` with the winner reduction fused in: instead of
    writing each (bp, bn) score tile to HBM, every pod row carries a
    running ``(best_score, best_node)`` pair across the node-tile axis
    ``j`` — the output BlockSpecs map every ``(j, k)`` step to block
    ``(i, 0)``, so the pair stays VMEM-resident for the whole row
    sweep (the revisited-output-block reduction pattern) and the P×N
    score plane never exists in HBM.

    Tie-break contract (score.winner_from_scores): lowest node index
    among equal-best candidates.  Within a tile that is the
    min-index-of-max; across tiles the update takes a later tile only
    on STRICTLY greater score — earlier ``j`` means lower global node
    indices, so ties keep the earlier tile's winner.  Global indices
    (``row_offset`` from params[7]) make the same kernel correct under
    the shard_map'd tp path, where each instance owns a row shard."""
    j = pl.program_id(1)
    k = pl.program_id(2)
    nk = pl.num_programs(2)
    _net_accum(params_ref, t_ref, bw_ref, lat_ref, validk_ref, acc_ref,
               block_n=block_n, block_k=block_k,
               use_bfloat16=use_bfloat16)

    @pl.when(k == nk - 1)
    def _reduce():
        s = _tile_scores(
            params_ref, nodef_ref, nodei_ref, podf_ref, podi_ref,
            acc_ref[:], num_resources=num_resources,
            mask_words=mask_words, soft_terms=soft_terms)
        row_offset = params_ref[7].astype(jnp.int32)
        cols = (row_offset + j * block_n
                + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1))
        tile_best = jnp.max(s, axis=1, keepdims=True)       # (bp, 1)
        tile_node = jnp.min(
            jnp.where(s == tile_best, cols,
                      jnp.int32(_WINNER_SENTINEL)),
            axis=1, keepdims=True)
        # Lane-broadcast to the (bp, 128) output blocks: a (bp, 1)
        # store would fight the lane tiling; every lane carries the
        # same pair and the caller reads lane 0.
        tb = jnp.broadcast_to(tile_best, best_ref.shape)
        tn = jnp.broadcast_to(tile_node, node_ref.shape)

        @pl.when(j == 0)
        def _init():
            best_ref[:] = tb
            node_ref[:] = tn

        @pl.when(j > 0)
        def _update():
            prev = best_ref[:]
            better = tb > prev
            best_ref[:] = jnp.where(better, tb, prev)
            node_ref[:] = jnp.where(better, tn, node_ref[:])


def _static_kernel(params_ref, t_ref, bw_ref, lat_ref, validk_ref,
                   nodes_ref, nodei_ref, groups_ref, podf_ref, podi_ref,
                   raw_ref, ok_ref, acc_ref, *,
                   block_n: int, block_k: int, num_resources: int,
                   mask_words: int, soft_terms: int, use_bfloat16: bool):
    """Batch-invariant slice of :func:`_kernel` for the assign/replay
    seam (assign._static_parts): raw score = net(T@C) + base + soft,
    plus the placement-independent feasibility mask (validity, taints,
    node selectors).  Capacity fit, group (anti-)affinity and the
    balance penalty stay OUTSIDE — they mutate per conflict-resolution
    round, so the round loop recomputes them against this raw.

    Node-side layouts (packed by :func:`static_replay_pack`, compact —
    no used/cap/resident_anti rows, this kernel never reads them):
    ``nodes_ref`` rows 0=base, 1=valid; ``nodei_ref`` rows
    taint[0..W), label[W..2W).  ``groups_ref`` (rows group_bits[W]) is
    the one PER-BATCH node-side input: the soft group term scores
    against batch-entry residency, which prior batches' commits move.
    """
    k = pl.program_id(2)
    nk = pl.num_programs(2)
    _net_accum(params_ref, t_ref, bw_ref, lat_ref, validk_ref, acc_ref,
               block_n=block_n, block_k=block_k,
               use_bfloat16=use_bfloat16)

    @pl.when(k == nk - 1)
    def _epilogue():
        r_res = num_resources
        base = nodes_ref[0:1, :]
        nvalid = nodes_ref[1:2, :] > 0.5
        pvalid = podf_ref[:, r_res:r_res + 1] > 0.5

        mw = mask_words
        ok = nvalid & pvalid
        for w in range(mw):
            taint = nodei_ref[w:w + 1, :]
            label = nodei_ref[mw + w:mw + w + 1, :]
            tol = podi_ref[:, w:w + 1]
            sel = podi_ref[:, mw + w:mw + w + 1]
            ok = ok & ((taint & ~tol) == 0)
            ok = ok & ((label & sel) == sel)

        soft = _soft_bonus(
            lambda w: nodei_ref[mw + w:mw + w + 1, :],
            lambda w: groups_ref[w:w + 1, :],
            podf_ref, podi_ref, acc_ref[:],
            r_res=r_res, mw=mw, soft_terms=soft_terms)

        raw_ref[:] = acc_ref[:] + base + params_ref[6] * soft
        ok_ref[:] = ok.astype(jnp.float32)


def static_replay_pack(state: ClusterState, cfg: SchedulerConfig,
                       block_n: int = 128, block_k: int = 128):
    """Batch-invariant device arrays for :func:`static_scores_tiled`,
    computed ONCE per replay/serving window: params (weights + global
    normalizers), padded bw/lat (the O(N²) copies that must NOT happen
    per scan step), the valid-row, and the compact static node arrays.
    Everything placements can change is excluded — per batch only the
    pod-side arrays and the group-bits rows are packed."""
    import math

    n_real = state.num_nodes
    base, bw_max, lat_max = static_tile_inputs(state, cfg)
    n_pad = _round_up(n_real, math.lcm(block_n, block_k))
    mw = cfg.mask_words

    def pad2(x):
        return jnp.pad(x, ((0, n_pad - x.shape[0]),
                           (0, n_pad - x.shape[1])))

    params = jnp.stack([
        jnp.float32(cfg.weights.peer_bw), jnp.float32(cfg.weights.peer_lat),
        1.0 / bw_max, 1.0 / lat_max,
        jnp.float32(cfg.weights.balance), jnp.float32(_EPS),
        jnp.float32(cfg.weights.soft_affinity / 100.0), jnp.float32(0)])
    bw = pad2(state.bw)
    lat = pad2(state.lat)
    validf = state.node_valid.astype(jnp.float32)
    validk = jnp.pad(validf[None, :], ((0, 0), (0, n_pad - n_real)))
    nodes = jnp.zeros((8, n_pad), jnp.float32)
    nodes = nodes.at[0, :n_real].set(base)
    nodes = nodes.at[1, :n_real].set(validf)
    nodei = jnp.zeros((_round_up(2 * mw, 8), n_pad), jnp.int32)
    nodei = nodei.at[0:mw, :n_real].set(state.taint_bits.astype(jnp.int32).T)
    nodei = nodei.at[mw:2 * mw, :n_real].set(
        state.label_bits.astype(jnp.int32).T)
    return params, bw, lat, validk, nodes, nodei


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "block_p", "block_n", "block_k", "interpret"))
def static_scores_tiled(state: ClusterState, pods: PodBatch,
                        cfg: SchedulerConfig, static=None, *,
                        block_p: int = 128, block_n: int = 128,
                        block_k: int = 128, interpret: bool = False
                        ) -> tuple[jax.Array, jax.Array]:
    """``(raw f32[P, N], static_ok bool[P, N])`` for
    :func:`~.assign._static_parts` — the tiled-Pallas replacement for
    the dense path's ``base + T @ C.T + soft`` (which materializes
    ``C[N, N]`` in HBM).  ``static`` is a :func:`static_replay_pack`
    (packed with the SAME block sizes); the per-batch packing here is
    pod-sized plus one N×W group-bits transpose — no O(N²) work.
    Dynamic constraints (capacity, groups, balance) are intentionally
    absent — the conflict loop recomputes them per round."""
    p_real, n_real = pods.num_pods, state.num_nodes
    r_res = state.num_resources
    bp = min(block_p, _round_up(p_real, 8))
    p_pad = _round_up(p_real, bp)
    nb, kb = block_n, block_k
    mw = cfg.mask_words
    t_soft = cfg.max_soft_terms
    pf_cols = _round_up(r_res + 1 + 2 * t_soft, 8)
    pi_cols = _round_up((5 + 2 * t_soft) * mw, 8)

    if static is None:
        static = static_replay_pack(state, cfg, nb, kb)
    params, bw, lat, validk, nodes, nodei = static
    n_pad = bw.shape[0]
    ni_rows = nodei.shape[0]

    t = score_lib.peer_traffic_matrix(pods, n_real)
    t = jnp.pad(t, ((0, p_pad - p_real), (0, n_pad - n_real)))
    groups = pack_group_rows(state.group_bits, n_pad, mw)
    podf, podi = _pack_pod_inputs(pods, p_real, p_pad, r_res, mw,
                                  t_soft, pf_cols, pi_cols)
    raw, ok = _static_pallas_call(
        params, t, bw, lat, validk, nodes, nodei, groups, podf, podi,
        cfg=cfg, bp=bp, nb=nb, kb=kb, interpret=interpret)
    # Hard nodeAffinity matchExpressions and the soft zone term join
    # OUTSIDE the tile kernel (like the spread join in
    # score_pods_tiled): neither streams over the N×N matrices, and
    # both self-gate, so batches without them pay nothing on this
    # path.
    return (raw[:p_real, :n_real]
            + score_lib.soft_zone_scores(state, pods, cfg),
            (ok[:p_real, :n_real] > 0.5)
            & score_lib.ns_affinity_ok(state, pods))


def pack_group_rows(group_bits: jax.Array, n_pad: int,
                    mw: int) -> jax.Array:
    """Current node group-bits as kernel rows ``i32[~W, n_pad]`` — the
    one per-batch node-side input of the static kernel (soft group
    terms score against batch-entry residency)."""
    n_real = group_bits.shape[0]
    groups = jnp.zeros((8 * ((mw + 7) // 8), n_pad), jnp.int32)
    return groups.at[0:mw, :n_real].set(group_bits.astype(jnp.int32).T)


def _static_pallas_call(params, t, bw, lat, validk, nodes, nodei,
                        groups, podf, podi, *, cfg: SchedulerConfig,
                        bp: int, nb: int, kb: int, interpret: bool):
    """The raw static-kernel dispatch over already-packed arrays.

    Shapes may be non-square: ``bw``/``lat`` are
    ``[n_out_pad, n_k_pad]`` — the OUTPUT node axis (rows) can be one
    tp shard while the contraction axis (columns, the peer side) stays
    full, which is exactly the row-sharded layout the shard_map'd
    multi-chip path hands each device (params[7] then carries the
    shard's global row offset for the diagonal pin)."""
    p_pad = t.shape[0]
    n_out, n_k = bw.shape
    r_res = cfg.num_resources
    mw = cfg.mask_words
    t_soft = cfg.max_soft_terms
    pf_cols = podf.shape[1]
    pi_cols = podi.shape[1]
    ni_rows = nodei.shape[0]
    g_rows = groups.shape[0]
    grid = (p_pad // bp, n_out // nb, n_k // kb)
    kernel = functools.partial(_static_kernel, block_n=nb, block_k=kb,
                               num_resources=r_res, mask_words=mw,
                               soft_terms=t_soft,
                               use_bfloat16=cfg.use_bfloat16)
    return pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((p_pad, n_out), jnp.float32),
                   jax.ShapeDtypeStruct((p_pad, n_out), jnp.float32)],
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),                 # params
            pl.BlockSpec((bp, kb), lambda i, j, k: (i, k)),        # T
            pl.BlockSpec((nb, kb), lambda i, j, k: (j, k)),        # bw
            pl.BlockSpec((nb, kb), lambda i, j, k: (j, k)),        # lat
            pl.BlockSpec((1, kb), lambda i, j, k: (0, k)),         # validk
            pl.BlockSpec((8, nb), lambda i, j, k: (0, j)),         # nodes
            pl.BlockSpec((ni_rows, nb), lambda i, j, k: (0, j)),   # nodei
            pl.BlockSpec((g_rows, nb), lambda i, j, k: (0, j)),    # groups
            pl.BlockSpec((bp, pf_cols), lambda i, j, k: (i, 0)),   # podf
            pl.BlockSpec((bp, pi_cols), lambda i, j, k: (i, 0)),   # podi
        ],
        out_specs=[pl.BlockSpec((bp, nb), lambda i, j, k: (i, j)),
                   pl.BlockSpec((bp, nb), lambda i, j, k: (i, j))],
        scratch_shapes=[pltpu.VMEM((bp, nb), jnp.float32)],
        interpret=interpret,
    )(params, t, bw, lat, validk, nodes, nodei, groups, podf, podi)


def static_tile_inputs(state: ClusterState, cfg: SchedulerConfig):
    """The tiled kernel's batch-invariant prep: the per-node metric
    vote and the global bw/lat normalizers.  Analogous to
    :func:`~.score.static_node_scores` but WITHOUT the ``C.T``
    materialization (the whole point of the tiled kernel is that ``C``
    never exists in HBM); serving paths cache this across requests."""
    base = score_lib.metric_scores(state, cfg)
    pair_valid = state.node_valid[:, None] & state.node_valid[None, :]
    bw_max = jnp.maximum(jnp.max(jnp.where(pair_valid, state.bw, 0.0)),
                         _EPS)
    lat_max = jnp.maximum(jnp.max(jnp.where(pair_valid, state.lat, 0.0)),
                          _EPS)
    return base, bw_max, lat_max


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "block_p", "block_n", "block_k", "interpret"))
def score_pods_tiled(state: ClusterState, pods: PodBatch,
                     cfg: SchedulerConfig, static=None, *,
                     block_p: int = 128,
                     block_n: int = 128, block_k: int = 128,
                     interpret: bool = False) -> jax.Array:
    """Masked score matrix ``f32[P, N]``, tiled-Pallas implementation.

    Same contract as :func:`~.score.score_pods`.  Grid is
    ``(P/bp, N/bn, N/bk)`` with the contraction axis innermost; VMEM
    residency per step is ``O(bp·bk + 2·bn·bk + bp·bn)`` floats, so node
    count is bounded by HBM (the ``N×N`` lat/bw state), not VMEM.
    ``static`` is an optional precomputed :func:`static_tile_inputs`.
    """
    import math

    p_real, n_real = pods.num_pods, state.num_nodes
    r_res = state.num_resources
    bp = min(block_p, _round_up(p_real, 8))
    p_pad = _round_up(p_real, bp)
    # Pad N to a common multiple of both block sizes so the grid tiles
    # the output exactly — with max() instead of lcm(), a non-dividing
    # block pair (e.g. 48/128) silently truncated the grid and left
    # trailing node columns unwritten.  (On real TPU, Mosaic separately
    # requires lane blocks in multiples of 128 and rejects others with
    # a clear error; the interpreter accepts any size.)
    nb, kb = block_n, block_k
    n_pad = _round_up(n_real, math.lcm(nb, kb))
    # Packed-array extents scale with the resource count (R resources
    # need 2R+2 nodef rows / R+1 podf columns; 8 covers the default
    # R=3 and the lane tiling) and the mask width (4W nodei rows / 5W
    # podi columns).
    mw = cfg.mask_words
    t_soft = cfg.max_soft_terms
    nf_rows = _round_up(2 * r_res + 2, 8)
    pf_cols = _round_up(r_res + 1 + 2 * t_soft, 8)
    ni_rows = _round_up(4 * mw, 8)
    pi_cols = _round_up((5 + 2 * t_soft) * mw, 8)

    if static is None:
        static = static_tile_inputs(state, cfg)
    args = _pack_inputs(state, pods, cfg, static, p_real, n_real, p_pad,
                        n_pad, r_res, mw, t_soft, nf_rows, pf_cols,
                        ni_rows, pi_cols)
    grid = (p_pad // bp, n_pad // nb, n_pad // kb)
    kernel = functools.partial(_kernel, block_n=nb, block_k=kb,
                               num_resources=r_res, mask_words=mw,
                               soft_terms=t_soft,
                               use_bfloat16=cfg.use_bfloat16)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((p_pad, n_pad), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),                 # params
            pl.BlockSpec((bp, kb), lambda i, j, k: (i, k)),        # T
            pl.BlockSpec((nb, kb), lambda i, j, k: (j, k)),        # bw
            pl.BlockSpec((nb, kb), lambda i, j, k: (j, k)),        # lat
            pl.BlockSpec((1, kb), lambda i, j, k: (0, k)),         # validk
            pl.BlockSpec((nf_rows, nb), lambda i, j, k: (0, j)),   # nodef
            pl.BlockSpec((ni_rows, nb), lambda i, j, k: (0, j)),   # nodei
            pl.BlockSpec((bp, pf_cols), lambda i, j, k: (i, 0)),   # podf
            pl.BlockSpec((bp, pi_cols), lambda i, j, k: (i, 0)),   # podi
        ],
        out_specs=pl.BlockSpec((bp, nb), lambda i, j, k: (i, j)),
        scratch_shapes=[pltpu.VMEM((bp, nb), jnp.float32)],
        interpret=interpret,
    )(*args)
    out = out[:p_real, :n_real]

    # Hard nodeAffinity matchExpressions, zone-scoped pod
    # (anti-)affinity, and the soft zone term join OUTSIDE the tile
    # kernel (none streams over the N×N matrices; all self-gate on
    # their constraints being present), same as static_scores_tiled /
    # the dense path.  The additive soft term cannot resurrect a
    # masked entry: NEG_INF is -1e30 and weights are O(10).
    out = out + score_lib.soft_zone_scores(state, pods, cfg)
    out = jnp.where(score_lib.ns_affinity_ok(state, pods), out,
                    jnp.float32(float(NEG_INF)))
    out = jnp.where(score_lib.zone_affinity_ok(state, pods), out,
                    jnp.float32(float(NEG_INF)))

    # Topology spread joins OUTSIDE the tile kernel: it is an O(P*N)
    # gather over the small [G, Z] count matrix (no N×N streaming to
    # fuse), and keeping it in XLA keeps one implementation shared
    # with the dense path and the assign round loop.  The whole block
    # — including the static-eligibility recompute it needs for the
    # Honor-policy min, which the kernel cannot export — is gated on
    # any pod actually carrying a constraint, so spread-free batches
    # pay nothing on the large-N path this kernel exists for.
    def with_spread(scores):
        spread_pen, spread_ok = score_lib.spread_terms(
            state, pods, cfg,
            static_ok=score_lib.static_feasibility(state, pods))
        return jnp.where(spread_ok, scores - spread_pen,
                         jnp.float32(float(NEG_INF)))

    active = score_lib.spread_active(pods)
    return jax.lax.cond(jnp.any(active), with_spread, lambda s: s, out)


def winner_joins_active(state: ClusterState, pods: PodBatch) -> jax.Array:
    """Scalar bool: is any constraint that :func:`score_pods_tiled`
    joins OUTSIDE the tile kernel live for this (state, batch)?  The
    in-kernel winner reduction is exact only when every out-of-kernel
    join is a no-op (soft zone adds zeros, the ns/zone masks are
    all-true, spread is inactive) — when any is live the winner must
    be taken AFTER the joins, so :func:`score_winner_tiled` falls back
    to the two-stage score→argmax path.  Each predicate mirrors the
    corresponding join's own ``lax.cond`` gate in core/score.py; the
    two must agree or the fused path would silently skip a constraint
    the unfused path honors."""
    return (jnp.any(pods.soft_zone_bits != 0)
            | jnp.any(pods.ns_term_used)
            | jnp.any(pods.zaff_bits != 0)
            | jnp.any(pods.zanti_bits != 0)
            | jnp.any(state.az_anti != 0)
            | jnp.any(score_lib.spread_active(pods)))


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "block_p", "block_n", "block_k", "interpret"))
def score_winner_tiled(state: ClusterState, pods: PodBatch,
                       cfg: SchedulerConfig, static=None, *,
                       block_p: int = 128,
                       block_n: int = 128, block_k: int = 128,
                       interpret: bool = False
                       ) -> tuple[jax.Array, jax.Array]:
    """Fused winner selection, tiled-Pallas implementation: returns
    ``(best f32[P], node i32[P])`` with ``node == -1`` for infeasible
    rows — bit-identical to
    ``score.winner_from_scores(score_pods_tiled(...))`` (the parity
    property suite pins this, tie-breaks included).

    Grid and packing are exactly :func:`score_pods_tiled`'s; the
    difference is the output: two ``(P_pad, 128)`` lane-broadcast
    planes instead of the ``(P_pad, N_pad)`` score matrix, so HBM
    write traffic per batch drops from O(P·N) to O(P).  Batches with a
    live out-of-kernel constraint join (``winner_joins_active``) take
    the two-stage path under a ``lax.cond`` — correctness never
    depends on the workload being constraint-free."""
    import math

    p_real, n_real = pods.num_pods, state.num_nodes
    r_res = state.num_resources
    bp = min(block_p, _round_up(p_real, 8))
    p_pad = _round_up(p_real, bp)
    nb, kb = block_n, block_k
    n_pad = _round_up(n_real, math.lcm(nb, kb))
    mw = cfg.mask_words
    t_soft = cfg.max_soft_terms
    nf_rows = _round_up(2 * r_res + 2, 8)
    pf_cols = _round_up(r_res + 1 + 2 * t_soft, 8)
    ni_rows = _round_up(4 * mw, 8)
    pi_cols = _round_up((5 + 2 * t_soft) * mw, 8)

    if static is None:
        static = static_tile_inputs(state, cfg)

    def fused(_):
        args = _pack_inputs(state, pods, cfg, static, p_real, n_real,
                            p_pad, n_pad, r_res, mw, t_soft, nf_rows,
                            pf_cols, ni_rows, pi_cols)
        grid = (p_pad // bp, n_pad // nb, n_pad // kb)
        kernel = functools.partial(
            _winner_kernel, block_n=nb, block_k=kb,
            num_resources=r_res, mask_words=mw, soft_terms=t_soft,
            use_bfloat16=cfg.use_bfloat16)
        best2, node2 = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((p_pad, 128), jnp.float32),
                jax.ShapeDtypeStruct((p_pad, 128), jnp.int32)),
            grid=grid,
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),              # params
                pl.BlockSpec((bp, kb), lambda i, j, k: (i, k)),     # T
                pl.BlockSpec((nb, kb), lambda i, j, k: (j, k)),     # bw
                pl.BlockSpec((nb, kb), lambda i, j, k: (j, k)),     # lat
                pl.BlockSpec((1, kb), lambda i, j, k: (0, k)),      # validk
                pl.BlockSpec((nf_rows, nb), lambda i, j, k: (0, j)),  # nodef
                pl.BlockSpec((ni_rows, nb), lambda i, j, k: (0, j)),  # nodei
                pl.BlockSpec((bp, pf_cols), lambda i, j, k: (i, 0)),  # podf
                pl.BlockSpec((bp, pi_cols), lambda i, j, k: (i, 0)),  # podi
            ],
            # The revisited-output-block reduction: both outputs map
            # every (j, k) to block (i, 0), staying VMEM-resident
            # across the row sweep (see _winner_kernel).
            out_specs=(
                pl.BlockSpec((bp, 128), lambda i, j, k: (i, 0)),
                pl.BlockSpec((bp, 128), lambda i, j, k: (i, 0))),
            scratch_shapes=[pltpu.VMEM((bp, nb), jnp.float32)],
            interpret=interpret,
        )(*args)
        best = best2[:p_real, 0]
        node = node2[:p_real, 0]
        feasible = best > jnp.float32(float(NEG_INF)) * 0.5
        node = jnp.where(feasible, node, jnp.int32(-1))
        return best, node

    def two_stage(_):
        scores = score_pods_tiled(state, pods, cfg, static,
                                  block_p=block_p, block_n=block_n,
                                  block_k=block_k, interpret=interpret)
        return score_lib.winner_from_scores(scores)

    return jax.lax.cond(winner_joins_active(state, pods),
                        two_stage, fused, None)


def _pack_inputs(state: ClusterState, pods: PodBatch,
                 cfg: SchedulerConfig, static, p_real: int, n_real: int,
                 p_pad: int, n_pad: int, r_res: int, mw: int,
                 t_soft: int, nf_rows: int, pf_cols: int, ni_rows: int,
                 pi_cols: int):
    """Shared input packing for the tiled kernels (layouts documented
    at module top): params SMEM vector, padded T/bw/lat/validk, and
    the packed nodef/nodei/podf/podi arrays."""

    def pad(x, rows, cols=None):
        pr = rows - x.shape[0]
        if cols is None:
            return jnp.pad(x, ((0, pr),))
        return jnp.pad(x, ((0, pr), (0, cols - x.shape[1])))

    # Host-of-the-kernel prep (all cheap XLA, fused upstream): the dense
    # traffic matrix, the pod-independent metric vote, and the global
    # normalizers of the desirability tile.
    t = pad(score_lib.peer_traffic_matrix(pods, n_real), p_pad, n_pad)
    base, bw_max, lat_max = static
    params = jnp.stack([
        jnp.float32(cfg.weights.peer_bw), jnp.float32(cfg.weights.peer_lat),
        1.0 / bw_max, 1.0 / lat_max,
        jnp.float32(cfg.weights.balance), jnp.float32(_EPS),
        jnp.float32(cfg.weights.soft_affinity / 100.0), jnp.float32(0)])

    bw = pad(state.bw, n_pad, n_pad)
    lat = pad(state.lat, n_pad, n_pad)
    validk = pad(state.node_valid.astype(jnp.float32), n_real)[None, :]
    validk = pad(validk, 1, n_pad)

    nodef = jnp.zeros((nf_rows, n_pad), jnp.float32)
    nodef = nodef.at[0:r_res, :n_real].set(state.used.T)
    nodef = nodef.at[r_res:2 * r_res, :n_real].set(state.cap.T)
    nodef = nodef.at[2 * r_res, :n_real].set(base)
    nodef = nodef.at[2 * r_res + 1, :n_real].set(
        state.node_valid.astype(jnp.float32))

    nodei = jnp.zeros((ni_rows, n_pad), jnp.int32)
    for f, bits in enumerate((state.taint_bits, state.label_bits,
                              state.group_bits, state.resident_anti)):
        nodei = nodei.at[f * mw:(f + 1) * mw, :n_real].set(
            bits.astype(jnp.int32).T)

    podf, podi = _pack_pod_inputs(pods, p_real, p_pad, r_res, mw,
                                  t_soft, pf_cols, pi_cols)
    return params, t, bw, lat, validk, nodef, nodei, podf, podi


def _pack_pod_inputs(pods: PodBatch, p_real: int, p_pad: int, r_res: int,
                     mw: int, t_soft: int, pf_cols: int, pi_cols: int):
    """Pod-side packed arrays (layouts at module top), shared by both
    tiled kernels — O(P) work, the only per-batch packing the replay
    path pays."""
    podf = jnp.zeros((p_pad, pf_cols), jnp.float32)
    podf = podf.at[:p_real, 0:r_res].set(pods.req)
    podf = podf.at[:p_real, r_res].set(pods.pod_valid.astype(jnp.float32))
    # Soft-term weights, zeroed where the term's bits are empty so the
    # kernel's trivially-true subset match cannot add phantom weight.
    sel_w_eff = jnp.where(jnp.any(pods.soft_sel_bits != 0, axis=-1),
                          pods.soft_sel_w, 0.0)
    grp_w_eff = jnp.where(jnp.any(pods.soft_grp_bits != 0, axis=-1),
                          pods.soft_grp_w, 0.0)
    podf = podf.at[:p_real, r_res + 1:r_res + 1 + t_soft].set(sel_w_eff)
    podf = podf.at[:p_real,
                   r_res + 1 + t_soft:r_res + 1 + 2 * t_soft].set(grp_w_eff)

    podi = jnp.zeros((p_pad, pi_cols), jnp.int32)
    for f, bits in enumerate((pods.tol_bits, pods.sel_bits,
                              pods.affinity_bits, pods.anti_bits,
                              pods.group_bit)):
        podi = podi.at[:p_real, f * mw:(f + 1) * mw].set(
            bits.astype(jnp.int32))
    podi = podi.at[:p_real, 5 * mw:(5 + t_soft) * mw].set(
        pods.soft_sel_bits.astype(jnp.int32).reshape(p_real, -1))
    podi = podi.at[:p_real, (5 + t_soft) * mw:(5 + 2 * t_soft) * mw].set(
        pods.soft_grp_bits.astype(jnp.int32).reshape(p_real, -1))
    return podf, podi


def compute_static(state: ClusterState, cfg: SchedulerConfig):
    """Backend-appropriate batch-invariant prep for
    :func:`score_pods_auto` — cacheable by serving paths (depends only
    on metrics/network/validity, never on placements)."""
    if cfg.score_backend == "pallas":
        return static_tile_inputs(state, cfg)
    return score_lib.static_node_scores(state, cfg)


def compute_assign_static(state: ClusterState, cfg: SchedulerConfig):
    """Backend-appropriate batch-invariant prep for the assign/replay
    seam (:func:`~.assign._static_parts`): the dense ``(base, C.T)``
    pair, or the Pallas :func:`static_replay_pack` (which prepays the
    O(N²) pad/pack work the scan body must not repeat per step).
    Same invariance contract as :func:`compute_static`."""
    if cfg.score_backend == "pallas":
        return static_replay_pack(state, cfg)
    return score_lib.static_node_scores(state, cfg)


def static_replay_pack_delta(state: ClusterState, cfg: SchedulerConfig,
                             prev, ex: "score_lib.NetExtrema",
                             ii: np.ndarray, jj: np.ndarray):
    """Delta rebuild of :func:`static_replay_pack`, bit-identical to
    the full path.  Preconditions: since ``prev`` was packed, only net
    elements ``(ii, jj)`` changed (both orientations listed) and
    topology/validity did not; ``base`` (O(N*M)) is recomputed
    outright.  Unlike the dense path, a moved normalizer does NOT
    force O(N²) work here: the pack carries RAW padded bw/lat and the
    normalizers live in the 8-scalar params vector."""
    ex2 = score_lib.net_extrema_update(state, ex, ii, jj)
    _, bw_p, lat_p, validk, nodes, nodei = prev
    base = score_lib.metric_scores(state, cfg)
    bw_max = jnp.maximum(jnp.float32(ex2.bw_m), _EPS)
    lat_max = jnp.maximum(jnp.float32(ex2.lat_m), _EPS)
    params = jnp.stack([
        jnp.float32(cfg.weights.peer_bw), jnp.float32(cfg.weights.peer_lat),
        1.0 / bw_max, 1.0 / lat_max,
        jnp.float32(cfg.weights.balance), jnp.float32(_EPS),
        jnp.float32(cfg.weights.soft_affinity / 100.0), jnp.float32(0)])
    if len(ii):
        iid = jnp.asarray(ii)
        jjd = jnp.asarray(jj)
        bw_p = bw_p.at[iid, jjd].set(state.bw[iid, jjd])
        lat_p = lat_p.at[iid, jjd].set(state.lat[iid, jjd])
    nodes = nodes.at[0, :state.num_nodes].set(base)
    return (params, bw_p, lat_p, validk, nodes, nodei), ex2


def compute_assign_static_incremental(
        state: ClusterState, cfg: SchedulerConfig, prev,
        ex: "score_lib.NetExtrema | None", dirty: "dict | None"):
    """Incremental :func:`compute_assign_static`: returns
    ``(static, extrema)``, patching ``prev`` when the dirty footprint
    permits and falling back to a full rebuild otherwise.

    ``dirty`` is the merged descriptor from
    ``Encoder.static_delta_since`` (None = coverage unprovable).  Full
    rebuild triggers on: no previous value, no descriptor, any topo
    dirt (validity changes every mask and both normalizers), or a
    whole-group net rewrite.  Metrics-only dirt recomputes just the
    O(N*M) base; net pair dirt takes the O(|dirty|) patch path."""
    pairs = None if dirty is None else dirty.get("net_pairs")
    if (prev is None or ex is None or dirty is None
            or dirty.get("topo")
            or (dirty.get("net") and pairs is None)):
        return (compute_assign_static(state, cfg),
                score_lib.net_extrema_scan(state))
    if pairs:
        srt = sorted(pairs)
        ii = np.array([p[0] for p in srt], np.int32)
        jj = np.array([p[1] for p in srt], np.int32)
    else:
        ii = jj = np.zeros(0, np.int32)
    if cfg.score_backend == "pallas":
        return static_replay_pack_delta(state, cfg, prev, ex, ii, jj)
    return score_lib.static_node_scores_delta(state, cfg, prev, ex,
                                              ii, jj)


# Jitted entry for the dense path: serving callers hit this once per
# webhook dispatch, where eager op-by-op tracing from Python would be
# the bottleneck (GIL-bound) — unlike the replay/assign paths, which
# call score_pods inside their own jit.
_score_pods_jit = functools.partial(
    jax.jit, static_argnames=("cfg",))(score_lib.score_pods)

# Dense fused winner: one jit around score→argmax, so XLA fuses the
# row reduction with the score producer (the segment-max epilogue)
# instead of round-tripping the P×N plane between two dispatches.
_score_winner_jit = functools.partial(
    jax.jit, static_argnames=("cfg",))(score_lib.score_winner)


def score_winner_auto(state: ClusterState, pods: PodBatch,
                      cfg: SchedulerConfig, static=None
                      ) -> tuple[jax.Array, jax.Array]:
    """Backend dispatch for the fused winner (:func:`score_pods_auto`'s
    twin): ``(best f32[P], node i32[P])``, ``node == -1`` infeasible.
    ``static`` is an optional precomputed :func:`compute_static`.
    With ``cfg.enable_winner_fusion`` off, the two-stage score→argmax
    path runs instead — same results (property-tested), kept as the
    bisection escape hatch (OPERATIONS.md)."""
    if not cfg.enable_winner_fusion:
        scores = score_pods_auto(state, pods, cfg, static)
        return score_lib.winner_from_scores(scores)
    if cfg.score_backend == "pallas":
        interpret = jax.default_backend() != "tpu"
        return score_winner_tiled(state, pods, cfg, static,
                                  interpret=interpret)
    return _score_winner_jit(state, pods, cfg, static)


def score_pods_auto(state: ClusterState, pods: PodBatch,
                    cfg: SchedulerConfig, static=None) -> jax.Array:
    """Dispatch on ``cfg.score_backend``: the dense XLA kernel or the
    tiled Pallas kernel (interpreted off-TPU so CPU CI still runs it).
    ``static`` is an optional precomputed :func:`compute_static`."""
    if cfg.score_backend == "pallas":
        interpret = jax.default_backend() != "tpu"
        return score_pods_tiled(state, pods, cfg, static,
                                interpret=interpret)
    return _score_pods_jit(state, pods, cfg, static)
