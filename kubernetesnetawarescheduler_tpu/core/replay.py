"""Device-resident replay: the whole scheduling run as ONE XLA program.

The host loop in :mod:`~kubernetesnetawarescheduler_tpu.core.loop` pays
one host↔device round-trip per batch (encode → dispatch → fetch → bind).
That is the right shape for live serving against a real API server, but
for throughput it re-introduces — in miniature — the reference's defect
of a synchronous network hop inside the scheduling cycle
(scheduler.go:275-279).  Here the full pending-pod stream is encoded
once, shipped to the device once, and a ``lax.scan`` drives batch after
batch of score → assign → commit *entirely on device*; the only
transfer back is the final assignment vector.

Peers inside the stream (a pod exchanging traffic with an
earlier-scheduled pod of its service) are carried as *stream indices*
and resolved on device against the assignments made so far — the
batch-to-batch dependency that forces the scan carry, and the analog of
the reference's pods-bind-one-at-a-time ordering (scheduler.go:191).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
from flax import struct

from kubernetesnetawarescheduler_tpu.config import SchedulerConfig
from kubernetesnetawarescheduler_tpu.core.assign import (
    UNASSIGNED,
    assign_greedy,
    assign_parallel,
)
from kubernetesnetawarescheduler_tpu.core.pallas_score import (
    compute_assign_static,
)
from kubernetesnetawarescheduler_tpu.core.state import (
    ClusterState,
    PodBatch,
    commit_assignments,
)


@struct.dataclass
class PodStream:
    """A whole workload of pending pods, encoded columnar.

    Same per-pod fields as :class:`~.state.PodBatch` except the peer
    encoding: ``peer_pods[i, k] >= 0`` names another *stream index*
    whose eventual node is the traffic endpoint; ``peer_nodes[i, k]``
    carries peers already placed before the replay started (node index,
    -1 = none).  Length is padded to a multiple of the batch size.
    """

    req: jax.Array            # f32[S, R]
    peer_pods: jax.Array      # i32[S, K]  stream index or -1
    peer_nodes: jax.Array     # i32[S, K]  node index or -1
    peer_traffic: jax.Array   # f32[S, K]
    tol_bits: jax.Array       # u32[S, W]
    sel_bits: jax.Array       # u32[S, W]
    affinity_bits: jax.Array  # u32[S, W]
    anti_bits: jax.Array      # u32[S, W]
    group_bit: jax.Array      # u32[S, W]
    priority: jax.Array       # f32[S]
    pod_valid: jax.Array      # bool[S]
    soft_sel_bits: jax.Array  # u32[S, T, W]
    soft_sel_w: jax.Array     # f32[S, T]
    soft_grp_bits: jax.Array  # u32[S, T, W]
    soft_grp_w: jax.Array     # f32[S, T]
    soft_zone_bits: jax.Array  # u32[S, T, W]
    soft_zone_w: jax.Array     # f32[S, T]
    group_idx: jax.Array       # i32[S]
    spread_maxskew: jax.Array  # i32[S]
    spread_hard: jax.Array     # bool[S]
    ns_anyof: jax.Array        # u32[S, T2, E, W]
    ns_forbid: jax.Array       # u32[S, T2, W]
    ns_term_used: jax.Array    # bool[S, T2]
    ns_num_col: jax.Array      # i32[S, T2, NE]
    ns_num_lo: jax.Array       # f32[S, T2, NE]
    ns_num_hi: jax.Array       # f32[S, T2, NE]
    zaff_bits: jax.Array       # u32[S, W]
    zanti_bits: jax.Array      # u32[S, W]

    @property
    def num_pods(self) -> int:
        return self.req.shape[0]


def _make_step(state: ClusterState, cfg: SchedulerConfig, method: str,
               s_total: int, static):
    """The per-batch scan body shared by every replay variant
    (monolithic, chunked/pipelined, mesh-sharded).

    Carry is ``(used, group_bits, resident_anti, node_of_pod)`` — only
    the placement-mutated arrays; the big immutable state (the N×N
    lat/bw matrices, metrics, capacities, label/taint bits) is closed
    over, so XLA keeps one HBM copy instead of round-tripping ~200 MB
    of carry per step.  ``x`` is ``(batch_index, stream_slice)``.

    Per-batch ys are ``(assignment i32[batch], rounds i32)`` — the
    conflict-round count is always collected (one scalar add per round;
    free) so benchmarks can report its distribution.
    """
    batch = cfg.max_pods

    def step(carry, x):
        (used, group_bits, resident_anti, gz_counts, az_anti,
         node_of_pod) = carry
        i, sl = x
        st = state.replace(used=used, group_bits=group_bits,
                           resident_anti=resident_anti,
                           gz_counts=gz_counts, az_anti=az_anti)
        # Resolve in-stream peers against assignments made so far; a
        # peer that is still unplaced (or unschedulable) stays -1 and
        # the scoring kernel drops it — traffic to a homeless pod
        # cannot pull the placement anywhere.
        pp = sl.peer_pods
        from_stream = node_of_pod[jnp.clip(pp, 0, s_total - 1)]
        peers = jnp.where(pp >= 0, from_stream, sl.peer_nodes)
        pods = PodBatch(
            req=sl.req, peers=peers, peer_traffic=sl.peer_traffic,
            tol_bits=sl.tol_bits, sel_bits=sl.sel_bits,
            affinity_bits=sl.affinity_bits, anti_bits=sl.anti_bits,
            group_bit=sl.group_bit, priority=sl.priority,
            pod_valid=sl.pod_valid,
            soft_sel_bits=sl.soft_sel_bits, soft_sel_w=sl.soft_sel_w,
            soft_grp_bits=sl.soft_grp_bits, soft_grp_w=sl.soft_grp_w,
            soft_zone_bits=sl.soft_zone_bits,
            soft_zone_w=sl.soft_zone_w,
            group_idx=sl.group_idx, spread_maxskew=sl.spread_maxskew,
            spread_hard=sl.spread_hard, ns_anyof=sl.ns_anyof,
            ns_forbid=sl.ns_forbid, ns_term_used=sl.ns_term_used,
            ns_num_col=sl.ns_num_col, ns_num_lo=sl.ns_num_lo,
            ns_num_hi=sl.ns_num_hi,
            zaff_bits=sl.zaff_bits, zanti_bits=sl.zanti_bits)
        if callable(static):
            # Mesh Pallas path: the per-batch static scores are
            # computed here (shard_map'd kernel) and passed into
            # assign precomputed — see assign._static_parts.
            raw, ok = static(st, pods)
            batch_static = {"raw": raw, "ok": ok}
        else:
            batch_static = static
        if method == "parallel":
            assignment, rounds = assign_parallel(st, pods, cfg,
                                                 batch_static,
                                                 with_stats=True)
        elif method == "greedy":
            assignment = assign_greedy(st, pods, cfg, batch_static)
            rounds = jnp.int32(0)
        else:
            raise ValueError(f"unknown method {method!r}")
        st = commit_assignments(st, pods, assignment)
        node_of_pod = jax.lax.dynamic_update_slice_in_dim(
            node_of_pod, assignment, i * batch, 0)
        return (st.used, st.group_bits, st.resident_anti, st.gz_counts,
                st.az_anti, node_of_pod), (assignment, rounds)

    return step


def _check_stream(stream: PodStream, cfg: SchedulerConfig) -> int:
    s_total = stream.num_pods
    if s_total % cfg.max_pods != 0:
        raise ValueError(f"stream length {s_total} not a multiple of "
                         f"max_pods={cfg.max_pods}")
    return s_total // cfg.max_pods


def fold_stream(stream: PodStream, cfg: SchedulerConfig):
    """Validate the stream length and fold every field to
    ``[NB, batch, ...]`` (the layout the scan walks).  Shared by the
    monolithic, chunked and mesh-sharded replays."""
    nb = _check_stream(stream, cfg)
    batch = cfg.max_pods
    return jax.tree_util.tree_map(
        lambda x: x.reshape((nb, batch) + x.shape[1:]), stream)


def replay_folded(state: ClusterState, folded, cfg: SchedulerConfig,
                  method: str = "parallel", static_builder=None,
                  with_stats: bool = False):
    """Scan over a pre-folded ``[NB, batch, ...]`` stream pytree.
    Traceable core of :func:`replay_stream`; also jitted directly by
    the mesh-sharded replay (which must keep the folded layout — a
    flat reshape of a dp-sharded batch axis would force a reshard).

    ``static_builder``, if given, replaces the default per-replay
    static-score prep: called once with the full state, it returns a
    per-batch callable ``(st, pods) -> (raw, static_ok)`` (the
    shard_map'd multi-chip Pallas path,
    parallel.sharding.pallas_static_builder)."""
    nb = jax.tree_util.tree_leaves(folded)[0].shape[0]
    batch = cfg.max_pods
    s_total = nb * batch
    # Batch-invariant node scores (metric vote + net normalizers):
    # computed ONCE here, closed over by the scan body, instead of
    # re-normalizing the N×N matrices inside every step (don't rely on
    # XLA's loop-invariant code motion for ~100 MB intermediates).
    # Backend-shaped: (base, C.T) for dense, the static_replay_pack
    # arrays (params, padded bw/lat, validk, nodes, nodei) for the
    # Pallas tiled path (which never materializes C).
    if static_builder is not None:
        static = static_builder(state)
    else:
        static = compute_assign_static(state, cfg)
    step = _make_step(state, cfg, method, s_total, static)
    xs = (jnp.arange(nb, dtype=jnp.int32), folded)
    init = (state.used, state.group_bits, state.resident_anti,
            state.gz_counts, state.az_anti,
            jnp.full((s_total,), UNASSIGNED, jnp.int32))
    (used, group_bits, resident_anti, gz_counts, az_anti, _), \
        (assignments, rounds) = jax.lax.scan(step, init, xs)
    final_state = state.replace(used=used, group_bits=group_bits,
                                resident_anti=resident_anti,
                                gz_counts=gz_counts, az_anti=az_anti)
    if with_stats:
        return assignments.reshape(-1), final_state, rounds
    return assignments.reshape(-1), final_state


@partial(jax.jit, static_argnames=("cfg", "method", "with_stats"))
def replay_stream(state: ClusterState, stream: PodStream,
                  cfg: SchedulerConfig, method: str = "parallel",
                  with_stats: bool = False):
    """Run the full stream through score→assign→commit on device.

    Returns ``(assignment i32[S], final_state)`` — plus per-batch
    conflict-round counts ``i32[NB]`` with ``with_stats=True``; one
    dispatch, one fetch.  ``stream`` length must be a multiple of
    ``cfg.max_pods`` (pad with invalid pods via :func:`pad_stream`).
    """
    return replay_folded(state, fold_stream(stream, cfg), cfg, method,
                         with_stats=with_stats)


@partial(jax.jit, static_argnames=("cfg", "method", "with_stats"))
def replay_stream_static(state: ClusterState, stream: PodStream,
                         static, cfg: SchedulerConfig,
                         method: str = "parallel",
                         with_stats: bool = False):
    """:func:`replay_stream` with the batch-invariant static prep
    passed IN instead of recomputed per call.  The serving loop's
    burst path dispatches one of these per backlog burst — at N=5120
    the O(N²) static prep is ~hundreds of ms on the CPU fallback, and
    the serving cycle already caches it across cycles keyed on the
    encoder's static version (loop._static_for); recomputing it every
    burst measured as a ~2× serving regression."""
    return replay_folded(state, fold_stream(stream, cfg), cfg, method,
                         static_builder=lambda _state: static,
                         with_stats=with_stats)


@partial(jax.jit, static_argnames=("cfg", "method", "chunk_batches"))
def _replay_chunk(state: ClusterState, static, carry, folded,
                  chunk_start: jax.Array, s_total: int,
                  cfg: SchedulerConfig, method: str, chunk_batches: int):
    """One pipelined chunk of the replay: ``chunk_batches`` scan steps
    starting at batch index ``chunk_start`` (traced, so every chunk
    shares one executable).  ``carry`` is the placement-mutated state
    plus the *global* ``node_of_pod`` vector; ``folded`` is the whole
    stream pre-folded to ``[NB, batch, ...]`` and device-resident."""
    xs_stream = jax.tree_util.tree_map(
        lambda x: jax.lax.dynamic_slice_in_dim(
            x, chunk_start, chunk_batches, 0), folded)
    batch_ids = chunk_start + jnp.arange(chunk_batches, dtype=jnp.int32)
    step = _make_step(state, cfg, method, s_total, static)
    carry, (assignments, rounds) = jax.lax.scan(step, carry,
                                                (batch_ids, xs_stream))
    return carry, assignments.reshape(-1), rounds


def replay_stream_pipelined(state: ClusterState, stream: PodStream,
                            cfg: SchedulerConfig, method: str = "parallel",
                            chunk_batches: int = 8,
                            dispatch_window: int = 4):
    """Chunked replay for the pipelined drain: yields
    ``(start_pod_index, assignment np.ndarray, rounds np.ndarray)``
    per chunk, in order (``rounds`` is the per-batch conflict-round
    count of the chunk's batches).

    Chunks are dispatched ahead of the fetch cursor up to
    ``dispatch_window`` in flight (JAX's async dispatch queues them with
    the carry threading the data dependency), so the device runs chunk
    ``i+1`` while the host fetches/binds chunk ``i`` — the async
    binding-cycle shape kube-scheduler itself uses, and the fix for the
    reference's fully synchronous cycle (scheduler.go:189-237).

    The window is bounded rather than "dispatch everything up front"
    because on a remote/tunneled device the dispatch messages share the
    transport with the result fetches: enqueueing every chunk before
    the first fetch makes chunk 0's host-observed latency absorb the
    whole dispatch train (measured ~4x p99 inflation at 32 chunks),
    while a small window keeps the device >= ``window * chunk_batches``
    batches ahead — far more than it needs to never go idle.
    The final short chunk falls back to :func:`_replay_chunk` with a
    smaller static ``chunk_batches`` (one extra compile, cached).

    SETUP IS EAGER (runs at call time, before the generator is
    returned): the one-time static prep (~3 HBM passes over the N×N
    matrix) and the whole-stream upload belong to replay startup, not
    to chunk 0's latency — a live deployment pays them once per state
    refresh, amortized.  Callers that time per-chunk service latency
    should take their clock AFTER this call returns (bench/density.py
    does; the one-time cost still lands in its throughput wall)."""
    static = compute_assign_static(state, cfg)
    s_total = stream.num_pods
    batch = cfg.max_pods
    if s_total % batch != 0:
        raise ValueError(
            f"stream length {s_total} not a multiple of max_pods={batch}")
    nb = s_total // batch

    folded = jax.tree_util.tree_map(
        lambda x: jax.device_put(
            jnp.asarray(x).reshape((nb, batch) + x.shape[1:])), stream)
    carry = (state.used, state.group_bits, state.resident_anti,
             state.gz_counts, state.az_anti,
             jnp.full((s_total,), UNASSIGNED, jnp.int32))

    start_box = [0]

    def dispatch_one():
        nonlocal carry
        start = start_box[0]
        if start >= nb:
            return False
        cb = min(chunk_batches, nb - start)
        carry, assignment, rounds = _replay_chunk(
            state, static, carry, folded, jnp.int32(start), s_total,
            cfg, method, cb)
        start_box[0] = start + cb
        return start * batch, assignment, rounds

    return _windowed_drain(dispatch_one, dispatch_window)


@partial(jax.jit, static_argnames=("cfg", "method"))
def _replay_chunk_feed(state: ClusterState, static, carry, chunk_folded,
                       batch_ids: jax.Array, cfg: SchedulerConfig,
                       method: str):
    """One chunk of the feed-based pipelined replay: like
    :func:`_replay_chunk` but the chunk's stream slice arrives as its
    own ``[cb, batch, ...]`` pytree (uploaded per chunk by the encode
    producer) instead of being dynamic-sliced out of a device-resident
    whole-stream copy.  ``batch_ids`` are the chunk's global batch
    indices (traced, so every equal-length chunk shares one
    executable; the final short chunk compiles once more)."""
    s_total = carry[-1].shape[0]
    step = _make_step(state, cfg, method, s_total, static)
    carry, (assignments, rounds) = jax.lax.scan(
        step, carry, (batch_ids, chunk_folded))
    return carry, assignments.reshape(-1), rounds


def _prefetch_to_host(*arrays) -> None:
    """Start async device→host copies so the later ``np.asarray`` finds
    the data already in flight — on a remote/tunneled chip this hides
    most of the per-chunk transport behind the compute of later
    chunks.  Best-effort: backends without the method just skip."""
    for a in arrays:
        fn = getattr(a, "copy_to_host_async", None)
        if fn is not None:
            try:
                fn()
            except Exception:  # noqa: BLE001 — purely an optimization
                return


def _windowed_drain(dispatch_next, dispatch_window: int,
                    prefetch: bool = True):
    """The dispatch-window scaffolding shared by both pipelined
    replays: keep up to ``dispatch_window`` chunks in flight (JAX's
    async dispatch queues them with the carry threading the data
    dependency), refilling BEFORE each blocking fetch so the next
    dispatch rides the transport ahead of the fetch request — and, in
    the feed variant, so the encode producer keeps running ahead.

    ``dispatch_next()`` dispatches one chunk and returns
    ``(pod_start, assignment, rounds)`` device handles, or ``False``
    once the stream is exhausted.  The initial window fill happens
    HERE, eagerly at call time (the "setup is eager" contract both
    variants document); the returned generator then yields
    ``(pod_start, np.ndarray assignment, np.ndarray rounds)`` in
    stream order."""
    from collections import deque
    pending: deque = deque()

    def refill() -> bool:
        item = dispatch_next()
        if item is False:
            return False
        if prefetch:
            _prefetch_to_host(item[1], item[2])
        pending.append(item)
        return True

    while len(pending) < max(1, dispatch_window) and refill():
        pass

    def drain():
        while pending:
            pod_start, assignment, rounds = pending.popleft()
            if len(pending) < max(1, dispatch_window):
                refill()
            yield pod_start, np.asarray(assignment), np.asarray(rounds)

    return drain()


def replay_stream_pipelined_feed(state: ClusterState, chunk_iter,
                                 s_total: int, cfg: SchedulerConfig,
                                 method: str = "parallel",
                                 dispatch_window: int = 4,
                                 prefetch: bool = True):
    """Pipelined replay fed by an encode producer: consumes
    :class:`PodStream` chunks from ``chunk_iter`` (each a multiple of
    ``cfg.max_pods`` pods except the last, concatenating to
    ``s_total``) and yields ``(start_pod_index, assignment, rounds)``
    per chunk, in order — the same contract as
    :func:`replay_stream_pipelined`.

    The difference is WHERE the stream comes from: the whole-stream
    variant needs the workload fully encoded and uploaded before the
    first dispatch, so at the bench's headline shape the host spends
    seconds encoding while the device sits idle.  Here the host encode
    (Encoder.encode_stream_chunks on a producer thread) overlaps the
    device drain — chunk ``i+window`` is being encoded while chunk
    ``i`` computes and chunk ``i-1`` binds, collapsing the wall clock
    from ``encode + replay`` to ``max(encode, replay)``.

    SETUP IS EAGER, matching the whole-stream variant: the static prep
    AND the initial window fill (blocking on the producer for the
    first ``dispatch_window`` chunks, dispatching each) run at call
    time, so a caller timing per-chunk service latency after this call
    returns never charges the encode ramp-up to chunk 0's sample.

    ``prefetch`` starts async device→host copies at dispatch time
    (see :func:`_prefetch_to_host`)."""
    static = compute_assign_static(state, cfg)
    batch = cfg.max_pods
    if s_total % batch != 0:
        raise ValueError(
            f"stream length {s_total} not a multiple of max_pods={batch}")
    nb = s_total // batch
    carry = (state.used, state.group_bits, state.resident_anti,
             state.gz_counts, state.az_anti,
             jnp.full((s_total,), UNASSIGNED, jnp.int32))

    it = iter(chunk_iter)
    start_box = [0]

    def dispatch_next():
        nonlocal carry
        start = start_box[0]
        try:
            ch = next(it)
        except StopIteration:
            if start != nb:
                raise ValueError(
                    f"chunk iterator ended at batch {start} of {nb}")
            return False
        cp = ch.num_pods
        if cp % batch != 0 or cp == 0:
            raise ValueError(
                f"chunk of {cp} pods is not a positive multiple of "
                f"max_pods={batch}")
        cb = cp // batch
        if start + cb > nb:
            raise ValueError(
                f"chunks overrun s_total={s_total} at batch {start}+{cb}")
        folded = jax.tree_util.tree_map(
            lambda x: x.reshape((cb, batch) + x.shape[1:]), ch)
        ids = jnp.arange(start, start + cb, dtype=jnp.int32)
        carry, assignment, rounds = _replay_chunk_feed(
            state, static, carry, folded, ids, cfg, method)
        start_box[0] = start + cb
        return start * batch, assignment, rounds

    return _windowed_drain(dispatch_next, dispatch_window, prefetch)


def pad_stream(stream: PodStream, multiple: int) -> PodStream:
    """Pad the stream with invalid pods up to a multiple of ``multiple``."""
    s = stream.num_pods
    target = ((s + multiple - 1) // multiple) * multiple
    if target == s:
        return stream
    pad = target - s

    def pd(x, fill):
        widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, widths, constant_values=fill)

    return PodStream(
        req=pd(stream.req, 0.0),
        peer_pods=pd(stream.peer_pods, -1),
        peer_nodes=pd(stream.peer_nodes, -1),
        peer_traffic=pd(stream.peer_traffic, 0.0),
        tol_bits=pd(stream.tol_bits, 0),
        sel_bits=pd(stream.sel_bits, 0),
        affinity_bits=pd(stream.affinity_bits, 0),
        anti_bits=pd(stream.anti_bits, 0),
        group_bit=pd(stream.group_bit, 0),
        priority=pd(stream.priority, 0.0),
        pod_valid=pd(stream.pod_valid, False),
        soft_sel_bits=pd(stream.soft_sel_bits, 0),
        soft_sel_w=pd(stream.soft_sel_w, 0.0),
        soft_grp_bits=pd(stream.soft_grp_bits, 0),
        soft_grp_w=pd(stream.soft_grp_w, 0.0),
        soft_zone_bits=pd(stream.soft_zone_bits, 0),
        soft_zone_w=pd(stream.soft_zone_w, 0.0),
        group_idx=pd(stream.group_idx, -1),
        spread_maxskew=pd(stream.spread_maxskew, 0),
        spread_hard=pd(stream.spread_hard, False),
        ns_anyof=pd(stream.ns_anyof, 0),
        ns_forbid=pd(stream.ns_forbid, 0),
        ns_term_used=pd(stream.ns_term_used, False),
        ns_num_col=pd(stream.ns_num_col, -1),
        ns_num_lo=pd(stream.ns_num_lo, -float("inf")),
        ns_num_hi=pd(stream.ns_num_hi, float("inf")),
        zaff_bits=pd(stream.zaff_bits, 0),
        zanti_bits=pd(stream.zanti_bits, 0),
    )
