"""State-layer chaos: seeded fault injection into the device-resident
state pipeline.

k8s/chaos.py makes *control-plane* misbehaviour a first-class input;
this module does the same for the r7 *state layer* — the host staging
mirror, the delta-patch stream, the HBM planes, and the checkpoint
files.  Every fault class below is something the delta-ingest design
could genuinely suffer and the integrity auditor (core/integrity.py)
must detect within one audit period and repair bit-identically:

- ``delta_drop`` — a staging write whose device patch was lost: the
  staging row moves with NO dirty marking, so the device keeps serving
  the stale row forever.
- ``delta_dup`` — a delta applied twice: the device row overshoots the
  staging truth by the delta a second application would add.
- ``delta_reorder`` — two patches landing out of order: the device net
  pair ends on the OLDER value while staging holds the newer one.
- ``nan_poison`` — NaN/Inf reaching a device metric row (a poisoned
  sample that bypassed ingest validation mid-transfer).
- ``bit_flip`` — one flipped bit in a device plane (HBM/transport
  corruption), across float, uint32 and int32 planes.
- ``checkpoint_corrupt`` — torn/corrupted checkpoint files on disk:
  truncation, byte flips, deleted members (detected by the r10
  MANIFEST digests at restore time, not by the runtime auditor).

Everything is deterministic from the seed (``np.random.default_rng``),
like :class:`~..k8s.chaos.ChaosSchedule`.  Each injection returns a
descriptor pinning exactly what was corrupted — the test matrix and
the ``--suite integrity`` bench drive the auditor against it — and is
counted in :attr:`injected` (``/metrics``:
``netaware_state_faults_injected_total{fault=...}``).  When a loop is
attached, the fault class is tagged onto the next committed flight-
recorder span (``fault_class``), so a trace reader sees WHICH cycle
first ran on corrupted state.
"""

from __future__ import annotations

import os
from typing import Sequence

import jax.numpy as jnp
import numpy as np

#: Every state-fault class the injector knows.
STATE_FAULT_CLASSES = ("delta_drop", "delta_dup", "delta_reorder",
                       "nan_poison", "bit_flip", "checkpoint_corrupt")

#: Device planes eligible for ``bit_flip``, with their numpy dtypes —
#: one float, one bitmask, one index plane, so the flip exercises every
#: bitcast path of the digest kernel.
_FLIP_PLANES = ("cap", "group_bits", "node_zone")


class StateChaosInjector:
    """Seeded injector of state-layer faults against one Encoder.

    ``inject(kind)`` applies one deterministic fault and returns its
    descriptor ``{"fault", "plane", "rows", ...}``; ``inject_random()``
    draws the class from the seeded stream.  ``checkpoint_corrupt``
    needs ``checkpoint_dir``; the others need a materialized device
    cache (the injector flushes a snapshot first so the fault survives
    the next legitimate flush — un-flushed dirt would silently heal
    it and the detection test would pass vacuously).
    """

    def __init__(self, encoder, seed: int = 0, loop=None,
                 checkpoint_dir: str | None = None) -> None:
        self.encoder = encoder
        self.loop = loop
        self.checkpoint_dir = checkpoint_dir
        self.seed = int(seed)
        self._rng = np.random.default_rng(seed)
        self.injected = {k: 0 for k in STATE_FAULT_CLASSES}
        self.faults: list[dict] = []

    # -- plumbing -----------------------------------------------------

    def _pick_row(self) -> int:
        rows = np.flatnonzero(self.encoder._node_valid)
        if rows.size == 0:
            return 0
        return int(rows[self._rng.integers(0, rows.size)])

    def _flush(self) -> None:
        """Materialize/settle the device cache so the injected fault
        is not masked by pending legitimate dirt."""
        self.encoder.snapshot()

    def _poke_device(self, key: str, mutate) -> None:
        """Round-trip one cached device plane through numpy, mutate it,
        and put it back — modelling corruption that happened ON the
        device/transfer side, invisible to the dirty tracking."""
        enc = self.encoder
        host = np.array(enc._cache[key])
        mutate(host)
        enc._cache[key] = jnp.asarray(host)

    def _record(self, desc: dict) -> dict:
        self.injected[desc["fault"]] += 1
        self.faults.append(desc)
        loop = self.loop
        if loop is not None:
            # One-shot span tag: the next committed cycle span carries
            # this fault class (core/loop.py _span_commit).
            loop._state_fault_pending = desc["fault"]
        return desc

    # -- fault classes ------------------------------------------------

    def inject(self, kind: str) -> dict:
        if kind not in STATE_FAULT_CLASSES:
            raise ValueError(f"unknown state-fault class {kind!r}")
        return getattr(self, f"_inject_{kind}")()

    def inject_random(self) -> dict:
        """Draw a class from the seeded stream (checkpoint faults only
        when a checkpoint directory with files exists)."""
        classes = [k for k in STATE_FAULT_CLASSES
                   if k != "checkpoint_corrupt"
                   or (self.checkpoint_dir
                       and os.path.exists(os.path.join(
                           self.checkpoint_dir, "state.npz")))]
        return self.inject(
            classes[int(self._rng.integers(0, len(classes)))])

    @staticmethod
    def _perturb(value: np.float32) -> np.float32:
        """A float32 value guaranteed bit-different from ``value`` at
        ANY magnitude — an additive epsilon would round away against
        multi-gigabyte metric values (f32 has 24 mantissa bits, so
        1e10 + 2.0 == 1e10 exactly) and the fault would vanish."""
        new = np.float32(value * np.float32(1.5) + np.float32(1.0))
        if new == value:  # value == -2.0, the fixpoint
            new = np.float32(value + np.float32(3.0))
        return new

    def _inject_delta_drop(self) -> dict:
        enc = self.encoder
        with enc._lock:
            self._flush()
            row = self._pick_row()
            chan = int(self._rng.integers(0, enc._metrics.shape[1]))
            # Staging moves; the dirty marking the write would have
            # left is deliberately NOT made — the patch was "dropped".
            enc._metrics[row, chan] = self._perturb(
                enc._metrics[row, chan])
        return self._record({"fault": "delta_drop", "plane": "metrics",
                             "rows": [row], "channel": chan})

    def _inject_delta_dup(self) -> dict:
        enc = self.encoder
        with enc._lock:
            self._flush()
            row = self._pick_row()
            chan = int(self._rng.integers(0, enc._metrics.shape[1]))

            def mutate(host, r=row, c=chan):
                # The same delta applied twice: device overshoots the
                # staging truth by one application (scale-aware so it
                # cannot round away against large values).
                host[r, c] = self._perturb(np.float32(host[r, c]))

            self._poke_device("metrics", mutate)
        return self._record({"fault": "delta_dup", "plane": "metrics",
                             "rows": [row], "channel": chan})

    def _inject_delta_reorder(self) -> dict:
        enc = self.encoder
        with enc._lock:
            self._flush()
            i = self._pick_row()
            j = self._pick_row()
            if j == i:
                j = (i + 1) % enc._lat.shape[0]
            stale = float(self._rng.uniform(0.1, 50.0))

            def mutate(host, a=i, b=j, v=stale):
                # An older patch landed LAST: the device pair reverts
                # to a stale value while staging keeps the newer one.
                host[a, b] = np.float32(v)

            self._poke_device("lat", mutate)
        return self._record({"fault": "delta_reorder", "plane": "lat",
                             "rows": [i], "pair": [i, j]})

    def _inject_nan_poison(self) -> dict:
        enc = self.encoder
        with enc._lock:
            self._flush()
            row = self._pick_row()
            chan = int(self._rng.integers(0, enc._metrics.shape[1]))
            val = np.float32(np.nan if self._rng.random() < 0.5
                             else np.inf)

            def mutate(host, r=row, c=chan, v=val):
                host[r, c] = v

            self._poke_device("metrics", mutate)
        return self._record({"fault": "nan_poison", "plane": "metrics",
                             "rows": [row], "channel": chan})

    def _inject_bit_flip(self) -> dict:
        enc = self.encoder
        with enc._lock:
            self._flush()
            plane = _FLIP_PLANES[
                int(self._rng.integers(0, len(_FLIP_PLANES)))]
            host = np.array(enc._cache[plane])
            flat = host.reshape(host.shape[0], -1)
            row = self._pick_row() % host.shape[0]
            col = int(self._rng.integers(0, flat.shape[1]))
            bit = int(self._rng.integers(0, 32))

            u32 = (flat if flat.dtype == np.uint32
                   else flat.view(np.uint32))
            u32[row, col] ^= np.uint32(1 << bit)
            enc._cache[plane] = jnp.asarray(host)
        return self._record({"fault": "bit_flip", "plane": plane,
                             "rows": [int(row)], "bit": bit})

    def _inject_checkpoint_corrupt(self) -> dict:
        if not self.checkpoint_dir:
            raise ValueError(
                "checkpoint_corrupt needs a checkpoint_dir")
        path = self.checkpoint_dir
        modes = ("truncate", "flip", "delete_meta")
        mode = modes[int(self._rng.integers(0, len(modes)))]
        target = os.path.join(path, "state.npz")
        if mode == "delete_meta":
            target = os.path.join(path, "meta.json")
            if os.path.exists(target):
                os.remove(target)
        elif mode == "truncate":
            size = os.path.getsize(target)
            keep = int(self._rng.integers(0, max(size, 1)))
            with open(target, "r+b") as fh:
                fh.truncate(keep)
        else:  # flip one byte
            size = os.path.getsize(target)
            off = int(self._rng.integers(0, max(size, 1)))
            with open(target, "r+b") as fh:
                fh.seek(off)
                b = fh.read(1)
                fh.seek(off)
                fh.write(bytes([(b[0] if b else 0) ^ 0xFF]))
        return self._record({"fault": "checkpoint_corrupt",
                             "plane": "checkpoint", "rows": [],
                             "mode": mode, "file": target})


def run_state_fault_matrix(encoder, auditor,
                           classes: Sequence[str] | None = None,
                           seed: int = 0) -> dict[str, dict]:
    """Drive the runtime fault classes (everything but
    ``checkpoint_corrupt``) against one encoder + auditor and report
    per-class ``{"injected", "detected", "repaired", "rung"}`` — the
    fault-detection matrix the acceptance criteria and the
    ``--suite integrity`` bench leg both consume."""
    from kubernetesnetawarescheduler_tpu.core.integrity import (
        compare_row_digests,
        host_row_digests,
    )

    injector = StateChaosInjector(encoder, seed=seed)
    kinds = [k for k in (classes or STATE_FAULT_CLASSES)
             if k != "checkpoint_corrupt"]
    results: dict[str, dict] = {}
    for kind in kinds:
        desc = injector.inject(kind)
        outcome = auditor.audit_once()
        detected = not outcome["clean"]
        # Bit-identity proof: after repair, the device digests must
        # equal a fresh host derivation of the expected view.
        with encoder._lock:
            state, _ = encoder.snapshot_versioned()
            expected = encoder.expected_device_arrays()
        from kubernetesnetawarescheduler_tpu.core.integrity import (
            device_row_digests,
        )

        dev = {k: np.asarray(v)
               for k, v in device_row_digests(state).items()}
        identical = not compare_row_digests(
            dev, host_row_digests(expected))
        results[kind] = {"injected": 1,
                         "detected": int(detected),
                         "repaired": int(outcome["repaired"]
                                         and identical),
                         "rung": outcome["rung"],
                         "descriptor": desc}
    return results
