"""State integrity: plane digests, the anti-entropy auditor, and the
automatic repair ladder.

Since r7 the scheduler's truth is *device-resident incremental state*:
the encoder keeps a host staging mirror and patches the HBM planes with
row/pair scatters (core/encode.py snapshot_versioned).  That design has
a failure class the reference scheduler could not even express: a
dropped or re-ordered delta patch, a NaN-poisoned probe row, or a
flipped bit in a device plane silently drifts the device view away from
staging truth, and every subsequent placement is wrong with no detector
anywhere.  This module closes that gap with three legs:

- **Detect** — :func:`device_row_digests` / :func:`host_row_digests`: a
  cheap per-plane rolling checksum (positionally weighted uint32
  wraparound sums over the raw bit patterns), computed identically by a
  jitted kernel over :class:`~.state.ClusterState` and by a numpy
  mirror over the encoder's staging arrays.  Bit-exact agreement is the
  invariant; disagreement localizes drift to (plane, row).  The fused
  scheduling step can fold the digest into its single donated dispatch
  (:func:`~.assign.fused_schedule_step` ``with_digest=True``) so the
  hot path pays zero extra dispatches for a running fingerprint.
- **Audit** — :class:`IntegrityAuditor`: a background anti-entropy
  thread that periodically flushes pending deltas, shadow-re-derives
  the expected device view from staging
  (:meth:`~.encode.Encoder.expected_device_arrays`) and compares
  digests.  Observation-only on clean runs: placements are bit-identical
  with the auditor on or off (tests/test_integrity.py pins this).
- **Repair** — an escalation ladder, cheapest rung first:
  row-level re-patch from staging -> full re-encode -> checkpoint
  restore -> apiserver relist.  Each rung is re-audited before the next
  is tried; per-rung counters feed ``/metrics``
  (``netaware_integrity_repairs_total{rung=...}``), escalations emit
  k8s Events, and a stuck-audit watchdog (drift surviving the whole
  ladder for ``watchdog_failures`` consecutive audits) triggers the r8
  flight-recorder ``crash_dump``.

Fault injection for all of this lives in core/state_chaos.py; the
offline twin (checkpoint vs decision-replay digests) in
tools/state_audit.py.
"""

from __future__ import annotations

import threading
import time
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubernetesnetawarescheduler_tpu.core.state import ClusterState

#: Every ClusterState plane, in checkpoint (_STATE_ARRAYS) order, with
#: the encoder dirty group whose transfer path owns it.  The digest
#: machinery iterates this — adding a plane to ClusterState without
#: registering it here fails test_integrity's coverage check.
PLANES: tuple[tuple[str, str], ...] = (
    ("metrics", "metrics"),
    ("metrics_age", "metrics"),
    ("lat", "net"),
    ("bw", "net"),
    ("cap", "alloc"),
    ("used", "alloc"),
    ("node_valid", "topo"),
    ("label_bits", "topo"),
    ("taint_bits", "topo"),
    ("group_bits", "alloc"),
    ("resident_anti", "alloc"),
    ("node_zone", "topo"),
    ("gz_counts", "alloc"),
    ("az_anti", "alloc"),
    ("node_numeric", "topo"),
)

PLANE_NAMES: tuple[str, ...] = tuple(name for name, _ in PLANES)
GROUP_OF: dict[str, str] = dict(PLANES)

#: Float planes where a non-finite STAGING value is itself corruption
#: (the ingest paths all validate; NaN here means something bypassed
#: them).  node_numeric is excluded on purpose — NaN is its legitimate
#: "label absent" sentinel.
_FINITE_PLANES = ("metrics", "metrics_age", "lat", "bw", "cap", "used")

#: The repair ladder, cheapest first.  Rung names are the
#: ``netaware_integrity_repairs_total{rung=...}`` label values.
REPAIR_RUNGS = ("repatch_rows", "full_reencode", "checkpoint_restore",
                "relist")


# ---------------------------------------------------------------------------
# Digest kernels — device (jitted) and host (numpy) mirrors.
#
# Per row: digest = sum_k u32(row[k]) * (2k + 1)  (mod 2^32).
# The raw BIT PATTERN is digested (float32 bitcast to uint32), so the
# comparison is bit-exact, not tolerance-based — the delta-ingest
# contract is bit-identity with a full re-upload, so any mismatch at
# all is drift.  Odd positional weights make the map value -> digest a
# bijection per element (multiplication by an odd number is invertible
# mod 2^32): a single flipped bit or swapped pair always moves the
# digest.
# ---------------------------------------------------------------------------


def _row_weights(width: int) -> np.ndarray:
    return (2 * np.arange(width, dtype=np.uint32) + np.uint32(1))


def _host_u32_rows(arr: np.ndarray) -> np.ndarray:
    a = np.ascontiguousarray(arr)
    if a.dtype == np.bool_:
        a = a.astype(np.uint32)
    elif a.dtype in (np.dtype(np.float32), np.dtype(np.int32)):
        a = a.view(np.uint32)
    elif a.dtype != np.dtype(np.uint32):
        a = a.astype(np.float32).view(np.uint32)
    return a.reshape(a.shape[0], -1)


def host_row_digest(arr: np.ndarray) -> np.ndarray:
    """``u32[rows]`` rolling digest of one host array."""
    u = _host_u32_rows(arr)
    w = _row_weights(u.shape[1])
    return np.sum(u * w[None, :], axis=1, dtype=np.uint32)


def host_row_digests(arrays: Mapping[str, np.ndarray]
                     ) -> dict[str, np.ndarray]:
    """Per-plane row digests of a host array set (the expected device
    view from :meth:`Encoder.expected_device_arrays`, or raw staging
    arrays for offline audits)."""
    return {name: host_row_digest(arrays[name]) for name in PLANE_NAMES
            if name in arrays}


def _dev_u32_rows(x: jax.Array) -> jax.Array:
    if x.dtype == jnp.bool_:
        u = x.astype(jnp.uint32)
    elif x.dtype in (jnp.dtype(jnp.float32), jnp.dtype(jnp.int32)):
        u = jax.lax.bitcast_convert_type(x, jnp.uint32)
    elif x.dtype == jnp.dtype(jnp.uint32):
        u = x
    else:
        # Narrow accelerator dtypes (bf16 planes): digest the f32
        # widening, matching the host mirror's fallback bit-for-bit.
        u = jax.lax.bitcast_convert_type(
            x.astype(jnp.float32), jnp.uint32)
    return u.reshape(x.shape[0], -1)


def _dev_row_digest(x: jax.Array) -> jax.Array:
    u = _dev_u32_rows(x)
    w = jnp.asarray(_row_weights(u.shape[1]))
    return jnp.sum(u * w[None, :], axis=1, dtype=jnp.uint32)


@jax.jit
def device_row_digests(state: ClusterState) -> dict[str, jax.Array]:
    """Per-plane ``u32[rows]`` digests of the device-resident state —
    ONE fused dispatch over every plane (the per-plane reductions fuse;
    the transfer back is ~sum(rows) u32, a few KB at N=5120)."""
    return {name: _dev_row_digest(getattr(state, name))
            for name in PLANE_NAMES}


def _fold_rows(rowd) -> np.ndarray:
    w = _row_weights(int(rowd.shape[0]))
    if isinstance(rowd, np.ndarray):
        return np.sum(rowd * w, dtype=np.uint32)
    return jnp.sum(rowd * jnp.asarray(w), dtype=jnp.uint32)


@jax.jit
def plane_digest_vector(state: ClusterState) -> jax.Array:
    """``u32[len(PLANES)]`` — one scalar digest per plane, the compact
    fingerprint the fused scheduling step folds into its donated chain
    (:func:`~.assign.fused_schedule_step` ``with_digest=True``)."""
    return jnp.stack([_fold_rows(_dev_row_digest(getattr(state, name)))
                      for name in PLANE_NAMES])


def host_plane_digest_vector(arrays: Mapping[str, np.ndarray]
                             ) -> np.ndarray:
    """Numpy mirror of :func:`plane_digest_vector`."""
    return np.stack([
        np.sum(host_row_digest(arrays[name])
               * _row_weights(arrays[name].shape[0]), dtype=np.uint32)
        for name in PLANE_NAMES])


def compare_row_digests(dev: Mapping[str, np.ndarray],
                        host: Mapping[str, np.ndarray]
                        ) -> dict[str, list[int]]:
    """Drift localization: plane -> sorted row indices whose digests
    disagree.  Empty dict == bit-identical state."""
    drift: dict[str, list[int]] = {}
    for name in PLANE_NAMES:
        if name not in dev or name not in host:
            continue
        d = np.asarray(dev[name])
        h = np.asarray(host[name])
        rows = np.flatnonzero(d != h)
        if rows.size:
            drift[name] = [int(r) for r in rows]
    return drift


def staging_sanity(arrays: Mapping[str, np.ndarray]
                   ) -> dict[str, list[int]]:
    """Rows of the HOST truth itself holding non-finite values in
    planes where that is corruption (every ingest path validates;
    see _FINITE_PLANES).  Device-vs-staging digests cannot see this
    case — both sides agree on the poison — so the auditor checks it
    separately and repairs from the checkpoint rung."""
    bad: dict[str, list[int]] = {}
    for name in _FINITE_PLANES:
        if name not in arrays:
            continue
        a = np.asarray(arrays[name])
        flat = a.reshape(a.shape[0], -1)
        rows = np.flatnonzero(~np.all(np.isfinite(flat), axis=1))
        if rows.size:
            bad[name] = [int(r) for r in rows]
    return bad


# ---------------------------------------------------------------------------
# The anti-entropy auditor + repair ladder.
# ---------------------------------------------------------------------------


class IntegrityAuditor:
    """Periodic device-vs-staging integrity audit with self-healing.

    ``audit_once`` is the whole cycle: flush pending deltas, compare
    digests, and if anything drifted walk the repair ladder, re-auditing
    after each rung.  ``start``/``stop`` run it on a daemon thread
    every ``interval_s`` (the serve.py ``--audit-interval`` flag).

    Clean-run bit-identity: a passing audit only ever calls
    ``snapshot_versioned()`` — the same flush the next scheduling cycle
    would perform, producing the same arrays by the delta-ingest
    bit-identity contract — so placements are unchanged by auditing.
    """

    def __init__(self, encoder, loop=None, *,
                 interval_s: float = 5.0,
                 checkpoint_dir: str | None = None,
                 watchdog_failures: int = 3,
                 crash_dump_path: str | None = None) -> None:
        self.encoder = encoder
        self.loop = loop
        self.interval_s = float(interval_s)
        self.checkpoint_dir = checkpoint_dir
        self.watchdog_failures = max(1, int(watchdog_failures))
        self.crash_dump_path = crash_dump_path
        # Counters (selfmetrics reads these; names mirror /metrics).
        self.audits_total = 0
        self.drift_detected_total = 0
        self.drift_rows_total = 0
        self.repairs = {rung: 0 for rung in REPAIR_RUNGS}
        self.unrepaired_total = 0
        self.watchdog_dumps = 0
        self.last_audit_ms = 0.0
        self.last_drift: dict[str, list[int]] = {}
        from collections import deque
        self.audit_ms: "deque[float]" = deque(maxlen=2048)
        self._unrepaired_streak = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- detect -------------------------------------------------------

    def check(self) -> tuple[dict[str, list[int]], dict[str, list[int]]]:
        """One detection pass: ``(device_drift, staging_corruption)``,
        both plane -> row lists (empty == clean).  Flushes pending
        deltas first so legitimate not-yet-shipped dirt is never
        reported as drift — "detected within one audit period" starts
        from a flushed baseline."""
        enc = self.encoder
        with enc._lock:
            state, _ = enc.snapshot_versioned()
            expected = enc.expected_device_arrays()
        dev = {k: np.asarray(v)
               for k, v in device_row_digests(state).items()}
        host = host_row_digests(expected)
        return compare_row_digests(dev, host), staging_sanity(expected)

    # -- repair rungs -------------------------------------------------

    def _rung_repatch_rows(self, drift: Mapping[str, Sequence[int]]
                           ) -> None:
        """Rung 1: re-scatter exactly the drifted rows from staging.
        Net drift re-ships the whole group — its delta protocol is
        (i, j) pairs, and a drifted ROW of an N x N matrix is already
        past the pair-scatter's break-even."""
        enc = self.encoder
        with enc._lock:
            for plane, rows in drift.items():
                group = GROUP_OF[plane]
                if group == "net":
                    enc._mark_full("net")
                else:
                    enc._mark_rows(group, *[int(r) for r in rows])
            enc.snapshot()

    def _rung_full_reencode(self) -> None:
        """Rung 2: drop the device cache and re-upload every plane
        from staging (the pre-delta full-transfer path)."""
        enc = self.encoder
        with enc._lock:
            enc._cache.clear()
            for group in enc._dirty:
                enc._mark_full(group)
            enc.snapshot()

    def _rung_checkpoint_restore(self) -> None:
        """Rung 3: overwrite the STAGING planes from the last good
        (manifest-verified) checkpoint, then full re-encode.  Repairs
        staging-side corruption rungs 1-2 cannot touch; the ledger and
        interners are left alone (rung 4's relist reconciles them
        against the apiserver if they too have drifted)."""
        if not self.checkpoint_dir:
            raise RuntimeError("no checkpoint directory configured")
        from kubernetesnetawarescheduler_tpu.core.checkpoint import (
            _STATE_ARRAYS,
            read_state_arrays,
        )

        arrays = read_state_arrays(self.checkpoint_dir)
        enc = self.encoder
        with enc._lock:
            for name in _STATE_ARRAYS:
                target = getattr(enc, name)
                stored = arrays[name.lstrip("_")]
                if stored.shape != target.shape:
                    raise ValueError(
                        f"checkpoint array {name} has shape "
                        f"{stored.shape}, expected {target.shape}")
                target[...] = stored
            enc._cache.clear()
            for group in enc._dirty:
                enc._mark_full(group)
            enc.snapshot()

    def _rung_relist(self) -> None:
        """Rung 4: apiserver relist (the r9 watch-gap audit) to repair
        ledger/node drift at the source of truth, then re-encode."""
        if self.loop is not None:
            self.loop.relist_audit()
        self._rung_full_reencode()

    def _apply_rung(self, rung: str,
                    drift: Mapping[str, Sequence[int]]) -> None:
        if rung == "repatch_rows":
            self._rung_repatch_rows(drift)
        elif rung == "full_reencode":
            self._rung_full_reencode()
        elif rung == "checkpoint_restore":
            self._rung_checkpoint_restore()
        elif rung == "relist":
            self._rung_relist()
        else:  # pragma: no cover - registry and ladder stay in sync
            raise ValueError(f"unknown repair rung {rung!r}")

    def _emit_event(self, message: str) -> None:
        loop = self.loop
        if loop is None or getattr(loop, "client", None) is None:
            return
        try:
            from kubernetesnetawarescheduler_tpu.k8s.types import Event

            loop.client.create_event(Event(
                message=message,
                reason="StateIntegrity",
                involved_pod=loop.cfg.scheduler_name,
                namespace="default",
                component=loop.cfg.scheduler_name,
                type="Warning"))
        except Exception:  # noqa: BLE001 — events are best-effort
            pass

    # -- the audit cycle ----------------------------------------------

    def audit_once(self) -> dict:
        """Detect + repair.  Returns a summary dict:
        ``{"clean", "drift", "staging", "rung", "repaired"}``."""
        t0 = time.perf_counter()
        self.audits_total += 1
        drift, staging_bad = self.check()
        out = {"clean": not drift and not staging_bad,
               "drift": drift, "staging": staging_bad,
               "rung": None, "repaired": True}
        if not out["clean"]:
            self.drift_detected_total += 1
            self.drift_rows_total += sum(
                len(r) for r in drift.values()) + sum(
                len(r) for r in staging_bad.values())
            self.last_drift = {**drift,
                               **{f"staging:{k}": v
                                  for k, v in staging_bad.items()}}
            out.update(self._repair(drift, staging_bad))
        if out["repaired"]:
            self._unrepaired_streak = 0
        else:
            self._unrepaired_streak += 1
            if self._unrepaired_streak >= self.watchdog_failures:
                self._watchdog_fire()
        dt_ms = (time.perf_counter() - t0) * 1e3
        self.last_audit_ms = dt_ms
        self.audit_ms.append(dt_ms)
        return out

    def _repair(self, drift: dict, staging_bad: dict) -> dict:
        for i, rung in enumerate(REPAIR_RUNGS):
            if (rung == "checkpoint_restore"
                    and not self.checkpoint_dir):
                continue
            if rung == "relist" and self.loop is None:
                # A bare-encoder auditor has no apiserver to relist
                # against; full_reencode is then its top rung.
                continue
            try:
                self._apply_rung(rung, drift)
            except Exception:  # noqa: BLE001 — a failing rung (e.g. a
                # corrupt checkpoint refused by its manifest) escalates
                # to the next one instead of killing the audit thread.
                continue
            drift, staging_bad = self.check()
            if not drift and not staging_bad:
                self.repairs[rung] += 1
                if i > 0:
                    self._emit_event(
                        f"state drift repaired at rung '{rung}' "
                        f"(escalated past {i} cheaper rung(s))")
                return {"rung": rung, "repaired": True,
                        "drift": {}, "staging": {}}
        self.unrepaired_total += 1
        self._emit_event(
            "state drift UNREPAIRED after full ladder: "
            + ", ".join(sorted(set(drift) | {f"staging:{k}"
                                             for k in staging_bad})))
        return {"rung": None, "repaired": False,
                "drift": drift, "staging": staging_bad}

    def _watchdog_fire(self) -> None:
        """Stuck-audit watchdog: drift has survived the whole ladder
        for ``watchdog_failures`` consecutive audits — dump the flight
        recorder for the post-mortem (once per streak)."""
        if self._unrepaired_streak != self.watchdog_failures:
            return  # fire once per streak, not every audit after
        self.watchdog_dumps += 1
        loop = self.loop
        flight = getattr(loop, "flight", None) if loop else None
        if flight is not None and self.crash_dump_path:
            try:
                flight.crash_dump(
                    self.crash_dump_path, reason="stuck_audit",
                    extra={"drift": {k: list(v) for k, v
                                     in self.last_drift.items()},
                           "unrepaired_streak":
                               self._unrepaired_streak,
                           "repairs": dict(self.repairs)})
            except Exception:  # noqa: BLE001 — the dump is best-effort
                pass

    # -- background thread --------------------------------------------

    def start(self) -> None:
        """Run :meth:`audit_once` every ``interval_s`` on a daemon
        thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="integrity-audit", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.audit_once()
            except Exception:  # noqa: BLE001 — a wedged audit must not
                # kill the daemon; the next tick retries and the
                # watchdog counters surface persistent failure.
                pass

    def stop(self, timeout: float | None = 10.0) -> None:
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout)
        self._thread = None
