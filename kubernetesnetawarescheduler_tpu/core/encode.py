"""Host-side encoding: Kubernetes objects -> device arrays.

The bridge between the object world (:mod:`..k8s.types`) and the
columnar device state (:mod:`.state`).  This is where the reference's
per-pod scrape-and-parse loop (scheduler.go:275-331) becomes an
asynchronous staging buffer: telemetry updates land in pinned NumPy
staging arrays, and :meth:`Encoder.snapshot` transfers only the dirty
field groups to the device, so a scheduling cycle never waits on a
scrape and never re-uploads the big ``N x N`` matrices unless they
changed.

String sets (labels, taints, affinity groups) are interned to bit
positions so feasibility checks are bitmask algebra on device.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable, Mapping, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubernetesnetawarescheduler_tpu.config import (
    Metric,
    Resource,
    SchedulerConfig,
)
from kubernetesnetawarescheduler_tpu.core.gang import gang_key_of
from kubernetesnetawarescheduler_tpu.core.state import ClusterState, PodBatch
from kubernetesnetawarescheduler_tpu.k8s.types import Node, Pod

# Past this many dirty indices the per-index bookkeeping costs more
# than it saves; the group collapses to the "full" sentinel.
_DELTA_MAX_INDICES = 65536


@jax.jit
def _scatter_rows(dev, idx, vals):
    """Patch rows ``idx`` of a device-resident array.  NOT donated:
    previously returned snapshots alias the old buffer and must stay
    readable (the serving loop may still be scoring against them)."""
    return dev.at[idx].set(vals)


@jax.jit
def _scatter_pairs(dev, ii, jj, vals):
    """Patch elements ``(ii, jj)`` of a device-resident matrix (same
    aliasing contract as :func:`_scatter_rows`)."""
    return dev.at[ii, jj].set(vals)


def _pad_pow2(idx: np.ndarray) -> np.ndarray:
    """Pad an index vector to the next power of two by repeating its
    first element (duplicate scatter indices carrying the same value
    are safe for ``.set``), bounding jit recompiles to O(log n) index
    shapes per (array shape, dtype)."""
    n = len(idx)
    cap = 1
    while cap < n:
        cap *= 2
    if cap == n:
        return idx
    return np.concatenate([idx, np.full(cap - n, idx[0], idx.dtype)])


# The top bit of the last mask word is reserved: never assigned to a
# real key, so a mask carrying it can never be satisfied by any node.
# Lenient interning uses it to keep un-internable *requirements*
# conservative (infeasible) instead of silently weakened.
# Plain Python ints throughout the interning path (arbitrary precision
# — a mask spanning ``mask_words`` uint32 words is still ONE int here;
# the split into word arrays happens at array-store time): numpy scalar
# construction is ~10x a Python int op and this runs 5x per pod on the
# encode fast path.
def unknown_bit(words: int) -> int:
    """The reserved can-never-match sentinel for a ``words``-wide mask."""
    return 1 << (32 * words - 1)


# Back-compat alias for the single-word layout (tests, extender docs).
UNKNOWN_BIT = unknown_bit(1)


def int_to_words(x: int, words: int) -> np.ndarray:
    """Split an arbitrary-precision mask into ``words`` uint32 words
    (little-endian: word 0 holds bits 0..31)."""
    return np.fromiter(((x >> (32 * i)) & 0xFFFFFFFF
                        for i in range(words)), np.uint32, words)


def words_to_int(arr) -> int:
    """Inverse of :func:`int_to_words` (accepts any uint32 sequence)."""
    out = 0
    for i, w in enumerate(arr):
        out |= int(w) << (32 * i)
    return out


def _fill_words(row: np.ndarray, x: int) -> None:
    """Write mask ``x`` into a preallocated uint32 word row in place
    (allocation-free variant of :func:`int_to_words` for hot paths)."""
    for i in range(row.shape[0]):
        row[i] = (x >> (32 * i)) & 0xFFFFFFFF


class Interner:
    """Stable string -> bit-position mapping over ``32 * words - 1``
    assignable bits.

    Strict interning (trusted, self-inflicted paths: node
    registration) raises when the slot space is exhausted.  Paths fed
    by untrusted manifests — the watch-driven scheduling loop, the
    extender webhook, and the bind-time commit — pass ``lenient=True``:
    an unknown-when-full key yields ``on_overflow`` — callers choose
    the conservative direction for their constraint (``self.unknown``
    for must-match requirements, 0 for grants like tolerations) — so
    one exotic manifest degrades only its own request (recorded per
    pod for a ConstraintDegraded event) instead of raising and taking
    the whole batch's cycle down with it."""

    def __init__(self, kind: str, words: int = 1) -> None:
        self._kind = kind
        self.words = words
        self.max_keys = 32 * words - 1
        self.unknown = unknown_bit(words)
        self._bits: dict[str, int] = {}
        self.overflow_drops = 0

    def bit(self, key: str, lenient: bool = False,
            on_overflow: int = 0) -> int:
        b = self._bits.get(key)
        if b is None:
            if len(self._bits) >= self.max_keys:
                if lenient:
                    self.overflow_drops += 1
                    return on_overflow
                raise ValueError(
                    f"too many distinct {self._kind} keys "
                    f"(max {self.max_keys}; raise cfg.mask_words to "
                    f"widen): cannot intern {key!r}")
            b = len(self._bits)
            self._bits[key] = b
        return 1 << b

    def mask(self, keys: Iterable[str], lenient: bool = False,
             on_overflow: int = 0) -> int:
        out = 0
        for key in keys:
            out |= self.bit(key, lenient=lenient, on_overflow=on_overflow)
        return out


class PreparedStream(NamedTuple):
    """Encode-ahead product of :meth:`Encoder.encode_stream_prepare`:
    host numpy arrays with every field filled EXCEPT peer slots, which
    :meth:`Encoder.finalize_stream` resolves against live placements
    just before dispatch."""

    pods: tuple
    arrays: dict
    stream_index: dict
    pristine: dict

    def __len__(self) -> int:
        return len(self.pods)


def _stream_index(pods: Sequence[Pod]) -> dict[str, int]:
    """Indexed under both the bare name and "namespace/name": fake
    workloads reference peers by bare name, KubeClient-sourced pods
    carry namespace-qualified references."""
    idx = {pod.name: i for i, pod in enumerate(pods)}
    idx.update({f"{pod.namespace}/{pod.name}": i
                for i, pod in enumerate(pods)})
    return idx


def _stream_slice(ar: Mapping[str, np.ndarray], a: int, b: int):
    from kubernetesnetawarescheduler_tpu.core.replay import PodStream

    return PodStream(**{name: jnp.asarray(arr[a:b])
                        for name, arr in ar.items()})


def _res_names(r: int) -> list[tuple[int, str]]:
    """Pre-enumerated resource names for allocation-free row fills."""
    return list(enumerate(Resource.NAMES[:r]))


def _fill_requests_row(row: np.ndarray, requests: Mapping[str, float],
                       res_names: list[tuple[int, str]]) -> None:
    """Write one pod's resource requests into ``row`` in place — the
    single source of truth for request→vector mapping (shared by batch
    encode, stream encode and usage accounting)."""
    for j, name in res_names:
        row[j] = requests.get(name, 0.0)


def _requests_vector(requests: Mapping[str, float], r: int) -> np.ndarray:
    vec = np.zeros((r,), np.float32)
    _fill_requests_row(vec, requests, _res_names(r))
    return vec


def selector_matches(sel_def: tuple, labels: frozenset) -> bool:
    """Evaluate a canonical labelSelector structure against a pod's
    ``k=v`` label strings — Kubernetes ``LabelSelector`` semantics
    (apimachinery ``labels.Requirement``): matchLabels AND; In needs
    the key present with a listed value; NotIn passes when the key is
    absent OR its value is unlisted; Exists/DoesNotExist test key
    presence.  ``sel_def`` is ``(((k, v), ...), ((op, key, values),
    ...))`` with both banks sorted (the canonical form the kubeclient
    parser emits)."""
    match_labels, exprs = sel_def
    for k, v in match_labels:
        if f"{k}={v}" not in labels:
            return False
    if exprs:
        keys = {s.split("=", 1)[0] for s in labels}
        for op, key, values in exprs:
            if op == "In":
                if not any(f"{key}={v}" in labels for v in values):
                    return False
            elif op == "NotIn":
                if any(f"{key}={v}" in labels for v in values):
                    return False
            elif op == "Exists":
                if key not in keys:
                    return False
            elif op == "DoesNotExist":
                if key in keys:
                    return False
            else:
                return False
    return True


class CommitRecord(NamedTuple):
    """One usage-ledger entry: everything needed to reverse a commit
    (node + request vector + group/anti bits), reconcile it (stamp),
    and consider the pod as a preemption victim (priority +
    identity)."""

    node: int
    req: np.ndarray
    stamp: float
    priority: float
    namespace: str
    name: str
    group_bit: int = 0
    anti_bits: int = 0
    # Annotation-level PDB: minimum live members of this pod's group
    # (0 = unprotected).  Preemption planning consumes this.
    pdb_min: int = 0
    # Topology-spread accounting (AFTER pdb_min: several callers build
    # records positionally): the group's bit-slot index and the node's
    # zone AT COMMIT TIME (node slots can be reused; the zone recorded
    # here is the one the count was added under).
    group_slot: int = -1
    zone: int = -1
    # Zone-scoped anti-affinity mask this pod declared (symmetric
    # residency recorded under ``zone``; 0 = none).
    zanti_bits: int = 0
    # FULL group-membership mask (annotation group bit | every
    # registered selector-group the pod's labels satisfy).  0 on
    # records restored from pre-v5 checkpoints — release/gz paths
    # fall back to ``group_bit``/``group_slot`` then.
    member_bits: int = 0
    # The pod's labels at commit time, kept so a selector-group
    # registered LATER can claim this resident retroactively
    # (register_selectors).  ``None`` = unknown (pre-v5 restore) —
    # such residents are never retro-claimed; an EMPTY set is a
    # genuinely label-less pod, which negative selectors (NotIn /
    # DoesNotExist) do match.
    labels: frozenset | None = None
    # Gang membership: the ``namespace/pod-group`` key this pod was
    # committed under ("" = not gang-scheduled).  Preemption consumes
    # this to expand one victim into its whole gang (all-or-nothing
    # holds for eviction too), and the loop uses it to release the
    # rest of a gang when a member vanishes.
    gang_key: str = ""


class Encoder:
    """Owns the staging buffers and the node/pod index maps."""

    def __init__(self, cfg: SchedulerConfig) -> None:
        self.cfg = cfg
        n, m, r = cfg.max_nodes, cfg.num_metrics, cfg.num_resources
        w = cfg.mask_words
        self.labels = Interner("label", w)
        self.taints = Interner("taint", w)
        self.groups = Interner("group", w)
        # labelSelector-parity group machinery: group key -> canonical
        # selector structure (see :func:`selector_matches`).  A pod is
        # a member of every registered selector its LABELS satisfy —
        # no annotation opt-in required (kube semantics; the
        # ``netaware.io/group`` annotation remains an additional,
        # label-free membership surface).  ``_selector_gen`` bumps on
        # every new registration so shape-cache entries computed
        # against an older registry can never serve stale memberships.
        self._selector_defs: dict[str, tuple] = {}
        self._selector_gen = 0
        # Live committed members per group bit-slot (cluster-wide):
        # backs the first-pod escape hatch (a required affinity term
        # whose group has NO member anywhere is waived for the first
        # self-member pod, like kube-scheduler's special case).
        self._group_member_counts = np.zeros((32 * w,), np.int64)
        # Real policy/v1 PodDisruptionBudgets (uid -> the reduced
        # object), consumed by the preemption planner beside the
        # annotation-level surface.
        self._pdbs: dict[str, object] = {}
        self._node_index: dict[str, int] = {}
        self._node_names: list[str] = []
        # Slots freed by remove_node, reused FIFO (oldest-freed first).
        # _node_gen[i] increments on every removal, so an in-flight
        # scheduling cycle that captured the pre-removal name table
        # (node_table()) can detect that slot i now means a different
        # node and drop the stale commit instead of booking usage onto
        # the wrong node.  _node_stamp[i] is the registration time
        # (monotonic) guarding the reconcile race where a node is
        # registered after a list_nodes() snapshot was taken.
        self._free_slots: "list[int]" = []
        self._node_gen: "list[int]" = []
        self._node_stamp: "list[float]" = []
        self._lock = threading.RLock()

        # Lazy label interning: a node's raw label strings live here;
        # only strings some pod's selector references are ever given a
        # bit (so per-node-unique labels like kubernetes.io/hostname
        # never consume slots — the reference-scale failure mode of
        # interning everything eagerly was a hard crash at node #32).
        # _label_nodes is the reverse map used to backfill the bit
        # column when a selector first references an existing label.
        self._node_labels: dict[int, frozenset[str]] = {}
        self._label_nodes: dict[str, set[int]] = {}
        # Key-presence reverse map for nodeAffinity Exists /
        # DoesNotExist: label KEY -> nodes carrying any value of it.
        # Presence bits intern in the same label table under the bare
        # key (collision-free: full label strings always contain '=').
        self._label_keys: dict[str, set[int]] = {}
        # Numeric nodeAffinity (Gt/Lt): label KEY -> column of the
        # parsed-value table below (NaN = absent/non-numeric, failing
        # every comparison).  Columns intern on first Gt/Lt reference,
        # backfilling values for nodes already carrying the key.
        self._numeric_keys: dict[str, int] = {}
        self._node_numeric = np.full((n, cfg.max_numeric_labels),
                                     np.nan, np.float32)

        # Staging (host) arrays — mirror of ClusterState fields.
        self._metrics = np.zeros((n, m), np.float32)
        self._metrics_age = np.full((n,), 1e9, np.float32)  # unseen = stale
        self._lat = np.zeros((n, n), np.float32)
        self._bw = np.zeros((n, n), np.float32)
        self._cap = np.zeros((n, r), np.float32)
        self._used = np.zeros((n, r), np.float32)
        self._node_valid = np.zeros((n,), bool)
        self._label_bits = np.zeros((n, w), np.uint32)
        self._taint_bits = np.zeros((n, w), np.uint32)
        self._group_bits = np.zeros((n, w), np.uint32)
        self._resident_anti = np.zeros((n, w), np.uint32)
        # Topology spread: interned zone per node (-1 unknown) and the
        # per-(group bit-slot, zone) scheduled-pod counts — the
        # resident state behind topologySpreadConstraints.
        self._node_zone = np.full((n,), -1, np.int32)
        self._zone_index: dict[str, int] = {}
        self._gz_counts = np.zeros((32 * w, self.cfg.max_zones),
                                   np.int32)
        # Per-(node, bit) member counts behind _group_bits /
        # _resident_anti: a bit clears only when its count hits zero
        # (precise release; see release()).
        self._group_refs = np.zeros((n, 32 * w), np.int32)
        self._anti_refs = np.zeros((n, 32 * w), np.int32)
        # Zone-scoped symmetric anti-affinity residency: per-ZONE OR of
        # resident pods' zone-anti masks, refcounted like _anti_refs so
        # a bit clears only when its last declaring member leaves.
        self._az_anti = np.zeros((cfg.max_zones, w), np.uint32)
        self._az_anti_refs = np.zeros((cfg.max_zones, 32 * w), np.int32)

        # Usage ledger: uid -> CommitRecord; release() reverses exactly
        # what commit recorded (see the allocation section), and the
        # preemption planner reads it to find victims.  _early_releases
        # marks pods whose termination beat their commit — an
        # insertion-ordered dict used as a set, so bounding evicts
        # oldest-first (release()).
        self._committed: dict[str, CommitRecord] = {}
        self._early_releases: dict[str, None] = {}
        # Gangs whose members are ASSUMED (usage committed) but whose
        # all-or-nothing bind has not confirmed: gang key -> member
        # [uid, namespace, name, node_name] entries.  Persisted by the
        # checkpoint so a crash inside the bind window rolls the whole
        # gang back deterministically on restore (no member of a gang
        # may survive in the ledger without the rest).
        self._inflight_gangs: dict[str, list[list]] = {}
        # Live-migration ledger (core/rebalance.py): moves staged
        # between evict and re-bind, persisted by checkpoints so a
        # crash mid-move restores fully-moved-or-fully-reverted.
        self._inflight_migrations: dict[str, list[list]] = {}
        # Elastic-reshape ledger (r17): gangs mid-reshape, staged
        # between the first member eviction and the last re-pin.
        # gang key -> [old_count, new_count, member entries] where
        # each member entry is [uid, namespace, name, from_node,
        # to_node] (to_node "" = the member is DROPPED by the new
        # shape).  Persisted by checkpoints so a crash mid-reshape
        # restores fully-old-shape-or-fully-new-shape, never a hybrid.
        self._inflight_reshapes: dict[str, list] = {}
        # Committed realization per gang: gang key -> [chosen_count,
        # declared_count].  Written when a shaped gang commits or a
        # reshape completes; read by the checkpoint meta and audited
        # by tools/state_audit.py against the committed ledger.
        self._gang_realizations: dict[str, list[int]] = {}

        # Nominations (kube's nominatedNodeName analog): a preemptor
        # whose victims are terminating holds a capacity reservation on
        # its target node so the freed space is not stolen by the next
        # batch.  _reserved is added to `used` in snapshot(); the hold
        # is dropped when the preemptor is encoded for scoring (its own
        # request takes over), commits, or expires.
        self._nominations: dict[str, tuple[int, np.ndarray, float]] = {}
        self._reserved = np.zeros((n, r), np.float32)
        # Victims whose graceful deletion is in flight (delete accepted,
        # DELETED not yet confirmed).  The preemption planner treats
        # them as already gone: not victim candidates again, not live
        # members for PDB min-available accounting.
        self._terminating: set[str] = set()

        # Constraint-shape cache for the encode hot path (see
        # _pod_constraint_rows).  _degrade_capture, when not None,
        # accumulates _record_degraded counts so a cache entry stores
        # the shape's true per-pod degradation regardless of event
        # dedup or the bounded record deque.
        self._shape_cache: dict[tuple, tuple] = {}
        self._degrade_capture: int | None = None
        self.shape_cache_hits = 0
        self.shape_cache_misses = 0

        # Optional learned topology model (netmodel.TopologyModel):
        # when attached AND enabled, the net snapshot group uploads the
        # confidence-blended matrices instead of the raw probe staging
        # arrays.  None/disabled leaves the net path bit-identical.
        self.netmodel = None

        # Dirty tracking per transfer group, so snapshot() uploads the
        # 100 MB-class N x N matrices only when the probe pipeline
        # actually moved them.
        self._dirty = {"metrics": True, "net": True, "alloc": True,
                       "topo": True}
        # Per-group dirty INDEX sets refining the booleans above: node
        # rows for metrics/alloc/topo, (i, j) element pairs for net.
        # ``None`` is the "full" sentinel — the whole group must be
        # re-uploaded (bulk rewrite, overflow past _DELTA_MAX_INDICES,
        # or a mutation whose footprint isn't row-shaped).  Start full:
        # the first snapshot has no device cache to patch.
        self._dirty_rows: dict = {"metrics": None, "alloc": None,
                                  "topo": None}
        self._dirty_pairs: "set | None" = None
        self._cache: dict[str, jnp.ndarray] = {}
        # Host->device transfer accounting for the delta-ingest path
        # (bytes actually shipped, padded scatter payloads included).
        self.snapshot_delta_bytes_total = 0
        self.snapshot_full_bytes_total = 0
        # Monotonic counter of static-score-input rebuilds (metrics/
        # net/topo snapshot groups); see snapshot() and
        # static_version.
        self._static_version = 0
        # Per-version delta descriptors for static consumers
        # (static_delta_since): one entry per static_version bump,
        # capturing which static groups moved and, for net, WHICH
        # (i, j) pairs (None = full).  Bounded: a consumer more than
        # maxlen versions behind gets a gap -> full rebuild.
        from collections import deque as _deque
        self._static_deltas: "_deque" = _deque(maxlen=128)
        # Pods whose constraints were degraded by interner overflow
        # ((namespace, name, dropped_count) tuples, bounded), drained
        # by the loop into per-pod Warning events.  ``_degraded_seen``
        # dedupes per pod identity: dropped keys are never cached by
        # the Interner, so the same pod re-drops at commit and on
        # every retry cycle — without the guard that is one Warning
        # event per cycle forever.
        from collections import deque
        self._degraded_pods: deque = deque(maxlen=256)
        self._degraded_seen: set[tuple[str, str]] = set()
        self.degraded_total = 0  # distinct pods degraded (self-metrics)

    def pop_degraded(self) -> list[tuple[str, str, int, tuple]]:
        """Drain the constraint-degradation records
        (``(namespace, name, dropped_count, detail_strings)``)
        accumulated since the last call — see
        :meth:`_constraint_bits`.  ``detail_strings`` names the
        parse-time term drops (e.g. which anti-affinity term stopped
        being enforced), so operators get term-level diagnostics, not
        just a count."""
        with self._lock:
            out = list(self._degraded_pods)
            self._degraded_pods.clear()
        return out

    @property
    def static_version(self) -> int:
        """Monotonic version of the batch-invariant score inputs
        (metrics, lat/bw, node validity/labels/taints).  Serving paths
        may cache derived static scores as long as this is unchanged;
        placement commits (the ``alloc`` group) do NOT bump it."""
        return self._static_version

    # -- nodes --------------------------------------------------------

    def node_index(self, name: str) -> int:
        return self._node_index[name]

    def node_slot(self, name: str) -> int | None:
        """Slot index of ``name``, or None if unregistered (probe
        threads hold target lists that can lag a node removal)."""
        with self._lock:
            return self._node_index.get(name)

    def node_name(self, index: int) -> str:
        return self._node_names[index]

    @property
    def num_nodes(self) -> int:
        return len(self._node_names)

    def upsert_node(self, node: Node) -> int:
        """Register or refresh a node; returns its index.

        Labels are NOT interned here (lazy interning): the raw strings
        are recorded in ``_node_labels``/``_label_nodes`` and the bit
        row carries only labels already referenced by some pod's
        selector.  Eager interning of every label crashed real clusters
        around node #32 (per-node-unique ``kubernetes.io/hostname=…``
        labels exhausting the slot space)."""
        with self._lock:
            idx = self._node_index.get(node.name)
            if idx is None:
                if self._free_slots:
                    idx = self._free_slots.pop(0)
                    self._node_names[idx] = node.name
                elif len(self._node_names) >= self.cfg.max_nodes:
                    raise ValueError(
                        f"cluster exceeds max_nodes={self.cfg.max_nodes}")
                else:
                    idx = len(self._node_names)
                    self._node_names.append(node.name)
                    self._node_gen.append(0)
                    self._node_stamp.append(0.0)
                self._node_index[node.name] = idx
                self._node_stamp[idx] = time.monotonic()
            self._cap[idx] = _requests_vector(node.capacity,
                                              self.cfg.num_resources)
            # A cordoned (spec.unschedulable) node drops out of every
            # mask exactly like an unready one — running pods keep
            # their usage, new pods don't land.
            self._node_valid[idx] = node.ready and not node.unschedulable
            self._set_node_labels(idx, node.labels)
            # Node taints ARE eager: every taint must be representable
            # or pods lacking a toleration could slip on (the
            # conservative direction is a bit no pod tolerates, which
            # is exactly what a fresh bit is until granted).
            _fill_words(self._taint_bits[idx],
                        self.taints.mask(node.taints))
            self._node_zone[idx] = self._intern_zone(node)
            self._mark_rows("topo", idx)
            self._mark_rows("alloc", idx)
            return idx

    def _intern_zone(self, node: Node) -> int:
        """Topology domain id for a node (caller holds the lock):
        ``Node.zone`` or its ``topology.kubernetes.io/zone=`` label.
        -1 when absent or past ``max_zones`` — such nodes are invisible
        to spread constraints (degrades open, never crashes)."""
        zone = node.zone
        if not zone:
            for s in node.labels:
                if s.startswith("topology.kubernetes.io/zone="):
                    zone = s.split("=", 1)[1]
                    break
        if not zone:
            return -1
        zi = self._zone_index.get(zone)
        if zi is None:
            if len(self._zone_index) >= self.cfg.max_zones:
                return -1
            zi = len(self._zone_index)
            self._zone_index[zone] = zi
        return zi

    def _set_node_labels(self, idx: int, labels: Iterable[str]) -> None:
        """Record a node's raw label set and rebuild its bit row from
        the already-interned subset (caller holds the lock)."""
        new = frozenset(labels)
        old = self._node_labels.get(idx, frozenset())
        if new != old:
            for s in old - new:
                nodes = self._label_nodes.get(s)
                if nodes is not None:
                    nodes.discard(idx)
                    if not nodes:
                        del self._label_nodes[s]
            for s in new - old:
                self._label_nodes.setdefault(s, set()).add(idx)
            old_keys = {s.split("=", 1)[0] for s in old}
            new_keys = {s.split("=", 1)[0] for s in new}
            for key in old_keys - new_keys:
                nodes = self._label_keys.get(key)
                if nodes is not None:
                    nodes.discard(idx)
                    if not nodes:
                        del self._label_keys[key]
            for key in new_keys - old_keys:
                self._label_keys.setdefault(key, set()).add(idx)
            self._node_labels[idx] = new
        table = self.labels._bits
        bits = 0
        for s in new:
            b = table.get(s)
            if b is not None:
                bits |= 1 << b
            # Presence bit (Exists/DoesNotExist): interned under the
            # bare key, set whenever the node carries ANY value of it.
            kb = table.get(s.split("=", 1)[0])
            if kb is not None:
                bits |= 1 << kb
        _fill_words(self._label_bits[idx], bits)
        # Numeric Gt/Lt table: refresh this node's value for every
        # registered numeric key (label updates can change them).
        for key, col in self._numeric_keys.items():
            self._node_numeric[idx, col] = self._parse_numeric_label(
                new, key)

    @staticmethod
    def _parse_numeric_label(labels, key: str) -> float:
        """The node's value for ``key`` as a float (NaN when absent or
        non-numeric — kube's Gt/Lt fail on both)."""
        prefix = key + "="
        for s in labels:
            if s.startswith(prefix):
                try:
                    return float(s[len(prefix):])
                except ValueError:
                    return float("nan")
        return float("nan")

    def _numeric_col(self, key: str, lenient: bool) -> int | None:
        """Column of the numeric-value table for label ``key``,
        interning (and backfilling every node already carrying the
        key) on first sight.  ``None`` on lenient overflow — the
        caller degrades the term CLOSED.  Caller holds the lock."""
        col = self._numeric_keys.get(key)
        if col is not None:
            return col
        if len(self._numeric_keys) >= self.cfg.max_numeric_labels:
            if lenient:
                return None
            raise ValueError(
                f"too many numeric nodeAffinity keys "
                f"(max {self.cfg.max_numeric_labels}; raise "
                f"cfg.max_numeric_labels): cannot intern {key!r}")
        col = len(self._numeric_keys)
        self._numeric_keys[key] = col
        for idx in self._label_keys.get(key, ()):
            self._node_numeric[idx, col] = self._parse_numeric_label(
                self._node_labels.get(idx, ()), key)
        self._mark_rows("topo", *self._label_keys.get(key, ()))
        return col

    def _selector_mask(self, keys: Iterable[str], lenient: bool) -> int:
        """Intern a pod selector's label keys, backfilling the bit of a
        newly-interned label onto every node that already carries it
        (caller holds the lock).  Overflow degrades to the UNKNOWN
        sentinel: a selector we cannot represent matches nowhere rather
        than everywhere."""
        table = self.labels._bits
        out = 0
        for key in keys:
            known = key in table
            b = self.labels.bit(key, lenient,
                                on_overflow=self.labels.unknown)
            out |= b
            if not known and key in table:
                carriers = self._label_nodes.get(key)
                if carriers:
                    word, pos = divmod(table[key], 32)
                    for idx in carriers:
                        self._label_bits[idx, word] |= np.uint32(1 << pos)
                    self._mark_rows("topo", *carriers)
        return out

    def _presence_mask(self, keys: Iterable[str], lenient: bool) -> int:
        """Intern label-KEY presence bits (nodeAffinity Exists /
        DoesNotExist), backfilling a newly-interned key onto every node
        that already carries any value of it (caller holds the lock).
        Same overflow direction as :meth:`_selector_mask`: UNKNOWN, so
        an unrepresentable presence requirement matches nowhere."""
        table = self.labels._bits
        out = 0
        for key in keys:
            known = key in table
            b = self.labels.bit(key, lenient,
                                on_overflow=self.labels.unknown)
            out |= b
            if not known and key in table:
                carriers = self._label_keys.get(key)
                if carriers:
                    word, pos = divmod(table[key], 32)
                    for idx in carriers:
                        self._label_bits[idx, word] |= np.uint32(1 << pos)
                    self._mark_rows("topo", *carriers)
        return out

    def mark_unready(self, name: str) -> None:
        """Failure detection hook: an unready node drops out of every
        mask without resizing anything.  Unknown names are ignored —
        scrape/probe threads hold target lists that can lag a node
        removal, and a KeyError here would kill the ingest thread."""
        with self._lock:
            idx = self._node_index.get(name)
            if idx is None:
                return
            self._node_valid[idx] = False
            self._mark_rows("topo", idx)

    def remove_node(self, name: str) -> None:
        """Node DELETED: free the slot for reuse.

        The reference was blind to node removal (scheduler.go:175-184
        logs node ADDs only), and round 1 of this build leaked slots
        until ``max_nodes`` — fatal for a long-running daemon on a
        churning cluster.  Everything the node carried is cleared:
        telemetry, lat/bw row+column, capacity/usage, constraint bits,
        refcounts, the label reverse map, and every usage-ledger entry
        for pods that lived there (their node is gone; the watch will
        also deliver their deletions, which then no-op as early-release
        markers).  Unknown names are ignored (duplicate DELETED
        delivery)."""
        with self._lock:
            idx = self._node_index.pop(name, None)
            if idx is None:
                return
            # Release ledger entries bound to this node BEFORE zeroing
            # usage (release subtracts; the zeroing below makes the
            # order moot, but the refcount arrays must agree).
            for uid in [u for u, rec in self._committed.items()
                        if rec.node == idx]:
                self._gz_sub(self._committed[uid])
                del self._committed[uid]
                self._terminating.discard(uid)
            for uid in [u for u, (i, _, _) in self._nominations.items()
                        if i == idx]:
                self._drop_nomination_locked(uid)
            self._metrics[idx] = 0.0
            self._metrics_age[idx] = 1e9
            self._reserved[idx] = 0.0
            self._lat[idx, :] = 0.0
            self._lat[:, idx] = 0.0
            self._bw[idx, :] = 0.0
            self._bw[:, idx] = 0.0
            if self.netmodel is not None:
                # Slot reuse must not inherit the old node's learned
                # coordinates/factors (lock order: encoder, then
                # model — the model never calls back in).
                self.netmodel.reset_node(idx)
            self._cap[idx] = 0.0
            self._used[idx] = 0.0
            self._node_valid[idx] = False
            self._set_node_labels(idx, ())
            self._node_labels.pop(idx, None)
            self._taint_bits[idx] = 0
            self._group_bits[idx] = 0
            self._resident_anti[idx] = 0
            self._group_refs[idx] = 0
            self._anti_refs[idx] = 0
            self._node_zone[idx] = -1
            self._node_names[idx] = ""
            self._node_gen[idx] += 1
            self._free_slots.append(idx)
            # Row-shaped dirt for the row groups; the net clear is a
            # full row AND column — rare enough (node DELETED) that a
            # full net re-upload beats tracking 2N pairs.
            self._mark_rows("metrics", idx)
            self._mark_rows("alloc", idx)
            self._mark_rows("topo", idx)
            self._mark_full("net")

    def is_committed(self, uid: str) -> bool:
        """Whether a pod's usage is in the ledger (cheap duplicate
        check for the loop's healed-409 path)."""
        with self._lock:
            return uid in self._committed

    def committed_node(self, uid: str) -> str | None:
        """Node NAME the ledger holds this pod's usage at, or None.
        A checkpoint-restored commit must bind at this node — the
        assume already happened in a previous process life, and a
        re-score (whose snapshot includes the pod's own usage) can
        land anywhere else, stranding the recorded usage."""
        with self._lock:
            rec = self._committed.get(uid)
            if rec is None:
                return None
            name = self._node_names[rec.node]
            return name or None

    def note_gang_inflight(self, gang_key: str,
                           entries: list[list]) -> None:
        """Record a gang entering its assume->bind window (entries:
        ``[uid, namespace, name, node_name]`` per member).  A
        checkpoint taken inside the window persists this so restore
        rolls the gang back instead of resurrecting a half-bound
        subset."""
        with self._lock:
            self._inflight_gangs[gang_key] = [list(e) for e in entries]

    def clear_gang_inflight(self, gang_key: str) -> None:
        """The gang's bind resolved (bound or rolled back)."""
        with self._lock:
            self._inflight_gangs.pop(gang_key, None)

    def rollback_gang_members(self, uids: Iterable[str]) -> int:
        """Ledger-driven rollback of gang member commits by uid (the
        restore path; the live path goes through ``release`` with the
        member Pod in hand).  Returns how many records were reversed."""
        n = 0
        with self._lock:
            for uid in uids:
                rec = self._committed.pop(uid, None)
                if rec is not None:
                    self._release_record(rec)
                    self._mark_rows("alloc", rec.node)
                    n += 1
        return n

    def note_migration_inflight(self, move_key: str,
                                entries: list[list]) -> None:
        """Record a live migration entering its evict->rebind window
        (entries: ``[uid, namespace, name, from_node, to_node]`` per
        member).  A checkpoint taken inside the window persists this
        so restore rolls ALL members back — the move becomes
        fully-reverted rather than half-evicted (the rebalancer's
        all-or-nothing contract, tests/test_rebalance.py)."""
        with self._lock:
            self._inflight_migrations[move_key] = [
                list(e) for e in entries]

    def clear_migration_inflight(self, move_key: str) -> None:
        """The move resolved (every member re-bound, or reverted)."""
        with self._lock:
            self._inflight_migrations.pop(move_key, None)

    def migrations_inflight(self) -> dict[str, list[list]]:
        """Snapshot of the live-migration ledger (deep copy; the
        checkpoint writer and tools/state_audit.py read this)."""
        with self._lock:
            return {k: [list(e) for e in v]
                    for k, v in self._inflight_migrations.items()}

    def note_reshape_inflight(self, gang_key: str, old_count: int,
                              new_count: int,
                              entries: list[list]) -> None:
        """Record a gang entering its reshape window (entries:
        ``[uid, namespace, name, from_node, to_node]`` per affected
        member; ``to_node == ""`` means the new shape DROPS the
        member).  Written BEFORE the first eviction; a checkpoint
        taken inside the window persists it so restore settles the
        gang to fully-the-old-shape, never a hybrid.  A gang already
        mid-reshape raises — one gang in two concurrent reshapes is
        the exact corruption tools/state_audit.py treats as fatal."""
        with self._lock:
            if gang_key in self._inflight_reshapes:
                raise ValueError(
                    f"gang {gang_key} is already mid-reshape")
            self._inflight_reshapes[gang_key] = [
                int(old_count), int(new_count),
                [list(e) for e in entries]]

    def clear_reshape_inflight(self, gang_key: str,
                               committed_count: int | None = None,
                               declared_count: int | None = None) -> None:
        """The reshape resolved (new shape fully pinned, or fully
        reverted).  When it COMMITTED, record the new realization so
        checkpoint meta and the state audit see the shape the ledger
        now holds."""
        with self._lock:
            self._inflight_reshapes.pop(gang_key, None)
            if committed_count is not None:
                self._gang_realizations[gang_key] = [
                    int(committed_count),
                    int(declared_count
                        if declared_count is not None
                        else committed_count)]

    def reshapes_inflight(self) -> dict[str, list]:
        """Snapshot of the reshape ledger (deep copy; the checkpoint
        writer and tools/state_audit.py read this)."""
        with self._lock:
            return {k: [v[0], v[1], [list(e) for e in v[2]]]
                    for k, v in self._inflight_reshapes.items()}

    def note_gang_realization(self, gang_key: str, chosen: int,
                              declared: int) -> None:
        """Record the physical realization a shaped gang committed at
        (chosen members placed out of declared) — the checkpoint-meta
        fact the reshape audit cross-checks against committed member
        placements."""
        if not gang_key:
            return
        with self._lock:
            self._gang_realizations[gang_key] = [int(chosen),
                                                 int(declared)]

    def drop_gang_realization(self, gang_key: str) -> None:
        """The gang left the ledger (rolled back / fully released)."""
        with self._lock:
            self._gang_realizations.pop(gang_key, None)

    def gang_realizations(self) -> dict[str, list[int]]:
        """Snapshot of committed realizations (deep copy)."""
        with self._lock:
            return {k: list(v)
                    for k, v in self._gang_realizations.items()}

    def gang_members(self, gang_key: str) -> list[tuple[str, "CommitRecord"]]:
        """Committed ledger entries belonging to one gang (by the
        ``namespace/pod-group`` key recorded at commit time) — the
        preemption planner's victim-expansion surface: evicting one
        slice-job member strands the rest, so the whole gang goes.
        Host dict scan; preemption planning is rare and already does
        a full ledger pass."""
        if not gang_key:
            return []
        with self._lock:
            return [(uid, rec) for uid, rec in self._committed.items()
                    if rec.gang_key == gang_key]

    def known_node_names(self) -> list[str]:
        """Currently registered node names (copy, lock-consistent)."""
        with self._lock:
            return list(self._node_index)

    def node_table(self) -> tuple[list[str], list[int]]:
        """Snapshot of ``(slot -> name, slot -> generation)`` taken in
        one lock acquisition.  A scheduling cycle resolves assignment
        indices against THIS table (not live lookups), and re-checks
        the generation before committing usage — so a slot freed and
        reused mid-cycle yields the old (now-unknown) node name at bind
        (rejected by the API server) rather than a silent bind/commit
        onto whatever node inherited the index."""
        with self._lock:
            return list(self._node_names), list(self._node_gen)

    def topology_features(self) -> dict[str, float]:
        """Size/topology fingerprint of this cluster for the fleet
        transfer registry (r15): valid node count, zone-class count,
        and the mean/std of the OBSERVED (nonzero) latency/bandwidth
        entries.  Donor matching compares these — a policy learned on
        a similar-shaped, similar-fabric cluster is the best
        warm-start candidate."""
        with self._lock:
            valid = self._node_valid.copy()
            zones = self._node_zone[valid]
            lat = self._lat[np.ix_(valid, valid)]
            bw = self._bw[np.ix_(valid, valid)]
        n = int(valid.sum())
        lat_obs = lat[lat > 0]
        bw_obs = bw[bw > 0]
        return {
            "nodes": float(n),
            "zones": float(len({int(z) for z in zones if z >= 0})),
            "lat_mean": float(lat_obs.mean()) if lat_obs.size else 0.0,
            "lat_std": float(lat_obs.std()) if lat_obs.size else 0.0,
            "bw_mean": float(bw_obs.mean()) if bw_obs.size else 0.0,
            "bw_std": float(bw_obs.std()) if bw_obs.size else 0.0,
        }

    def slot_generation(self, idx: int) -> int:
        with self._lock:
            return self._node_gen[idx]

    def reconcile_nodes(self, listed_names, listed_at: float) -> int:
        """Remove registered nodes absent from a full node listing.

        ``listed_at`` (``time.monotonic()`` taken BEFORE the listing
        request) guards the race where a node is registered after the
        listing was snapshotted — such nodes are skipped this round,
        mirroring :meth:`reconcile_committed`.  Returns removals."""
        listed = set(listed_names)
        with self._lock:
            stale = [name for name, idx in self._node_index.items()
                     if name not in listed
                     and self._node_stamp[idx] < listed_at]
        for name in stale:
            self.remove_node(name)
        return len(stale)

    def mark_ready(self, name: str) -> None:
        """Recovery hook: the inverse of :meth:`mark_unready`."""
        with self._lock:
            idx = self._node_index.get(name)
            if idx is None:
                return
            self._node_valid[idx] = True
            self._mark_rows("topo", idx)

    # -- telemetry ----------------------------------------------------

    def update_metrics(self, name: str, values: Mapping[str, float],
                       age_s: float = 0.0) -> None:
        """Ingest one node's metric sample (node_exporter shaped:
        :class:`Metric` channel names).  Non-finite values are dropped —
        one NaN reaching the score matrix would poison every comparison
        against that node — and a sample with no usable channel does not
        reset staleness."""
        with self._lock:
            idx = self._node_index.get(name)
            if idx is None:
                return  # node removed; a late scrape result is noise
            any_ok = False
            for chan, chan_name in enumerate(Metric.NAMES):
                if chan_name in values:
                    val = float(values[chan_name])
                    if np.isfinite(val):
                        self._metrics[idx, chan] = val
                        any_ok = True
            if any_ok:
                self._metrics_age[idx] = age_s
                self._mark_rows("metrics", idx)

    def age_metrics(self, dt_s: float) -> None:
        with self._lock:
            # Every valid node's age moves: full-group dirt (the
            # metrics group is O(N x M) small — not worth indexing).
            self._metrics_age[self._node_valid] += dt_s
            self._mark_full("metrics")

    def update_link(self, a: str, b: str, lat_ms: float | None = None,
                    bw_bps: float | None = None) -> None:
        """Ingest one probe measurement (the iperf3 result of
        run.sh:12, generalized to pairwise)."""
        with self._lock:
            i = self._node_index.get(a)
            j = self._node_index.get(b)
            if i is None or j is None:
                return  # an endpoint was removed; drop the late probe
            if lat_ms is not None and np.isfinite(lat_ms) and lat_ms >= 0:
                self._lat[i, j] = self._lat[j, i] = lat_ms
            if bw_bps is not None and np.isfinite(bw_bps) and bw_bps >= 0:
                self._bw[i, j] = self._bw[j, i] = bw_bps
            self._mark_pair(i, j)
            self._mark_pair(j, i)

    def set_network(self, lat_ms: np.ndarray, bw_bps: np.ndarray) -> None:
        """Bulk-load full matrices (fake-cluster generator path)."""
        with self._lock:
            k = lat_ms.shape[0]
            self._lat[:k, :k] = lat_ms
            self._bw[:k, :k] = bw_bps
            self._mark_full("net")

    def attach_netmodel(self, model) -> None:
        """Attach a :class:`~..netmodel.TopologyModel`; the next net
        snapshot flush blends its predictions (if enabled)."""
        with self._lock:
            self.netmodel = model
            self._mark_full("net")

    def touch_net(self) -> None:
        """Mark the net group dirty without a probe write — used after
        a model refit, whose new predictions change the BLENDED
        matrices even though no staging entry moved."""
        with self._lock:
            self._mark_full("net")

    # -- allocation ---------------------------------------------------
    #
    # Usage is LEDGERED by pod uid: release() reverses exactly what
    # commit recorded, and only for pods we committed.  This makes the
    # accounting robust against (a) foreign pods — a cluster-wide
    # watch delivers deletions of pods other schedulers bound, which
    # must not subtract usage we never added — and (b) the
    # release-before-commit race: a pod that terminates between its
    # bind POST and commit_many() gets an "early release" marker, and
    # the late commit is then dropped instead of leaking forever.

    def commit(self, pod: Pod, node_name: str) -> None:
        """Host-side bookkeeping of a bind: usage + group/anti bits."""
        self.commit_many([pod], [self._node_index[node_name]])

    def commit_many(self, pods: Sequence[Pod],
                    node_indices: Sequence[int]) -> None:
        """Batched commit: one lock acquisition, vectorized usage
        accounting (``np.add.at`` handles repeated nodes)."""
        if not pods:
            return
        r = self.cfg.num_resources
        idx = np.asarray(node_indices, np.int64)
        reqs = np.zeros((len(pods), r), np.float32)
        res_names = _res_names(r)
        for i, pod in enumerate(pods):
            _fill_requests_row(reqs[i], pod.requests, res_names)
        with self._lock:
            # Intern the group bits FIRST, before any state mutation
            # (under the lock — the Interner itself is unsynchronized),
            # and LENIENTLY: the pod was already scored with degraded
            # bits if the interner is full, so the commit must land the
            # SAME (possibly reduced) bits rather than raise mid-batch
            # with usage accounting half-applied.  Any drop that first
            # happens here (extender-path binds commit pods this
            # encoder never scored) is recorded for the per-pod
            # ConstraintDegraded event like every other drop.
            bits = []
            for pod in pods:
                defs = getattr(pod, "selector_defs", None)
                dropped_defs = (self.register_selectors(defs, True)
                                if defs else 0)
                # Snapshot AFTER register_selectors: its failed bit()
                # calls already bump overflow_drops, and dropped_defs
                # reports them — snapshotting before would count each
                # failure twice in the ConstraintDegraded event
                # (ADVICE r3 low #1).
                before = self.groups.overflow_drops
                bits.append((
                    (self.groups.bit(pod.group, lenient=True)
                     if pod.group else 0),
                    (self.groups.mask(pod.anti_groups, lenient=True)
                     if pod.anti_groups else 0),
                    (self.groups.mask(
                        getattr(pod, "zone_anti_groups", ()) or (),
                        lenient=True)
                     if getattr(pod, "zone_anti_groups", None) else 0),
                    self._membership_mask(pod, lenient=True)))
                if self.groups.overflow_drops > before or dropped_defs:
                    self._record_degraded(
                        pod, self.groups.overflow_drops - before
                        + dropped_defs)
            keep = np.ones(len(pods), bool)
            for i, pod in enumerate(pods):
                if pod.uid in self._committed:
                    # Already accounted (duplicate delivery healed as a
                    # 409): committing again would double-count usage
                    # that a single release can never fully undo.
                    keep[i] = False
                    continue
                if pod.uid in self._early_releases:
                    # Terminated before we could account it: skip.
                    del self._early_releases[pod.uid]
                    keep[i] = False
                    continue
                gbit = bits[i][0]
                member = bits[i][3]
                # Spread-count slot (the constraint's selector group
                # or the own group); the UNKNOWN sentinel counts
                # nothing (its gz row never matches).
                gslot = self._spread_slot(pod)
                zone = int(self._node_zone[int(idx[i])])
                zanti = bits[i][2]
                if zanti and zone < 0:
                    # A zone-anti declaration landing on a zone-less
                    # node cannot be recorded (the node is its own
                    # topology domain) — flag the silent non-
                    # enforcement like every other degradation.
                    self._record_degraded(pod, 1)
                    zanti = 0
                self._committed[pod.uid] = CommitRecord(
                    int(idx[i]), reqs[i].copy(), time.monotonic(),
                    float(pod.priority), pod.namespace, pod.name,
                    bits[i][0], bits[i][1],
                    int(getattr(pod, "pdb_min_available", 0)),
                    group_slot=gslot, zone=zone, zanti_bits=zanti,
                    member_bits=member,
                    labels=frozenset(getattr(pod, "labels", None)
                                     or ()),
                    gang_key=gang_key_of(pod))
                # Zone presence + member counts for EVERY membership
                # bit (selector groups included), not just the own
                # group: gz_counts is what zone affinity and spread
                # read.
                m = member
                while m:
                    b = m & -m
                    m ^= b
                    slot = b.bit_length() - 1
                    if zone >= 0:
                        self._gz_counts[slot, zone] += 1
                    self._group_member_counts[slot] += 1
                self._drop_nomination(pod.uid)
            np.add.at(self._used, idx[keep], reqs[keep])
            w = self.cfg.mask_words
            for i, pod in enumerate(pods):
                if not keep[i]:
                    continue
                rec = self._committed[pod.uid]
                if rec.member_bits:
                    self._group_bits[idx[i]] |= int_to_words(
                        rec.member_bits, w)
                    self._ref_add(self._group_refs, int(idx[i]),
                                  rec.member_bits)
                if rec.anti_bits:
                    self._resident_anti[idx[i]] |= int_to_words(
                        rec.anti_bits, w)
                    self._ref_add(self._anti_refs, int(idx[i]),
                                  rec.anti_bits)
                if rec.zanti_bits:
                    self._az_anti[rec.zone] |= int_to_words(
                        rec.zanti_bits, w)
                    self._ref_add(self._az_anti_refs, rec.zone,
                                  rec.zanti_bits)
            self._mark_rows("alloc", *(int(i) for i in idx[keep]))

    def release(self, pod: Pod, node_name: str = "",
                rollback: bool = False) -> None:
        """Reverse this pod's commit (pod deletion/completion).

        Ledger-driven: the subtraction uses the committed record, not
        the caller's view, so double-release is a no-op and foreign
        pods (never committed) do not corrupt usage.  A release that
        beats the commit leaves an early-release marker consumed by
        :meth:`commit_many`.  Group/anti bits are refcounted per
        (node, bit): the bit clears when the LAST member pod leaves —
        without this, a node that ever hosted group ``g`` would block
        anti-``g`` pods forever.

        ``rollback=True`` is the assume-then-bind undo: release the
        commit if it still exists, but NEVER plant an early-release
        marker — the marker guards deletion-beats-commit races, and a
        rollback whose record was already removed (node scale-down
        deleted it directly) planting one would silently cancel the
        pod's next legitimate commit after a requeue, leaving a
        running pod's usage unaccounted forever."""
        with self._lock:
            if self._nominations:
                self._drop_nomination_locked(pod.uid)
            self._terminating.discard(pod.uid)
            rec = self._committed.pop(pod.uid, None)
            if rec is None:
                if rollback:
                    return
                self._early_releases[pod.uid] = None
                if len(self._early_releases) > 4096:
                    # Bound stray markers (e.g. a pod whose bind failed
                    # then got deleted) by evicting the OLDEST — a
                    # fresh marker guards a live race; an old one is
                    # almost certainly a stray.
                    del self._early_releases[
                        next(iter(self._early_releases))]
                return
            self._release_record(rec)
            self._mark_rows("alloc", rec.node)

    def _release_record(self, rec: CommitRecord) -> None:
        """Reverse one ledger record (caller holds the lock)."""
        w = self.cfg.mask_words
        self._used[rec.node] = np.maximum(
            self._used[rec.node] - rec.req, 0.0)
        # member_bits supersets group_bit on v5+ records; pre-v5
        # restores carry member_bits=0 and fall back to the own bit.
        member = rec.member_bits or rec.group_bit
        if member:
            cleared = self._ref_sub(self._group_refs, rec.node, member)
            self._group_bits[rec.node] &= np.invert(
                int_to_words(cleared, w))
        if rec.anti_bits:
            cleared = self._ref_sub(self._anti_refs, rec.node,
                                    rec.anti_bits)
            self._resident_anti[rec.node] &= np.invert(
                int_to_words(cleared, w))
        if rec.zanti_bits and rec.zone >= 0:
            cleared = self._ref_sub(self._az_anti_refs, rec.zone,
                                    rec.zanti_bits)
            self._az_anti[rec.zone] &= np.invert(
                int_to_words(cleared, w))
        self._gz_sub(rec)

    def _gz_sub(self, rec: CommitRecord) -> None:
        """Reverse one record's zone-presence/member counts (caller
        holds the lock).  v5+ records reverse every membership bit;
        pre-v5 restores (member_bits == 0) reverse the legacy single
        own-group slot for gz — their member counts were rebuilt from
        ``group_bit``, so that is what the count decrement mirrors."""
        member = rec.member_bits or rec.group_bit
        m = member
        while m:
            b = m & -m
            m ^= b
            slot = b.bit_length() - 1
            if rec.member_bits and rec.zone >= 0:
                self._gz_counts[slot, rec.zone] = max(
                    0, self._gz_counts[slot, rec.zone] - 1)
            self._group_member_counts[slot] = max(
                0, self._group_member_counts[slot] - 1)
        if member:
            # Count-only dirt: gz/member counts ship whole whenever
            # the alloc group is dirty, so no row index is needed.
            self._mark_rows("alloc")
        if not rec.member_bits and rec.group_slot >= 0 and rec.zone >= 0:
            self._gz_counts[rec.group_slot, rec.zone] = max(
                0, self._gz_counts[rec.group_slot, rec.zone] - 1)
            self._mark_rows("alloc")

    @staticmethod
    def _ref_add(refs: np.ndarray, node: int, bits: int) -> None:
        while bits:
            b = bits & -bits
            refs[node, b.bit_length() - 1] += 1
            bits ^= b

    @staticmethod
    def _ref_sub(refs: np.ndarray, node: int, bits: int) -> int:
        """Decrement refcounts for each set bit; returns the mask of
        bits whose count reached zero (to be cleared)."""
        cleared = 0
        while bits:
            b = bits & -bits
            pos = b.bit_length() - 1
            if refs[node, pos] > 0:
                refs[node, pos] -= 1
            if refs[node, pos] == 0:
                cleared |= b
            bits ^= b
        return cleared

    # -- nominations --------------------------------------------------

    def nominate(self, uid: str, node_name: str,
                 requests: Mapping[str, float]) -> None:
        """Reserve capacity on ``node_name`` for preemptor ``uid``
        while its victims terminate (nominatedNodeName semantics:
        without this, the space freed by eviction is up for grabs by
        any pod scored in the interim)."""
        with self._lock:
            idx = self._node_index.get(node_name)
            if idx is None:
                return
            self._drop_nomination_locked(uid)
            req = _requests_vector(requests, self.cfg.num_resources)
            self._nominations[uid] = (idx, req, time.monotonic())
            self._reserved[idx] += req
            self._mark_rows("alloc", idx)

    def _drop_nomination_locked(self, uid: str) -> None:
        entry = self._nominations.pop(uid, None)
        if entry is not None:
            idx, req, _ = entry
            self._reserved[idx] = np.maximum(
                self._reserved[idx] - req, 0.0)
            self._mark_rows("alloc", idx)

    def _drop_nomination(self, uid: str) -> None:
        with self._lock:
            self._drop_nomination_locked(uid)

    def mark_terminating(self, uid: str) -> None:
        """Record that a victim's graceful deletion was accepted; the
        planner stops counting it as live.  Cleared on release (the
        DELETED confirmation) or by reconcile."""
        with self._lock:
            if uid in self._committed:
                self._terminating.add(uid)

    def expire_nominations(self, ttl_s: float) -> int:
        """Drop reservations older than ``ttl_s`` (a victim that never
        terminates must not hold capacity hostage).  Returns drops."""
        cutoff = time.monotonic() - ttl_s
        with self._lock:
            stale = [uid for uid, (_, _, t) in self._nominations.items()
                     if t < cutoff]
            for uid in stale:
                self._drop_nomination_locked(uid)
        return len(stale)

    def reconcile_committed(self, alive_uids,
                            listed_at: float | None = None) -> int:
        """Release every ledger entry whose pod no longer exists.

        The watch cannot deliver deletions that happened while the
        daemon was down (a restored checkpoint carries their committed
        usage forever otherwise); a periodic listing of live pods
        closes that gap.  ``listed_at`` (``time.monotonic()`` taken
        BEFORE the listing request) guards the race where a pod is
        committed after the listing was snapshotted — entries stamped
        later are skipped this round.  Returns entries released."""
        alive = set(alive_uids)
        cutoff = float("inf") if listed_at is None else listed_at
        released = 0
        with self._lock:
            stale = [u for u, rec in self._committed.items()
                     if u not in alive and rec.stamp < cutoff]
            for uid in stale:
                rec = self._committed.pop(uid)
                self._release_record(rec)
                self._mark_rows("alloc", rec.node)
                self._terminating.discard(uid)
                released += 1
            # Terminating markers must track the ledger.
            self._terminating &= set(self._committed)
            # Early-release markers for pods that no longer exist can
            # never be consumed by a commit — drop them.
            for uid in [u for u in self._early_releases
                        if u not in alive]:
                del self._early_releases[uid]
        return released

    # -- delta-ingest bookkeeping -------------------------------------

    def _mark_rows(self, group: str, *rows: int) -> None:
        """Mark ``group`` dirty at node rows ``rows``.  No rows means
        flag-only dirt (e.g. the zone-count sidecars of the alloc
        group, which are always shipped whole).  Caller holds the
        lock."""
        self._dirty[group] = True
        s = self._dirty_rows[group]
        if s is not None:
            s.update(rows)
            if len(s) > _DELTA_MAX_INDICES:
                self._dirty_rows[group] = None

    def _mark_full(self, group: str) -> None:
        """Mark ``group`` dirty for a full re-upload: bulk rewrites
        (interner backfill, set_network) or footprints the row/pair
        protocol cannot express.  Caller holds the lock."""
        self._dirty[group] = True
        if group == "net":
            self._dirty_pairs = None
        else:
            self._dirty_rows[group] = None

    def _mark_pair(self, i: int, j: int) -> None:
        """Mark net element (i, j) dirty.  DIRECTED — symmetric
        writers mark both orientations.  Caller holds the lock."""
        self._dirty["net"] = True
        if self._dirty_pairs is not None:
            self._dirty_pairs.add((int(i), int(j)))
            if len(self._dirty_pairs) > _DELTA_MAX_INDICES:
                self._dirty_pairs = None

    def _rows_idx(self, group: str, n: int,
                  delta_on: bool) -> "np.ndarray | None":
        """Resolve a dirty group to a scatter row-index vector, or
        None to force a full upload (delta disabled, no device cache
        yet, full sentinel, or past the dirty-fraction escalation
        knob — scattering most of the array costs more than one
        contiguous transfer)."""
        rows = self._dirty_rows[group]
        if (not delta_on or rows is None
                or len(rows) > self.cfg.delta_full_fraction * n):
            return None
        return np.array(sorted(rows), np.int32)

    def _full_up(self, key: str, host) -> None:
        """Full-group transfer of one cached array (+accounting).

        ``copy=True`` is load-bearing: on the CPU backend a bare
        ``jnp.asarray(host)`` zero-copies a well-aligned numpy buffer,
        so the cached "device" plane would ALIAS the staging array and
        every later in-place staging write would leak into snapshots
        already handed out — breaking the immutable-pytree contract
        and making device-vs-staging drift undetectable."""
        arr = jnp.array(host, copy=True)
        self._cache[key] = arr
        self.snapshot_full_bytes_total += int(arr.nbytes)

    def _rows_up(self, key: str, idx: np.ndarray, host) -> None:
        """Scatter-patch rows ``idx`` of one cached array from its
        host staging twin (+accounting; ships the padded payload)."""
        pidx = _pad_pow2(idx)
        vals = jnp.asarray(np.ascontiguousarray(host[pidx]))
        self._cache[key] = _scatter_rows(
            self._cache[key], jnp.asarray(pidx), vals)
        self.snapshot_delta_bytes_total += int(vals.nbytes + pidx.nbytes)

    def _pairs_up(self, key: str, ii: np.ndarray, jj: np.ndarray,
                  host) -> None:
        """Scatter-patch elements (ii, jj) of one cached matrix."""
        vals = jnp.asarray(np.ascontiguousarray(host[ii, jj]))
        self._cache[key] = _scatter_pairs(
            self._cache[key], jnp.asarray(ii), jnp.asarray(jj), vals)
        self.snapshot_delta_bytes_total += int(
            vals.nbytes + ii.nbytes + jj.nbytes)

    def static_delta_since(self, version: int) -> "dict | None":
        """Merged static-input dirty descriptor covering
        ``(version, current_static_version]``.

        Returns None when the bounded per-version history cannot prove
        coverage (consumer too many versions behind, or delta tracking
        disabled) — the caller must rebuild its static prep from
        scratch.  Otherwise a dict with ``metrics``/``topo``/``net``
        booleans and ``net_pairs``: the union of dirty (i, j) net
        elements across the span, or None meaning the whole net group
        moved (bulk rewrite / netmodel blend, which is global)."""
        with self._lock:
            cur = self._static_version
            if version == cur:
                return {"metrics": False, "topo": False, "net": False,
                        "net_pairs": frozenset()}
            ents = [(v, d) for v, d in self._static_deltas
                    if v > version]
            if version > cur or len(ents) != cur - version:
                return None
            metrics = topo = net = False
            pairs: "set | None" = set()
            for _, d in ents:
                metrics = metrics or d["metrics"]
                topo = topo or d["topo"]
                if d["net"]:
                    net = True
                    if pairs is not None:
                        if d["net_pairs"] is None:
                            pairs = None
                        else:
                            pairs |= d["net_pairs"]
            return {"metrics": metrics, "topo": topo, "net": net,
                    "net_pairs": (None if pairs is None
                                  else frozenset(pairs))}

    # -- snapshot -----------------------------------------------------

    def snapshot(self) -> ClusterState:
        """Device view of the current staging state; transfers only
        dirty groups (double-buffering: the returned pytree is
        immutable, later updates build a new one)."""
        return self.snapshot_versioned()[0]

    def snapshot_versioned(self) -> tuple[ClusterState, int]:
        """:meth:`snapshot` plus the matching :attr:`static_version`,
        read atomically under the encoder lock.

        The pairing matters for static-score caching: the version
        bumps lazily inside the flush, so reading it in a separate
        call before OR after the snapshot can mispair it with the
        state (a dirty flag pending at the pre-read, or a concurrent
        thread's flush after it) and serve stale static scores against
        fresh state."""
        with self._lock:
            # Version the static-score inputs (metrics/net/topo): any
            # rebuild of those cache groups invalidates cached
            # batch-invariant score prep held by serving paths (the
            # extender batcher keys on this counter — an explicit
            # contract, not reliance on array-object reuse).
            static_bumped = (self._dirty["metrics"] or self._dirty["net"]
                             or self._dirty["topo"])
            if static_bumped:
                self._static_version += 1
            model = self.netmodel
            net_blend = model is not None and model.enabled
            if static_bumped and self.cfg.enable_delta_state:
                # Record this version's dirty footprint for static
                # consumers (static_delta_since).  The netmodel blend
                # mixes every element regardless of which probes moved,
                # so its net footprint is always "full".
                pairs = self._dirty_pairs
                self._static_deltas.append((self._static_version, {
                    "metrics": self._dirty["metrics"],
                    "topo": self._dirty["topo"],
                    "net": self._dirty["net"],
                    # Empty pairs with the net flag up = boolean-only
                    # dirt (external poke): record "whole group moved"
                    # so static consumers rebuild, never skip.
                    "net_pairs": ((None if (net_blend or not pairs)
                                   else frozenset(pairs))
                                  if self._dirty["net"] else frozenset()),
                }))
            # Delta ingest patches the previous device arrays in place
            # of full transfers when the dirty footprint is small; the
            # scattered values are computed by the SAME host formulas
            # as the full path, so the resulting pytree is
            # bit-identical (property-tested in test_static_delta).
            delta_on = bool(self.cfg.enable_delta_state) and bool(self._cache)
            n = self._node_valid.shape[0]
            if self._dirty["metrics"]:
                idx = self._rows_idx("metrics", n, delta_on)
                # Dirty flag with NO recorded rows = someone set the
                # boolean directly (the pre-delta contract, still used
                # by tests poking staging arrays) — coverage is
                # unprovable, so ship the whole group.  Internal
                # writers always record rows, so this costs nothing in
                # the steady state.
                if idx is None or len(idx) == 0:
                    self._full_up("metrics", self._metrics)
                    self._full_up("metrics_age", self._metrics_age)
                else:
                    self._rows_up("metrics", idx, self._metrics)
                    self._rows_up("metrics_age", idx, self._metrics_age)
            if self._dirty["net"]:
                if net_blend:
                    lat_host, bw_host = model.blend(self._lat, self._bw)
                    self._full_up("lat", lat_host)
                    self._full_up("bw", bw_host)
                else:
                    pairs = self._dirty_pairs
                    # Empty pair set with the net flag up: boolean-only
                    # dirt (see the metrics branch) — full upload.
                    if (not delta_on or not pairs
                            or len(pairs) >
                            self.cfg.delta_full_fraction * n * n):
                        self._full_up("lat", self._lat)
                        self._full_up("bw", self._bw)
                    else:
                        srt = sorted(pairs)
                        ii = _pad_pow2(np.array(
                            [p[0] for p in srt], np.int32))
                        jj = _pad_pow2(np.array(
                            [p[1] for p in srt], np.int32))
                        self._pairs_up("lat", ii, jj, self._lat)
                        self._pairs_up("bw", ii, jj, self._bw)
            if self._dirty["alloc"]:
                # Nominated reservations count as used: the scoring
                # kernel must not hand a preemptor's freed space to
                # someone else (the preemptor's own hold is dropped
                # when it is encoded for scoring).  Row-sliceable: the
                # reservation array is zero except at nominated rows,
                # and every row whose reservation moves is marked.
                used_host = (self._used + self._reserved
                             if self._nominations else self._used)
                idx = self._rows_idx("alloc", n, delta_on)
                if idx is None:
                    self._full_up("cap", self._cap)
                    self._full_up("used", used_host)
                    self._full_up("group_bits", self._group_bits)
                    self._full_up("resident_anti", self._resident_anti)
                elif len(idx):
                    self._rows_up("cap", idx, self._cap)
                    self._rows_up("used", idx, used_host)
                    self._rows_up("group_bits", idx, self._group_bits)
                    self._rows_up("resident_anti", idx,
                                  self._resident_anti)
                # The zone-count sidecars are O(slots x zones) small
                # and not row-shaped: shipped whole whenever the alloc
                # group is dirty.
                self._full_up("gz_counts", self._gz_counts)
                self._full_up("az_anti", self._az_anti)
            if self._dirty["topo"]:
                idx = self._rows_idx("topo", n, delta_on)
                if idx is None or len(idx) == 0:
                    self._full_up("node_valid", self._node_valid)
                    self._full_up("label_bits", self._label_bits)
                    self._full_up("taint_bits", self._taint_bits)
                    self._full_up("node_zone", self._node_zone)
                    self._full_up("node_numeric", self._node_numeric)
                else:
                    self._rows_up("node_valid", idx, self._node_valid)
                    self._rows_up("label_bits", idx, self._label_bits)
                    self._rows_up("taint_bits", idx, self._taint_bits)
                    self._rows_up("node_zone", idx, self._node_zone)
                    self._rows_up("node_numeric", idx,
                                  self._node_numeric)
            for key in self._dirty:
                self._dirty[key] = False
            self._dirty_rows = {"metrics": set(), "alloc": set(),
                                "topo": set()}
            self._dirty_pairs = set()
            return ClusterState(**self._cache), self._static_version

    def expected_device_arrays(self) -> "dict[str, np.ndarray]":
        """Host-side truth of what the device cache must hold after a
        flush: the staging arrays routed through the SAME transforms
        the snapshot transfer path applies (netmodel blend on the net
        group, nomination reservations folded into ``used``).  The
        anti-entropy auditor (core/integrity.py) digests this against
        the live device planes — bit-exact agreement is the invariant
        the delta-ingest design promises.  Returns copies (safe to
        digest outside the lock)."""
        with self._lock:
            model = self.netmodel
            if model is not None and model.enabled:
                lat, bw = model.blend(self._lat, self._bw)
                lat = np.asarray(lat, np.float32)
                bw = np.asarray(bw, np.float32)
            else:
                lat, bw = self._lat.copy(), self._bw.copy()
            used = (self._used + self._reserved if self._nominations
                    else self._used.copy())
            return {
                "metrics": self._metrics.copy(),
                "metrics_age": self._metrics_age.copy(),
                "lat": lat,
                "bw": bw,
                "cap": self._cap.copy(),
                "used": used,
                "node_valid": self._node_valid.copy(),
                "label_bits": self._label_bits.copy(),
                "taint_bits": self._taint_bits.copy(),
                "group_bits": self._group_bits.copy(),
                "resident_anti": self._resident_anti.copy(),
                "node_zone": self._node_zone.copy(),
                "gz_counts": self._gz_counts.copy(),
                "az_anti": self._az_anti.copy(),
                "node_numeric": self._node_numeric.copy(),
            }

    # -- pods ---------------------------------------------------------

    def _constraint_bits(self, pod: Pod, lenient: bool
                         ) -> tuple[int, int, int, int, int]:
        """Intern one pod's constraint sets → (tol, sel, aff, anti,
        group) bitmasks; single source of truth for batch AND stream
        encoding.

        Overflow direction per constraint: dropping a toleration/anti/
        own-group is conservative (more constrained / untracked); a
        must-match selector or required-affinity key degrades to the
        UNKNOWN sentinel (infeasible) rather than silently matching
        anywhere.

        Any lenient-mode drop records the pod in ``_degraded_pods`` so
        the loop can emit a per-pod Warning event — an operator must be
        able to tell WHICH pods lost constraints, not just read an
        aggregate overflow counter (the anti-affinity drop in
        particular silently stops being enforced).
        """
        drops_before = (self.taints.overflow_drops
                        + self.labels.overflow_drops
                        + self.groups.overflow_drops)
        if lenient and getattr(pod, "parse_degraded", 0):
            # Constraints already lost at PARSE time (kubeclient
            # dropped an unrepresentable required term): surface them
            # through the same per-pod event stream as interner drops.
            self._record_degraded(pod, int(pod.parse_degraded))
        bits = (
            self.taints.mask(pod.tolerations, lenient),
            self._selector_mask(pod.node_selector, lenient),
            self.groups.mask(pod.affinity_groups, lenient,
                             on_overflow=self.groups.unknown),
            self.groups.mask(pod.anti_groups, lenient),
            self._membership_mask(pod, lenient),
        )
        drops_after = (self.taints.overflow_drops
                       + self.labels.overflow_drops
                       + self.groups.overflow_drops)
        if drops_after > drops_before:
            self._record_degraded(pod, drops_after - drops_before)
        return bits

    def _zone_bits(self, pod: Pod, lenient: bool,
                   record: bool = True) -> tuple[int, int]:
        """Intern one pod's zone-scoped (anti-)affinity groups →
        (zaff, zanti) masks in the group bit space.  Overflow
        direction mirrors the hostname pair: a required zone-affinity
        group degrades to UNKNOWN (present in no zone — infeasible),
        a zone-anti group drops (untracked, recorded per pod)."""
        zaff_src = getattr(pod, "zone_affinity_groups", ()) or ()
        zanti_src = getattr(pod, "zone_anti_groups", ()) or ()
        if not zaff_src and not zanti_src:
            return 0, 0
        before = self.groups.overflow_drops
        zaff = self.groups.mask(zaff_src, lenient,
                                on_overflow=self.groups.unknown)
        zanti = self.groups.mask(zanti_src, lenient)
        if record and self.groups.overflow_drops > before:
            self._record_degraded(
                pod, self.groups.overflow_drops - before)
        return zaff, zanti

    def _record_degraded(self, pod: Pod, count: int) -> None:
        """Queue one ConstraintDegraded record per pod identity
        (caller holds the lock); repeat drops for the same pod (commit
        after encode, retry cycles) are not re-recorded."""
        if self._degrade_capture is not None:
            # Shape-cache capture (see _pod_constraint_rows): tally
            # only — the caller records ONE event with the shape's
            # total afterwards, so miss and hit pods of one shape
            # report the same count (piecemeal recording here would
            # give the miss pod only its first source's count, the
            # identity dedup suppressing the rest).
            self._degrade_capture += count
            return
        key = (pod.namespace, pod.name)
        if key in self._degraded_seen:
            return
        if len(self._degraded_seen) >= 4096:
            # Bounded: on a pathological fleet, prefer occasional
            # duplicate events over unbounded growth.
            self._degraded_seen.clear()
        self._degraded_seen.add(key)
        self.degraded_total += 1
        detail = tuple(getattr(pod, "parse_degraded_detail", ()) or ())
        self._degraded_pods.append((pod.namespace, pod.name, count,
                                    detail))

    def register_selectors(self, defs: Mapping[str, tuple],
                           lenient: bool) -> int:
        """Register selector-group definitions (group key → canonical
        structure for :func:`selector_matches`); returns the count of
        keys that could not get a bit (interner overflow — the caller
        records the degradation per pod).

        A NEW registration retroactively claims committed residents
        whose labels match — node group bits, refcounts, zone counts
        and the cluster-wide member counts all update — because
        Kubernetes evaluates selectors against live pods: a selector
        first seen after its members were scheduled must still see
        them.  Bumps ``_selector_gen`` so shape-cache entries computed
        against the older registry die.  Caller holds the lock."""
        degraded = 0
        w = self.cfg.mask_words
        for key, sel_def in defs.items():
            if key in self._selector_defs:
                continue
            before = self.groups.overflow_drops
            bit = self.groups.bit(key, lenient=lenient)
            if self.groups.overflow_drops > before or not bit:
                degraded += 1
                continue
            self._selector_defs[key] = tuple(sel_def)
            self._selector_gen += 1
            slot = bit.bit_length() - 1
            for uid, rec in self._committed.items():
                if (rec.labels is None or (rec.member_bits & bit)
                        or not selector_matches(sel_def, rec.labels)):
                    continue
                self._committed[uid] = rec._replace(
                    member_bits=rec.member_bits | bit)
                self._group_bits[rec.node] |= int_to_words(bit, w)
                self._ref_add(self._group_refs, rec.node, bit)
                if rec.zone >= 0:
                    self._gz_counts[slot, rec.zone] += 1
                self._group_member_counts[slot] += 1
                self._mark_rows("alloc", rec.node)
        return degraded

    def _membership_mask(self, pod: Pod, lenient: bool) -> int:
        """The pod's FULL group-membership mask: its annotation group
        bit | every registered selector-group its labels satisfy
        (label-driven membership, kube semantics — no annotation
        opt-in).  Caller holds the lock."""
        mask = self.groups.bit(pod.group, lenient) if pod.group else 0
        labels = getattr(pod, "labels", None)
        if labels is not None:
            # An EMPTY label set still evaluates: kube's NotIn /
            # DoesNotExist (and the empty selector) match label-less
            # pods too.
            for key, sel_def in self._selector_defs.items():
                if selector_matches(sel_def, labels):
                    mask |= self.groups.bit(key, lenient=True)
        return mask

    def _spread_slot(self, pod: Pod) -> int:
        """Bit-slot of the pod's topology-spread COUNTED group: the
        constraint's labelSelector group when parsed
        (``pod.spread_group``), else the pod's own group.  Caller
        holds the lock."""
        sg = getattr(pod, "spread_group", "") or pod.group
        if not sg:
            return -1
        bit = self.groups.bit(sg, lenient=True)
        return bit.bit_length() - 1 if bit else -1

    def set_pdb(self, pdb) -> None:
        """Upsert a real ``policy/v1`` PodDisruptionBudget: registers
        its selector as a selector-group (member counting then rides
        the same label-driven machinery as affinity) and records the
        disruption bound for the preemption planner.

        A selector that cannot get a group bit (interner exhausted)
        leaves the PDB UNENFORCED — the preemption planner finds no
        slot and skips the bound (degrades OPEN).  Unlike every other
        degradation that used to be silent (ADVICE r3 low #2), this is
        surfaced through the same ConstraintDegraded event channel the
        per-pod drops use, naming the PDB."""
        with self._lock:
            degraded = False
            if pdb.selector_key:
                self.register_selectors(
                    {pdb.selector_key: pdb.selector_def}, lenient=True)
                degraded = pdb.selector_key not in self._selector_defs
            self._pdbs[pdb.uid or f"{pdb.namespace}/{pdb.name}"] = pdb
            if degraded:
                # Same identity-dedup discipline as _record_degraded:
                # the PDB watch re-delivers on every resync, and
                # without dedup each upsert re-fires the event while
                # the interner stays exhausted.
                key = (pdb.namespace, f"pdb/{pdb.name}")
                if key not in self._degraded_seen:
                    if len(self._degraded_seen) >= 4096:
                        self._degraded_seen.clear()
                    self._degraded_seen.add(key)
                    self.degraded_total += 1
                    self._degraded_pods.append((
                        pdb.namespace, pdb.name, 1,
                        (f"PodDisruptionBudget {pdb.namespace}/"
                         f"{pdb.name} selector could not get a group"
                         " bit (interner exhausted); its disruption"
                         " bound is NOT enforced (degrades OPEN)",)))

    def remove_pdb(self, uid: str) -> None:
        with self._lock:
            self._pdbs.pop(uid, None)

    def _apply_first_pod_escape(self, aff_row: np.ndarray,
                                zaff_row: np.ndarray,
                                gbit_row: np.ndarray,
                                granted: set) -> None:
        """Kube-scheduler's required-affinity special case: a term
        whose group has NO live member anywhere is waived when the
        incoming pod itself is a member — without it, the first pod of
        a Deployment whose replicas carry required self-affinity
        deadlocks Pending forever (ADVICE.md round 2, medium #1).

        The waiver applies only when NO earlier pod of the same encode
        pass is a member either (``granted`` is the caller's
        accumulated member-slot set): an earlier member will normally
        place this pass, and the conflict loop then chains the later
        pod onto it within the batch — exactly the sequential
        co-location kube's one-at-a-time queue gives (a sidecar queued
        after its app must land beside it, not take the waiver).
        Zone-scoped terms use the same cluster-wide member counts
        (kube's rule is "no pod in the cluster matches the selector",
        not per-domain).  Caller holds the lock."""
        member = words_to_int(gbit_row)
        if not member:
            return
        for row in (aff_row, zaff_row):
            m = words_to_int(row)
            cand = m & member
            drop = 0
            while cand:
                b = cand & -cand
                cand ^= b
                slot = b.bit_length() - 1
                if (self._group_member_counts[slot] == 0
                        and slot not in granted):
                    drop |= b
            if drop:
                _fill_words(row, m & ~drop)

    def _soft_rows(self, pod: Pod, sel_bits_row: np.ndarray,
                   sel_w_row: np.ndarray, grp_bits_row: np.ndarray,
                   grp_w_row: np.ndarray, zone_bits_row: np.ndarray,
                   zone_w_row: np.ndarray) -> None:
        """Fill one pod's soft-affinity term rows (caller holds the
        lock; rows are ``u32[T, W]`` / ``f32[T]`` slices).

        Always lenient: a preference we cannot intern degrades
        score-neutrally.  Label terms go through
        :meth:`_selector_mask` so a newly-referenced label backfills
        onto already-registered nodes; on overflow the mask carries
        the UNKNOWN sentinel, which no node has — the term then simply
        never matches (0 contribution), exactly the right degradation
        for a *preference*.  Group terms intern like anti-affinity
        groups (0 on overflow = no contribution).
        """
        t_max = sel_w_row.shape[0]

        def top_terms(terms):
            # Over budget, keep the strongest preferences: zero-weight
            # terms are no-ops (dropped outright), and the k8s parser's
            # multi-value In expansion can inflate one stanza into
            # several terms — truncating by declaration order would let
            # such an expansion evict an unrelated, heavier stanza.
            live = [(x, float(w)) for x, w in terms if w]
            live.sort(key=lambda t: -abs(t[1]))  # stable: ties keep order
            return live[:t_max]

        for t, (labels, weight) in enumerate(
                top_terms(pod.soft_node_affinity)):
            mask = self._selector_mask(labels, lenient=True)
            if mask:
                _fill_words(sel_bits_row[t], mask)
                sel_w_row[t] = weight
        for t, (grp, weight) in enumerate(
                top_terms(pod.soft_group_affinity)):
            bit = self.groups.bit(grp, lenient=True) if grp else 0
            if bit:
                _fill_words(grp_bits_row[t], bit)
                grp_w_row[t] = weight
        for t, (grp, weight) in enumerate(
                top_terms(pod.soft_zone_affinity)):
            bit = self.groups.bit(grp, lenient=True) if grp else 0
            if bit:
                _fill_words(zone_bits_row[t], bit)
                zone_w_row[t] = weight

    def _ns_rows(self, pod: Pod, anyof_row: np.ndarray,
                 forbid_row: np.ndarray, used_row: np.ndarray,
                 num_col_row: np.ndarray, num_lo_row: np.ndarray,
                 num_hi_row: np.ndarray,
                 lenient: bool, record: bool = True) -> None:
        """Fill one pod's hard-nodeAffinity rows from
        ``pod.required_node_affinity`` (caller holds the lock).

        Rows are ``anyof u32[T2, E, W]`` / ``forbid u32[T2, W]`` /
        ``used bool[T2]`` / numeric ``col i32[T2, NE]`` +
        ``lo/hi f32[T2, NE]`` slices.  Ops map as: In -> any-of over
        the interned ``key=value`` strings; Exists -> any-of over
        the key-presence bit; NotIn/DoesNotExist -> the term's forbid
        mask; Gt/Lt -> a (numeric-column, lo, hi) comparison slot
        (same-key Gt+Lt merge into one interval).  Hard constraints
        degrade CLOSED: terms beyond the budget are dropped (fewer OR
        branches = stricter), an over-budget or unrepresentable
        expression marks its term unsatisfiable via the UNKNOWN
        sentinel (no node carries it), and a pod whose every term
        degrades away keeps one unsatisfiable term rather than
        silently losing the constraint.  Strict mode raises instead.
        Every lenient degradation is recorded for the per-pod
        ConstraintDegraded event unless ``record=False`` (read-only
        callers like the preemption planner, which re-encodes a pod
        the scoring path already recorded).
        """
        terms = tuple(getattr(pod, "required_node_affinity", ()) or ())
        if not terms:
            return
        t2, e_max = anyof_row.shape[0], anyof_row.shape[1]
        ne_max = num_col_row.shape[1]
        unknown = self.labels.unknown
        degraded = 0
        if len(terms) > t2:
            if not lenient:
                raise ValueError(
                    f"pod {pod.name}: {len(terms)} nodeSelectorTerms "
                    f"exceed max_ns_terms={t2}")
            degraded += len(terms) - t2
            terms = terms[:t2]
        for t, term in enumerate(terms):
            used_row[t] = True
            anyofs: list[int] = []
            numeric: dict[int, list[float]] = {}  # col -> [lo, hi]
            forbid = 0
            unsat = False
            for expr in term:
                try:
                    op, key, values = expr[0], expr[1], tuple(expr[2])
                except (TypeError, IndexError, KeyError):
                    # Malformed expression (programmatic Pod with the
                    # wrong nesting, not kubeclient output): a batch
                    # encode must not die on one bad pod — closed, per
                    # the hard-constraint rule.
                    if not lenient:
                        raise ValueError(
                            f"pod {pod.name}: malformed nodeAffinity "
                            f"expression {expr!r}") from None
                    degraded += 1
                    unsat = True
                    continue
                if op == "In":
                    if not values:
                        unsat = True  # k8s validation forbids; closed
                        continue
                    anyofs.append(self._selector_mask(
                        (f"{key}={v}" for v in values), lenient))
                elif op == "Exists":
                    anyofs.append(self._presence_mask((key,), lenient))
                elif op == "NotIn":
                    m = self._selector_mask(
                        (f"{key}={v}" for v in values), lenient)
                    if m & unknown:
                        # A forbidden value we cannot track: nodes
                        # carrying it are indistinguishable — closed.
                        unsat = True
                    forbid |= m & ~unknown
                elif op == "DoesNotExist":
                    m = self._presence_mask((key,), lenient)
                    if m & unknown:
                        unsat = True
                    forbid |= m & ~unknown
                elif op in ("Gt", "Lt"):
                    # Numeric comparison: kube parses the single value
                    # as an integer (we accept any float — a strict
                    # superset); unparseable values and column-budget
                    # overflow degrade the term CLOSED.
                    try:
                        val = float(values[0])
                    except (IndexError, ValueError, TypeError):
                        if not lenient:
                            raise ValueError(
                                f"pod {pod.name}: non-numeric "
                                f"{op} value {values!r}") from None
                        degraded += 1
                        unsat = True
                        continue
                    col = self._numeric_col(key, lenient)
                    if col is None:
                        degraded += 1
                        unsat = True
                        continue
                    lo, hi = numeric.setdefault(
                        col, [-np.inf, np.inf])
                    if op == "Gt":
                        numeric[col][0] = max(lo, val)
                    else:
                        numeric[col][1] = min(hi, val)
                else:
                    if not lenient:
                        raise ValueError(
                            f"pod {pod.name}: unsupported nodeAffinity "
                            f"operator {op!r}")
                    degraded += 1
                    unsat = True
            if len(anyofs) > e_max:
                if not lenient:
                    raise ValueError(
                        f"pod {pod.name}: {len(anyofs)} matchExpressions "
                        f"exceed max_ns_exprs={e_max}")
                degraded += len(anyofs) - e_max
                unsat = True
            if len(numeric) > ne_max:
                if not lenient:
                    raise ValueError(
                        f"pod {pod.name}: {len(numeric)} numeric "
                        f"Gt/Lt keys exceed max_ns_num={ne_max}")
                degraded += len(numeric) - ne_max
                unsat = True
            if unsat:
                anyof_row[t].fill(0)
                _fill_words(anyof_row[t, 0], unknown)
                forbid_row[t].fill(0)
                num_col_row[t].fill(-1)
                degraded += 1
            else:
                for e, m in enumerate(anyofs):
                    _fill_words(anyof_row[t, e], m)
                _fill_words(forbid_row[t], forbid)
                for j, (col, (lo, hi)) in enumerate(
                        sorted(numeric.items())):
                    num_col_row[t, j] = col
                    num_lo_row[t, j] = lo
                    num_hi_row[t, j] = hi
        if degraded and record:
            self._record_degraded(pod, degraded)

    def _pod_constraint_rows(self, pod: Pod, lenient: bool,
                             rows: tuple) -> tuple:
        """Fill one pod's 19 constraint-row slices and return its
        ``_constraint_bits`` tuple — with a SHAPE cache: pods of one
        service/Deployment share identical constraint sets (same
        tolerations/selectors/affinities/terms), so the interning and
        row-building work runs once per distinct shape and later pods
        memcpy the rows (measured ~2x on the 65k-pod stream encode).

        Cache safety: interned bits are stable once assigned (the
        tables only grow) and lazy label/presence backfill happens on
        first intern — both exactly-once effects a later identical
        shape no longer needs.  Degradation is replayed per pod: the
        compute's recorded drop count is stored and re-recorded for
        every cache-hit pod (events are per-pod, identity-keyed).
        Strict and lenient entries are keyed apart (strict must keep
        raising); a strict-mode raise caches nothing.  Caller holds
        the lock.
        """
        # New selector definitions must land BEFORE the cache lookup —
        # a registration bumps _selector_gen (part of the key below),
        # so entries whose memberships were computed against the older
        # registry can never be served stale.
        defs = getattr(pod, "selector_defs", None)
        if defs:
            dropped = self.register_selectors(defs, lenient=lenient)
            if dropped:
                self._record_degraded(pod, dropped)
        key: tuple | None = (
            lenient, pod.tolerations, pod.node_selector,
            pod.affinity_groups, pod.anti_groups, pod.group,
            getattr(pod, "labels", frozenset()),
            getattr(pod, "spread_group", ""), self._selector_gen,
            pod.required_node_affinity, pod.zone_affinity_groups,
            pod.zone_anti_groups, pod.soft_node_affinity,
            pod.soft_group_affinity, pod.soft_zone_affinity,
            int(getattr(pod, "parse_degraded", 0)))
        try:
            cached = self._shape_cache.get(key)
        except TypeError:
            # Programmatic Pods may carry list/set-valued fields (the
            # dataclass doesn't coerce); they encode fine, they just
            # can't key the cache — bypass it rather than crash the
            # lenient batch.
            key = None
            cached = None
        if cached is not None:
            self.shape_cache_hits += 1
            bits, nonzero, d_delta = cached
            # Only the rows the compute actually touched are stored
            # (targets are pre-zeroed): typical pods copy 1-3 small
            # arrays, not 16 — the copies were otherwise eating the
            # cache's win.
            for j, src in nonzero:
                rows[j][...] = src
            if d_delta:
                self._record_degraded(pod, d_delta)
            return bits
        (tol_r, sel_r, aff_r, anti_r, gbit_r, ssel_r, ssel_w_r,
         sgrp_r, sgrp_w_r, szone_r, szone_w_r, ns_any_r, ns_forb_r,
         ns_used_r, ns_ncol_r, ns_nlo_r, ns_nhi_r, zaff_r,
         zanti_r) = rows
        # Rows the compute may have written, tracked EXPLICITLY (a
        # superset is fine: untouched rows still hold the caller's
        # defaults, so an extra copy is a no-op).  The previous
        # ``r.any()`` sweep over all 19 rows cost ~30% of a rich-
        # constraint stream encode (160k tiny-ndarray reductions per
        # 10k pods) and, worse, the ns numeric rows' NON-zero defaults
        # (-1 / ±inf) made every cache entry store-and-copy them even
        # for pods with no nodeAffinity at all.
        touched: list[int] = []
        # Capture the compute's INTENDED degradation count through the
        # explicit accumulator (deque-length arithmetic would read 0
        # once the bounded _degraded_pods is full, or when this pod's
        # identity was already recorded).
        self._degrade_capture = 0
        try:
            bits = self._constraint_bits(pod, lenient)
            for j, (row, val) in enumerate(
                    zip((tol_r, sel_r, aff_r, anti_r, gbit_r), bits)):
                if val:  # rows are pre-zeroed; most masks are 0
                    _fill_words(row, val)
                    touched.append(j)
            self._soft_rows(pod, ssel_r, ssel_w_r, sgrp_r, sgrp_w_r,
                            szone_r, szone_w_r)
            if pod.soft_node_affinity:
                touched += [5, 6]
            if pod.soft_group_affinity:
                touched += [7, 8]
            if pod.soft_zone_affinity:
                touched += [9, 10]
            self._ns_rows(pod, ns_any_r, ns_forb_r, ns_used_r,
                          ns_ncol_r, ns_nlo_r, ns_nhi_r, lenient)
            if getattr(pod, "required_node_affinity", ()) or ():
                touched += [11, 12, 13, 14, 15, 16]
            zb = self._zone_bits(pod, lenient)
            if zb[0]:
                _fill_words(zaff_r, zb[0])
                touched.append(17)
            if zb[1]:
                _fill_words(zanti_r, zb[1])
                touched.append(18)
            d_delta = self._degrade_capture
        finally:
            # A strict-mode raise must not leave the accumulator armed
            # for unrelated later _record_degraded calls.
            self._degrade_capture = None
        if d_delta:
            self._record_degraded(pod, d_delta)
        if key is not None:
            # Counted here — after a successful, hashable compute —
            # so the unhashable bypass and strict-mode raises don't
            # inflate it (the bounded cache's evictions still recount
            # shapes; the metric is compute COUNT, not cardinality).
            self.shape_cache_misses += 1
            if len(self._shape_cache) >= 8192:
                # Bounded: pathological all-distinct fleets fall back
                # to compute-per-pod, never unbounded memory.
                self._shape_cache.clear()
            self._shape_cache[key] = (
                bits,
                tuple((j, rows[j].copy()) for j in touched),
                d_delta)
        return bits

    def encode_pods(self, pods: Sequence[Pod],
                    node_of: Callable[[str], str],
                    lenient: bool = False,
                    pad_to: int | None = None) -> PodBatch:
        """Build a :class:`PodBatch` for up to ``cfg.max_pods`` pods.

        ``node_of`` resolves a peer pod name to its node name ("" if
        unplaced — such peers are dropped: traffic to a pod that has no
        home yet cannot pull the placement anywhere).  ``lenient``
        governs interner overflow (see :class:`Interner`): pass True
        for request-driven paths fed by untrusted manifests.

        ``pad_to`` overrides the batch's padded pod-axis extent
        (default ``cfg.max_pods``): request-driven paths like the
        extender webhook batch to the actual demand so a lone request
        does not pay a ``max_pods``-shaped kernel.  Each distinct value
        is a separate XLA compilation — callers should quantize.
        """
        cfg = self.cfg
        p, k, r = pad_to or cfg.max_pods, cfg.max_peers, cfg.num_resources
        w = cfg.mask_words
        if len(pods) > p:
            raise ValueError(f"batch of {len(pods)} exceeds "
                             f"{'pad_to' if pad_to else 'max_pods'}={p}")
        req = np.zeros((p, r), np.float32)
        peers = np.full((p, k), -1, np.int32)
        traffic = np.zeros((p, k), np.float32)
        tol = np.zeros((p, w), np.uint32)
        sel = np.zeros((p, w), np.uint32)
        aff = np.zeros((p, w), np.uint32)
        anti = np.zeros((p, w), np.uint32)
        gbit = np.zeros((p, w), np.uint32)
        prio = np.zeros((p,), np.float32)
        valid = np.zeros((p,), bool)
        t_soft = cfg.max_soft_terms
        ssel = np.zeros((p, t_soft, w), np.uint32)
        ssel_w = np.zeros((p, t_soft), np.float32)
        sgrp = np.zeros((p, t_soft, w), np.uint32)
        sgrp_w = np.zeros((p, t_soft), np.float32)
        szone = np.zeros((p, t_soft, w), np.uint32)
        szone_w = np.zeros((p, t_soft), np.float32)
        gidx = np.full((p,), -1, np.int32)
        sp_skew = np.zeros((p,), np.int32)
        sp_hard = np.zeros((p,), bool)
        t2, e_ns = cfg.max_ns_terms, cfg.max_ns_exprs
        ne = cfg.max_ns_num
        ns_any = np.zeros((p, t2, e_ns, w), np.uint32)
        ns_forb = np.zeros((p, t2, w), np.uint32)
        ns_used = np.zeros((p, t2), bool)
        ns_ncol = np.full((p, t2, ne), -1, np.int32)
        ns_nlo = np.full((p, t2, ne), -np.inf, np.float32)
        ns_nhi = np.full((p, t2, ne), np.inf, np.float32)
        zaff = np.zeros((p, w), np.uint32)
        zanti = np.zeros((p, w), np.uint32)
        granted: set[int] = set()  # first-pod escape, one per group
        with self._lock:
            for i, pod in enumerate(pods):
                # A nominated preemptor entering scoring: its own
                # request is about to compete for the reserved space —
                # drop the hold so it does not block itself.
                if self._nominations:
                    self._drop_nomination_locked(pod.uid)
                req[i] = _requests_vector(pod.requests, r)
                slot = 0
                for peer_name, vol in pod.peers.items():
                    if slot >= k:
                        break  # peer list truncated at max_peers
                    peer_node = node_of(peer_name)
                    if not peer_node:
                        continue
                    idx = self._node_index.get(peer_node)
                    if idx is None:
                        continue
                    peers[i, slot] = idx
                    traffic[i, slot] = vol
                    slot += 1
                bits = self._pod_constraint_rows(pod, lenient, (
                    tol[i], sel[i], aff[i], anti[i], gbit[i],
                    ssel[i], ssel_w[i], sgrp[i], sgrp_w[i],
                    szone[i], szone_w[i], ns_any[i], ns_forb[i],
                    ns_used[i], ns_ncol[i], ns_nlo[i], ns_nhi[i],
                    zaff[i], zanti[i]))
                self._apply_first_pod_escape(aff[i], zaff[i], gbit[i],
                                             granted)
                m = words_to_int(gbit[i])
                while m:
                    b = m & -m
                    m ^= b
                    granted.add(b.bit_length() - 1)
                gidx[i] = self._spread_slot(pod)
                sp_skew[i] = int(getattr(pod, "spread_maxskew", 0))
                sp_hard[i] = bool(getattr(pod, "spread_hard", True))
                if sp_skew[i] > 0 and gidx[i] < 0:
                    # A spread constraint with no countable group is
                    # inert — a DoNotSchedule pod would silently
                    # schedule anywhere.  Flag it like every other
                    # constraint degradation.
                    self._record_degraded(pod, 1)
                prio[i] = pod.priority
                valid[i] = True
        return PodBatch(
            req=jnp.asarray(req), peers=jnp.asarray(peers),
            peer_traffic=jnp.asarray(traffic), tol_bits=jnp.asarray(tol),
            sel_bits=jnp.asarray(sel), affinity_bits=jnp.asarray(aff),
            anti_bits=jnp.asarray(anti), group_bit=jnp.asarray(gbit),
            priority=jnp.asarray(prio), pod_valid=jnp.asarray(valid),
            soft_sel_bits=jnp.asarray(ssel), soft_sel_w=jnp.asarray(ssel_w),
            soft_grp_bits=jnp.asarray(sgrp), soft_grp_w=jnp.asarray(sgrp_w),
            soft_zone_bits=jnp.asarray(szone),
            soft_zone_w=jnp.asarray(szone_w),
            group_idx=jnp.asarray(gidx),
            spread_maxskew=jnp.asarray(sp_skew),
            spread_hard=jnp.asarray(sp_hard),
            ns_anyof=jnp.asarray(ns_any),
            ns_forbid=jnp.asarray(ns_forb),
            ns_term_used=jnp.asarray(ns_used),
            ns_num_col=jnp.asarray(ns_ncol),
            ns_num_lo=jnp.asarray(ns_nlo),
            ns_num_hi=jnp.asarray(ns_nhi),
            zaff_bits=jnp.asarray(zaff),
            zanti_bits=jnp.asarray(zanti))

    def encode_stream(self, pods: Sequence[Pod],
                      node_of: Callable[[str], str],
                      lenient: bool = False):
        """Encode a whole workload for the device-resident replay
        (:func:`~kubernetesnetawarescheduler_tpu.core.replay.replay_stream`).

        One-shot form of :meth:`encode_stream_chunks` — a single chunk
        spanning the whole workload, field-for-field identical to the
        chunked pass."""
        return next(self.encode_stream_chunks(
            pods, node_of, chunk_pods=max(len(pods), 1),
            lenient=lenient))

    def encode_stream_chunks(self, pods: Sequence[Pod],
                             node_of: Callable[[str], str],
                             chunk_pods: int,
                             lenient: bool = False):
        """ONE encode pass over the workload, yielded as
        :class:`PodStream` chunks of ``chunk_pods`` pods (the final
        chunk shorter; one empty chunk for an empty workload).

        The chunked pass and :meth:`encode_stream` are field-for-field
        equal: peer stream indices are GLOBAL (the index space covers
        the whole workload, so peers crossing chunk boundaries resolve
        identically), and the first-pod-escape ``granted`` set persists
        across chunks.  The encoder lock is held per chunk rather than
        across the pass, so a concurrent binder can interleave
        ``commit_many`` between chunks (the overlapped pipeline drain
        in bench/density.py) instead of stalling until the whole
        workload is encoded — safe because commits only ADD committed
        group members, which the escape already sees through
        ``granted`` for every in-stream pod.

        Unlike :meth:`encode_pods`, peers naming pods *within this
        stream* are kept as stream indices (resolved on device against
        the replay's own assignments); peers already placed resolve to
        node indices via ``node_of`` here, host-side.

        Peer-slot allocation mirrors the host loop draining this stream
        in ``cfg.max_pods``-sized batches: an in-stream peer in the same
        or a later batch can never have a node by the time this pod is
        scored (the host's ``node_of`` returns "" and skips it without
        consuming a slot), so it is skipped here too.  Residual
        divergence from the host loop is only possible past
        ``max_peers`` when an earlier-batch peer ends up unschedulable
        (the host frees its slot, the stream cannot know in advance).
        """
        ar = self._alloc_stream_arrays(len(pods))
        stream_index = _stream_index(pods)
        res_names = _res_names(self.cfg.num_resources)
        # First-pod escape: ``granted`` accumulates member slots of
        # every pod already encoded this pass, so only the genuinely
        # FIRST member of a group can take the waiver — later pods
        # chain onto earlier members (in the conflict loop within a
        # batch, or via committed counts across the host loop's
        # batches; the stream sees both through this one set, under
        # the same earlier-pods-bind approximation the peer-slot logic
        # uses).
        granted: set[int] = set()
        if chunk_pods < 1:
            raise ValueError(f"chunk_pods must be >= 1, got {chunk_pods}")

        s = len(pods)
        pos = 0
        while True:
            end = min(pos + chunk_pods, s)
            with self._lock:
                for i in range(pos, end):
                    pod = pods[i]
                    self._fill_stream_row(i, pod, ar, granted,
                                          lenient, res_names)
                    self._resolve_peer_slots(i, pod, stream_index,
                                             ar, node_of)
            yield _stream_slice(ar, pos, end)
            pos = end
            if pos >= s:
                return

    def _alloc_stream_arrays(self, s: int) -> dict[str, np.ndarray]:
        """Zero-initialized host-side arrays for a ``s``-pod stream,
        keyed by :class:`PodStream` field name."""
        cfg = self.cfg
        k, r, w = cfg.max_peers, cfg.num_resources, cfg.mask_words
        t_soft = cfg.max_soft_terms
        t2, e_ns = cfg.max_ns_terms, cfg.max_ns_exprs
        return {
            "req": np.zeros((s, r), np.float32),
            "peer_pods": np.full((s, k), -1, np.int32),
            "peer_nodes": np.full((s, k), -1, np.int32),
            "peer_traffic": np.zeros((s, k), np.float32),
            "tol_bits": np.zeros((s, w), np.uint32),
            "sel_bits": np.zeros((s, w), np.uint32),
            "affinity_bits": np.zeros((s, w), np.uint32),
            "anti_bits": np.zeros((s, w), np.uint32),
            "group_bit": np.zeros((s, w), np.uint32),
            "priority": np.zeros((s,), np.float32),
            "pod_valid": np.zeros((s,), bool),
            "soft_sel_bits": np.zeros((s, t_soft, w), np.uint32),
            "soft_sel_w": np.zeros((s, t_soft), np.float32),
            "soft_grp_bits": np.zeros((s, t_soft, w), np.uint32),
            "soft_grp_w": np.zeros((s, t_soft), np.float32),
            "soft_zone_bits": np.zeros((s, t_soft, w), np.uint32),
            "soft_zone_w": np.zeros((s, t_soft), np.float32),
            "group_idx": np.full((s,), -1, np.int32),
            "spread_maxskew": np.zeros((s,), np.int32),
            "spread_hard": np.zeros((s,), bool),
            "ns_anyof": np.zeros((s, t2, e_ns, w), np.uint32),
            "ns_forbid": np.zeros((s, t2, w), np.uint32),
            "ns_term_used": np.zeros((s, t2), bool),
            "ns_num_col": np.full((s, t2, cfg.max_ns_num), -1,
                                  np.int32),
            "ns_num_lo": np.full((s, t2, cfg.max_ns_num), -np.inf,
                                 np.float32),
            "ns_num_hi": np.full((s, t2, cfg.max_ns_num), np.inf,
                                 np.float32),
            "zaff_bits": np.zeros((s, w), np.uint32),
            "zanti_bits": np.zeros((s, w), np.uint32),
        }

    def _fill_stream_row(self, i: int, pod: Pod,
                         ar: dict[str, np.ndarray],
                         granted: set[int] | None, lenient: bool,
                         res_names) -> None:
        """Everything about row ``i`` EXCEPT peer resolution — the
        placement-independent share of the encode (requests,
        constraint bitmaps, spread slots).  With ``granted`` given,
        also applies the first-pod escape inline (the serial path);
        ``granted=None`` defers it to :meth:`finalize_stream`, which
        must re-judge it against the member counts current at dispatch
        time (commits mutate them).  Caller holds ``self._lock``."""
        _fill_requests_row(ar["req"][i], pod.requests, res_names)
        self._pod_constraint_rows(pod, lenient, (
            ar["tol_bits"][i], ar["sel_bits"][i],
            ar["affinity_bits"][i], ar["anti_bits"][i],
            ar["group_bit"][i],
            ar["soft_sel_bits"][i], ar["soft_sel_w"][i],
            ar["soft_grp_bits"][i], ar["soft_grp_w"][i],
            ar["soft_zone_bits"][i], ar["soft_zone_w"][i],
            ar["ns_anyof"][i], ar["ns_forbid"][i],
            ar["ns_term_used"][i], ar["ns_num_col"][i],
            ar["ns_num_lo"][i], ar["ns_num_hi"][i],
            ar["zaff_bits"][i], ar["zanti_bits"][i]))
        if granted is not None:
            self._apply_first_pod_escape(ar["affinity_bits"][i],
                                         ar["zaff_bits"][i],
                                         ar["group_bit"][i], granted)
            m = words_to_int(ar["group_bit"][i])
            while m:
                b = m & -m
                m ^= b
                granted.add(b.bit_length() - 1)
        ar["group_idx"][i] = self._spread_slot(pod)
        ar["spread_maxskew"][i] = int(getattr(pod, "spread_maxskew", 0))
        ar["spread_hard"][i] = bool(getattr(pod, "spread_hard", True))
        if ar["spread_maxskew"][i] > 0 and ar["group_idx"][i] < 0:
            # A spread constraint with no countable group is inert — a
            # DoNotSchedule pod would silently schedule anywhere.
            # Flag it like every other constraint degradation.
            self._record_degraded(pod, 1)
        ar["priority"][i] = pod.priority
        ar["pod_valid"][i] = True

    def _resolve_peer_slots(self, i: int, pod: Pod,
                            stream_index: dict[str, int],
                            ar: dict[str, np.ndarray],
                            node_of: Callable[[str], str]) -> None:
        """Peer-slot allocation for row ``i`` — the only
        placement-DEPENDENT share of the encode (``node_of`` consults
        live placements).  Caller holds ``self._lock``."""
        k = self.cfg.max_peers
        batch = self.cfg.max_pods
        peer_pods = ar["peer_pods"]
        peer_nodes = ar["peer_nodes"]
        traffic = ar["peer_traffic"]
        slot = 0
        for peer_name, vol in pod.peers.items():
            if slot >= k:
                break
            j = stream_index.get(peer_name)
            if j is not None:
                if j // batch >= i // batch:
                    # Same/later batch: unresolvable at scoring time,
                    # exactly as the host loop sees it — don't burn a
                    # slot.
                    continue
                peer_pods[i, slot] = j
            else:
                peer_node = node_of(peer_name)
                idx = (self._node_index.get(peer_node)
                       if peer_node else None)
                if idx is None:
                    continue
                peer_nodes[i, slot] = idx
            traffic[i, slot] = vol
            slot += 1

    def encode_stream_prepare(self, pods: Sequence[Pod],
                              lenient: bool = False
                              ) -> "PreparedStream":
        """Placement-independent half of :meth:`encode_stream` — the
        encode-ahead stage of the pipelined serving loop.

        Fills every stream array EXCEPT peer slots (requests,
        constraint bitmaps, spread, first-pod escape) on the calling
        thread, typically while the PREVIOUS burst's device step is in
        flight.  :meth:`finalize_stream` completes peer resolution
        against the placements visible at that moment (after the
        previous burst's assume has published its nodes) and returns
        the :class:`PodStream`; the composition is field-for-field
        identical to a serial :meth:`encode_stream` call made at
        finalize time."""
        ar = self._alloc_stream_arrays(len(pods))
        res_names = _res_names(self.cfg.num_resources)
        with self._lock:
            for i, pod in enumerate(pods):
                # granted=None: the first-pod escape consults LIVE
                # group member counts (mutated by commits), so it is
                # deferred to finalize alongside peer resolution.
                self._fill_stream_row(i, pod, ar, None,
                                      lenient, res_names)
        pristine = {"affinity_bits": ar["affinity_bits"].copy(),
                    "zaff_bits": ar["zaff_bits"].copy()}
        return PreparedStream(pods=tuple(pods), arrays=ar,
                              stream_index=_stream_index(pods),
                              pristine=pristine)

    def finalize_stream(self, prepared: "PreparedStream",
                        node_of: Callable[[str], str]):
        """Resolve the placement-dependent leftovers of a prepared
        stream — peer slots and the first-pod escape — against the
        CURRENT placements, and return the device :class:`PodStream`.
        Cheap relative to prepare: the peer/escape loops plus the
        host→device transfer.  Idempotent: every placement-dependent
        field is rebuilt from a clean slate (fault/restart paths may
        retry it)."""
        ar = prepared.arrays
        with self._lock:
            ar["affinity_bits"][...] = prepared.pristine[
                "affinity_bits"]
            ar["zaff_bits"][...] = prepared.pristine["zaff_bits"]
            granted: set[int] = set()
            for i, pod in enumerate(prepared.pods):
                self._apply_first_pod_escape(
                    ar["affinity_bits"][i], ar["zaff_bits"][i],
                    ar["group_bit"][i], granted)
                m = words_to_int(ar["group_bit"][i])
                while m:
                    b = m & -m
                    m ^= b
                    granted.add(b.bit_length() - 1)
                ar["peer_pods"][i] = -1
                ar["peer_nodes"][i] = -1
                ar["peer_traffic"][i] = 0.0
                self._resolve_peer_slots(i, pod, prepared.stream_index,
                                         ar, node_of)
        return _stream_slice(ar, 0, len(prepared.pods))


# ---------------------------------------------------------------------
# Device wave ring (ISSUE 17): bounded device-side staging for the
# persistent multi-cycle serving program.


def split_stream_waves(stream, wave_pods: int) -> list:
    """Slice an encoded (padded) PodStream into per-wave pytree
    segments of ``wave_pods`` pods each.  Pure views — concatenating
    the segments back in order reproduces the original arrays bit for
    bit, which is what keeps the multicycle window's single dispatch
    placement-identical to the per-cycle path."""
    return [
        jax.tree_util.tree_map(lambda x: x[a:a + wave_pods], stream)
        for a in range(0, stream.num_pods, wave_pods)
    ]


def concat_stream_waves(waves: list):
    """Re-join per-wave PodStream segments along the pod axis (the
    inverse of :func:`split_stream_waves`).  Runs as device ops on
    already-staged waves, so the serving loop's window dispatch
    consumes device-resident inputs — no bulk host re-upload at
    dispatch time (the r5/r6 device-boundary lesson)."""
    if len(waves) == 1:
        return waves[0]
    return jax.tree_util.tree_map(
        lambda *xs: jnp.concatenate(xs, axis=0), *waves)


class DeviceWaveRing:
    """Bounded ring of pre-encoded pod waves staged on device.

    The host enqueues each wave (one batch's slice of the encoded
    window) with :meth:`push` — a ``jax.device_put`` per segment, the
    only host→device traffic the multicycle path pays per wave — and
    the serving loop drains the whole ring into one scan window with
    :meth:`pop_window`.  ``push`` returns False (and counts
    ``overflow_total``) when the ring is full: the caller falls back
    to per-cycle dispatch for the overflow waves instead of dropping
    or blocking, so a mis-tuned ``multicycle_queue_depth`` degrades
    throughput, never correctness."""

    def __init__(self, capacity: int) -> None:
        self.capacity = max(1, int(capacity))
        self._waves: list = []
        self.pushed_total = 0
        self.overflow_total = 0

    def __len__(self) -> int:
        return len(self._waves)

    def push(self, wave) -> bool:
        if len(self._waves) >= self.capacity:
            self.overflow_total += 1
            return False
        self._waves.append(jax.device_put(wave))
        self.pushed_total += 1
        return True

    def pop_window(self):
        """Drain every staged wave as one concatenated stream (None
        when the ring is empty)."""
        waves, self._waves = self._waves, []
        if not waves:
            return None
        return concat_stream_waves(waves)
