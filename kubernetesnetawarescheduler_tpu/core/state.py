"""Device-resident columnar cluster state.

The reference keeps no state at all: every scheduling cycle re-scrapes all
five node_exporters synchronously (scheduler.go:275-279) and re-reads the
iperf3 JSON files from ``/home`` (scheduler.go:503-530), i.e. its "state"
is the network.  Here the cluster lives in TPU HBM as fixed-shape arrays,
updated asynchronously by the ingest layer, and scoring is pure compute:

- ``metrics[N, M]``          — generalized ``PrometheusNodeMetrics``
                               (struct at scheduler.go:24-32).
- ``lat[N, N]`` / ``bw[N, N]`` — the netperf-derived pairwise matrices
                               replacing per-node iperf3 files
                               (scheduler.go:503-530, run.sh:12-14).
- ``cap/used[N, R]``          — capacities & usage; the reference never
                               consults these (``pod`` unused in
                               ``prioritize``, scheduler.go:248).
- label/taint/group bitmasks  — batched feasibility, replacing the stock
                               k8s mechanisms the reference leaned on
                               (nodeAffinity/toleration in its probe
                               manifests, deployment.yaml:17-31).

All shapes are static (padded to ``cfg.max_nodes`` / ``cfg.max_pods``)
with validity masks so that live updates never recompile.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from kubernetesnetawarescheduler_tpu.config import SchedulerConfig


@struct.dataclass
class ClusterState:
    """Columnar cluster telemetry + allocation state (a JAX pytree).

    Shapes (``N = cfg.max_nodes``, ``M = cfg.num_metrics``,
    ``R = cfg.num_resources``):

    - ``metrics``      f32[N, M]   raw metric values per node
    - ``metrics_age``  f32[N]      seconds since each node's last update
    - ``lat``          f32[N, N]   pairwise latency (ms); 0 on diagonal
    - ``bw``           f32[N, N]   pairwise bandwidth (bits/s)
    - ``cap``          f32[N, R]   allocatable capacity
    - ``used``         f32[N, R]   currently allocated
    - ``node_valid``   bool[N]     padding/health mask
    - ``label_bits``   u32[N, W]   interned node-label set (multi-word
                                   bitmask, ``W = cfg.mask_words``;
                                   lazily populated — only selector-
                                   referenced labels carry bits)
    - ``taint_bits``   u32[N, W]   interned taint set (bitmask)
    - ``group_bits``   u32[N, W]   pod-groups present on the node
                                   (inter-pod affinity at hostname
                                   topology, as batched masks)
    - ``resident_anti`` u32[N, W]  OR of the anti-affinity selectors of
                                   pods already on the node — enforces
                                   k8s's *symmetric* required
                                   anti-affinity (a group-G pod may not
                                   join a node hosting a pod that
                                   declared anti-affinity to G)
    - ``node_zone``    i32[N]      topology domain id per node
                                   (interned zone label; -1 unknown —
                                   spread constraints cannot see such
                                   nodes)
    - ``gz_counts``    i32[G, Z]   scheduled pods per (group bit-slot,
                                   zone): the resident state behind
                                   topologySpreadConstraints
                                   (``G = 32 * W``, ``Z = max_zones``
                                   — a few KB, updated on device per
                                   placement)
    - ``az_anti``      u32[Z, W]   OR of the ZONE-scoped anti-affinity
                                   selectors of pods resident in each
                                   zone — the symmetric direction of
                                   zone-topologyKey podAntiAffinity
                                   (the zone analog of
                                   ``resident_anti``; asymmetric zone
                                   (anti-)affinity rides ``gz_counts``
                                   presence instead)
    """

    metrics: jax.Array
    metrics_age: jax.Array
    lat: jax.Array
    bw: jax.Array
    cap: jax.Array
    used: jax.Array
    node_valid: jax.Array
    label_bits: jax.Array
    taint_bits: jax.Array
    group_bits: jax.Array
    resident_anti: jax.Array
    node_zone: jax.Array
    gz_counts: jax.Array
    az_anti: jax.Array
    # f32[N, L]: parsed numeric label values per interned numeric KEY
    # column (cfg.max_numeric_labels; NaN = absent/non-numeric — every
    # Gt/Lt comparison against NaN is False, kube's fail-closed
    # direction for nodes missing the label).
    node_numeric: jax.Array

    @property
    def num_nodes(self) -> int:
        return self.metrics.shape[0]

    @property
    def num_metrics(self) -> int:
        return self.metrics.shape[1]

    @property
    def num_resources(self) -> int:
        return self.cap.shape[1]


@struct.dataclass
class PodBatch:
    """A batch of pending pods to place (a JAX pytree).

    Shapes (``P = cfg.max_pods``, ``K = cfg.max_peers``,
    ``R = cfg.num_resources``):

    - ``req``            f32[P, R]  resource requests
    - ``peers``          i32[P, K]  node index of each already-placed peer
                                    the pod exchanges traffic with
                                    (-1 = padding)
    - ``peer_traffic``   f32[P, K]  relative traffic volume per peer
    - ``tol_bits``       u32[P, W]  tolerated taints (bitmask)
    - ``sel_bits``       u32[P, W]  required node labels (bitmask; node
                                    must have ALL of these)
    - ``affinity_bits``  u32[P, W]  required co-located pod groups (one
                                    bit per required term; the node
                                    must host members of ALL of them —
                                    terms AND, kube semantics)
    - ``anti_bits``      u32[P, W]  anti-affinity pod groups (node must
                                    host NONE)
    - ``group_bit``      u32[P, W]  the pod's FULL membership mask:
                                    its annotation-group bit OR'd with
                                    every selector-group its labels
                                    satisfy (0 = member of nothing);
                                    committed to ``group_bits`` on
                                    bind.  Multi-bit by design — the
                                    zone counts, symmetric-anti check
                                    and first-pod escape all consume
                                    the full mask (ADVICE r3 low #3)
    - ``priority``       f32[P]     scheduling priority (higher first)
    - ``pod_valid``      bool[P]    padding mask
    """

    req: jax.Array
    peers: jax.Array
    peer_traffic: jax.Array
    tol_bits: jax.Array
    sel_bits: jax.Array
    affinity_bits: jax.Array
    anti_bits: jax.Array
    group_bit: jax.Array
    priority: jax.Array
    pod_valid: jax.Array
    # Preferred (soft) affinity terms, ``T = cfg.max_soft_terms`` per
    # bank: weighted score bonuses, not masks (types.py Pod
    # soft_node_affinity / soft_group_affinity).
    soft_sel_bits: jax.Array   # u32[P, T, W] node labels (ALL must match)
    soft_sel_w: jax.Array      # f32[P, T]    signed term weight
    soft_grp_bits: jax.Array   # u32[P, T, W] resident groups (ANY overlap)
    soft_grp_w: jax.Array      # f32[P, T]    signed term weight
    # Zone-scoped preferred pod (anti-)affinity: bonus w_t on nodes
    # whose ZONE hosts a member of the term's group (gz_counts
    # presence); negative = preferred zone spreading.
    soft_zone_bits: jax.Array  # u32[P, T, W] zone-resident groups
    soft_zone_w: jax.Array     # f32[P, T]    signed term weight
    # Topology spread (zone-level topologySpreadConstraints): the
    # pod's own group's bit-slot index (-1 = no group), the skew bound
    # (0 = no constraint), and whether violating it masks
    # (DoNotSchedule) or only penalizes (ScheduleAnyway).
    group_idx: jax.Array       # i32[P]
    spread_maxskew: jax.Array  # i32[P]
    spread_hard: jax.Array     # bool[P]
    # Hard nodeAffinity matchExpressions (``T2 = cfg.max_ns_terms``
    # OR'd terms, ``E = cfg.max_ns_exprs`` AND'd expressions each):
    # an expression passes when the node carries ANY ``ns_anyof`` bit
    # (all-zero expr slot = unused = pass); a term additionally
    # requires NO ``ns_forbid`` bit on the node (NotIn/DoesNotExist,
    # merged per term).  ``ns_term_used`` all-False = no constraint.
    ns_anyof: jax.Array        # u32[P, T2, E, W]
    ns_forbid: jax.Array       # u32[P, T2, W]
    ns_term_used: jax.Array    # bool[P, T2]
    # Numeric Gt/Lt comparisons per nodeSelectorTerm (``NE =
    # cfg.max_ns_num``): node_numeric[:, col] must satisfy
    # ``lo < value < hi`` (Gt v -> lo=v, Lt v -> hi=v; col -1 =
    # unused slot).
    ns_num_col: jax.Array      # i32[P, T2, NE]
    ns_num_lo: jax.Array       # f32[P, T2, NE]
    ns_num_hi: jax.Array       # f32[P, T2, NE]
    # Zone-scoped (topologyKey: topology.kubernetes.io/zone) hard pod
    # (anti-)affinity, in the same group bit space as
    # ``affinity_bits``/``anti_bits``: the pod requires (some member
    # of any ``zaff_bits`` group) / (no member of any ``zanti_bits``
    # group) resident in the TARGET NODE'S ZONE.  Presence is read
    # from ``gz_counts``; the symmetric direction from ``az_anti``.
    zaff_bits: jax.Array       # u32[P, W]
    zanti_bits: jax.Array      # u32[P, W]

    @property
    def num_pods(self) -> int:
        return self.req.shape[0]

    @property
    def max_peers(self) -> int:
        return self.peers.shape[1]


def init_cluster_state(cfg: SchedulerConfig, **overrides: Any) -> ClusterState:
    """An empty, all-padding cluster of static shape."""
    n, m, r = cfg.max_nodes, cfg.num_metrics, cfg.num_resources
    w = cfg.mask_words
    fields = dict(
        metrics=jnp.zeros((n, m), jnp.float32),
        metrics_age=jnp.zeros((n,), jnp.float32),
        lat=jnp.zeros((n, n), jnp.float32),
        bw=jnp.zeros((n, n), jnp.float32),
        cap=jnp.zeros((n, r), jnp.float32),
        used=jnp.zeros((n, r), jnp.float32),
        node_valid=jnp.zeros((n,), jnp.bool_),
        label_bits=jnp.zeros((n, w), jnp.uint32),
        taint_bits=jnp.zeros((n, w), jnp.uint32),
        group_bits=jnp.zeros((n, w), jnp.uint32),
        resident_anti=jnp.zeros((n, w), jnp.uint32),
        node_zone=jnp.full((n,), -1, jnp.int32),
        gz_counts=jnp.zeros((32 * w, cfg.max_zones), jnp.int32),
        az_anti=jnp.zeros((cfg.max_zones, w), jnp.uint32),
        node_numeric=jnp.full((n, cfg.max_numeric_labels), jnp.nan,
                              jnp.float32),
    )
    fields.update(overrides)
    return ClusterState(**fields)


def init_pod_batch(cfg: SchedulerConfig, **overrides: Any) -> PodBatch:
    """An empty, all-padding pod batch of static shape."""
    p, k, r = cfg.max_pods, cfg.max_peers, cfg.num_resources
    w = cfg.mask_words
    fields = dict(
        req=jnp.zeros((p, r), jnp.float32),
        peers=jnp.full((p, k), -1, jnp.int32),
        peer_traffic=jnp.zeros((p, k), jnp.float32),
        tol_bits=jnp.zeros((p, w), jnp.uint32),
        sel_bits=jnp.zeros((p, w), jnp.uint32),
        affinity_bits=jnp.zeros((p, w), jnp.uint32),
        anti_bits=jnp.zeros((p, w), jnp.uint32),
        group_bit=jnp.zeros((p, w), jnp.uint32),
        priority=jnp.zeros((p,), jnp.float32),
        pod_valid=jnp.zeros((p,), jnp.bool_),
        soft_sel_bits=jnp.zeros((p, cfg.max_soft_terms, w), jnp.uint32),
        soft_sel_w=jnp.zeros((p, cfg.max_soft_terms), jnp.float32),
        soft_grp_bits=jnp.zeros((p, cfg.max_soft_terms, w), jnp.uint32),
        soft_grp_w=jnp.zeros((p, cfg.max_soft_terms), jnp.float32),
        soft_zone_bits=jnp.zeros((p, cfg.max_soft_terms, w), jnp.uint32),
        soft_zone_w=jnp.zeros((p, cfg.max_soft_terms), jnp.float32),
        group_idx=jnp.full((p,), -1, jnp.int32),
        spread_maxskew=jnp.zeros((p,), jnp.int32),
        spread_hard=jnp.zeros((p,), jnp.bool_),
        ns_anyof=jnp.zeros((p, cfg.max_ns_terms, cfg.max_ns_exprs, w),
                           jnp.uint32),
        ns_forbid=jnp.zeros((p, cfg.max_ns_terms, w), jnp.uint32),
        ns_term_used=jnp.zeros((p, cfg.max_ns_terms), jnp.bool_),
        ns_num_col=jnp.full((p, cfg.max_ns_terms, cfg.max_ns_num), -1,
                            jnp.int32),
        ns_num_lo=jnp.full((p, cfg.max_ns_terms, cfg.max_ns_num),
                           -jnp.inf, jnp.float32),
        ns_num_hi=jnp.full((p, cfg.max_ns_terms, cfg.max_ns_num),
                           jnp.inf, jnp.float32),
        zaff_bits=jnp.zeros((p, w), jnp.uint32),
        zanti_bits=jnp.zeros((p, w), jnp.uint32),
    )
    fields.update(overrides)
    return PodBatch(**fields)


def _plane_dtype():
    """Compute dtype for the 0/1 bitplane matmuls: bf16 on TPU (rides
    the MXU; 0/1 inputs with f32 accumulation are exact), f32
    everywhere else — XLA CPU has no native bf16 gemm and emulates it
    ~50x slower than the multithreaded f32 path (measured 161 ms vs
    3.4 ms for one commit at N=5120, P=128 — this was the r3 CPU
    throughput regression, VERDICT r3 weak #1: every batch pays
    commit_assignments' two plane reductions)."""
    return jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32


def bit_planes(bits: jax.Array, dtype=None) -> jax.Array:
    """Decompose ``u32[P, W]`` masks into 0/1 bitplanes ``[P, W*32]``
    (default :func:`_plane_dtype` so the plane reduction rides the MXU
    on TPU and Eigen f32 on CPU; 0/1 inputs with f32 accumulation give
    exact counts for any P.  Integer dtypes serve the cummax-based
    segmented ORs in :mod:`~.assign`)."""
    p, w = bits.shape
    if dtype is None:
        dtype = _plane_dtype()
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return ((bits[:, :, None] >> shifts) & jnp.uint32(1)) \
        .reshape(p, w * 32).astype(dtype)


def planes_to_words(present: jax.Array) -> jax.Array:
    """Re-pack boolean bitplanes ``[N, W*32]`` into ``u32[N, W]``
    masks (inverse of :func:`bit_planes` on presence)."""
    n, cols = present.shape
    w = cols // 32
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return jnp.sum(present.reshape(n, w, 32).astype(jnp.uint32) << shifts,
                   axis=-1, dtype=jnp.uint32)


def scatter_or_onehot(onehot: jax.Array, bits: jax.Array) -> jax.Array:
    """Per-node OR of per-pod multi-word bitmasks: ``out[n, :] =
    OR_p onehot[p, n] ? bits[p, :]`` for ``bits u32[P, W]``.

    Decomposed into bitplanes and reduced over the pod axis with ONE
    ``[N, P] x [P, W*32]`` MXU matmul (count > 0 ⇔ bit present)
    instead of a ``lax.reduce`` with ``bitwise_or``, which GSPMD cannot
    partition across a sharded pod axis (the matmul's pod-axis
    contraction becomes a plain psum).
    """
    counts = jax.lax.dot_general(
        onehot.astype(_plane_dtype()), bit_planes(bits),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # [N, W*32]
    return planes_to_words(counts > 0.5)


def commit_assignments(state: ClusterState, pods: PodBatch,
                       assignment: jax.Array) -> ClusterState:
    """Apply a batch assignment to the allocation state.

    ``assignment`` is i32[P] with the chosen node per pod (-1 =
    unschedulable).  Adds each placed pod's requests to ``used`` and ORs
    its group bit into the node's ``group_bits`` — the device-side
    counterpart of the reference's ``Bind`` POST (scheduler.go:196-206),
    which is emitted host-side by the binder.
    """
    placed = (assignment >= 0) & pods.pod_valid
    safe_idx = jnp.where(placed, assignment, 0)
    add = jnp.where(placed[:, None], pods.req, 0.0)
    used = state.used.at[safe_idx].add(add, mode="drop")
    # Per-node OR of the placed pods' group bits.  A scatter-add would
    # double-count two same-group pods landing on one node, so reduce a
    # one-hot [P, N] mask with bitwise-or instead.
    onehot = placed[:, None] & (
        assignment[:, None] == jnp.arange(state.num_nodes)[None, :])
    # Zone-scoped symmetric anti-affinity: OR each placed pod's
    # zanti_bits into its landing ZONE's row.  Several placed pods can
    # share a zone (and, via the multi-accept prefix, even a node), so
    # this must be an OR-reduction over a [P, Z] one-hot, not a
    # scatter-set; pods on zone-less nodes drop out (their "zone" is
    # the node itself — the hostname machinery already covers it).
    zone_of = state.node_zone[jnp.clip(assignment, 0,
                                       state.num_nodes - 1)]
    z = state.az_anti.shape[0]
    zhot = (placed & (zone_of >= 0))[:, None] & (
        jnp.clip(zone_of, 0, z - 1)[:, None]
        == jnp.arange(z)[None, :])
    return state.replace(
        used=used,
        group_bits=state.group_bits | scatter_or_onehot(onehot,
                                                        pods.group_bit),
        resident_anti=state.resident_anti | scatter_or_onehot(
            onehot, pods.anti_bits),
        gz_counts=add_zone_counts(state.gz_counts, state.node_zone,
                                  pods.group_bit, assignment, placed),
        az_anti=state.az_anti | scatter_or_onehot(zhot,
                                                  pods.zanti_bits))


def add_zone_counts(gz_counts: jax.Array, node_zone: jax.Array,
                    group_bit: jax.Array, assignment: jax.Array,
                    placed: jax.Array) -> jax.Array:
    """Add placed pods' FULL membership masks (``u32[P, W]``) into the
    per-(group-slot, zone) count matrix (the resident state behind
    topologySpreadConstraints and zone-scoped affinity).  Counting
    every membership bit — not just a single own-group slot — keeps
    the device replay consistent with the host ledger, where
    label-driven selector-group memberships are multi-bit.  Pods on
    zone-less nodes contribute nothing.  Same partitionable one-hot
    matmul shape as :func:`scatter_or_onehot` (pod-axis contraction →
    psum under GSPMD)."""
    z = gz_counts.shape[1]
    zone = node_zone[jnp.clip(assignment, 0, node_zone.shape[0] - 1)]
    ok = placed & (zone >= 0)
    zhot = ok[:, None] & (jnp.clip(zone, 0, z - 1)[:, None]
                          == jnp.arange(z)[None, :])      # [P, Z]
    counts = jax.lax.dot_general(
        zhot.astype(_plane_dtype()), bit_planes(group_bit),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)               # [Z, G]
    return gz_counts + counts.T.astype(jnp.int32)


def round_up(x: int, mult: int) -> int:
    """Smallest multiple of ``mult`` that is >= ``x``."""
    return ((x + mult - 1) // mult) * mult


def pad_axis(x: jax.Array, size: int, axis: int = 0,
             fill: float = 0.0) -> jax.Array:
    """Pad ``x`` along ``axis`` up to ``size`` with ``fill``."""
    cur = x.shape[axis]
    if cur > size:
        raise ValueError(f"axis {axis} has {cur} > max {size}")
    if cur == size:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, size - cur)
    return jnp.pad(x, widths, constant_values=fill)


# ---------------------------------------------------------------------------
# Fleet batching (r15): leading cluster axis over whole-state pytrees.
# ---------------------------------------------------------------------------


def stack_trees(trees):
    """Stack same-shape pytrees along a NEW leading cluster axis.

    Every PLANES array (and every PodBatch column) of tenant ``k``
    lands at ``out.<leaf>[k]`` — the batched device state the fleet
    vmaps the fused step over.  All inputs must share one treedef and
    per-leaf shape/dtype (one padding bucket); a mismatch raises
    through ``jnp.stack``."""
    if not trees:
        raise ValueError("stack_trees needs at least one tree")
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *trees)


def index_tree(tree, k: int):
    """Tenant ``k``'s row of a :func:`stack_trees` result (device-side
    slice; no host copy)."""
    return jax.tree_util.tree_map(lambda a: a[k], tree)


def set_tree_row(tree, k: int, row):
    """Functionally replace tenant ``k``'s row — the per-tenant state
    refresh between fleet cycles (donated under jit, so the batched
    buffer updates in place)."""
    return jax.tree_util.tree_map(
        lambda a, r: a.at[k].set(r), tree, row)
