"""Checkpoint / resume + the decision log.

The reference has NO persistence: its only state is the in-memory pod
channel plus the last scraped values, so a scheduler restart loses every
queued pod (they are enqueued only on ADD events, scheduler.go:165-173,
with no re-list on startup).  SURVEY.md §5 sets the bar for the build:
pending pods are reconstructable from the API server (that part is
:meth:`~..k8s.client.ClusterClient.list_pending_pods` + the informer
resync), and the *metric store* — the HBM-resident matrices the ingest
pipeline spent minutes building — plus the *decision log* are
snapshotted here so benchmarks replay deterministically.

A checkpoint is a directory:

- ``state.npz``  — every staging array of the :class:`~.encode.Encoder`
  (metrics, ages, the ``N×N`` lat/bw matrices, capacity/usage, validity
  and constraint bitmasks).
- ``meta.json``  — config echo, node name table, interner tables
  (string -> bit position), and counters.
- ``MANIFEST.json`` — per-file SHA-256 digests; its rename is the
  SINGLE commit point of a save (r10).  Payload files are written to
  ``.staging/`` and renamed into place first, the previous good file
  set is preserved under ``previous/``, and restore verifies every
  digest — a crash anywhere in the sequence leaves either the old
  committed set or a digest mismatch that falls back to
  ``previous/``, never a silently-torn mixed-version checkpoint (the
  pre-r10 bug: ``state.npz`` and ``meta.json`` were ``os.replace``d
  independently).

``decisions.jsonl`` (one JSON object per scheduling decision) is written
by :class:`DecisionLog`, which the loop appends to; replaying the same
pod stream against a restored checkpoint must reproduce it bit-for-bit
(test: tests/test_checkpoint.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
from typing import Iterator, Sequence

import numpy as np

from kubernetesnetawarescheduler_tpu.config import (
    SchedulerConfig,
    config_from_dict,
    config_to_dict,
)
from kubernetesnetawarescheduler_tpu.core.encode import (
    Encoder,
    words_to_int,
)

_STATE_ARRAYS = (
    "_metrics", "_metrics_age", "_lat", "_bw", "_cap", "_used",
    "_node_valid", "_label_bits", "_taint_bits", "_group_bits",
    "_resident_anti", "_node_zone", "_gz_counts", "_az_anti",
    "_node_numeric",
)

# Format history (NONE of the pre-v6 formats load anymore — see
# _ACCEPTED_VERSIONS; kept as a record of what each version added):
# v2 widened constraint bitmasks to u32[N, mask_words] and persisted
# raw node-label sets; v3 added topology-spread state; v4 zone-scoped
# anti-affinity residency; v5 the labelSelector-parity registry with
# per-record full membership masks and pod labels.  (The _rec() short-
# entry tolerances below remain live for a different reason: ledger
# ENTRIES may legitimately predate group tracking — the phantom-ref
# behavior test_restore_rebuilds_group_refcounts pins.)
# v6: namespace-scoped group keys (round 4) — selector-group and
# annotation-group keys parsed from kube objects now carry the
# namespace qualifier (kubeclient.NS_SEP).  Pre-v6 checkpoints hold
# memberships under the old cluster-wide keys: restoring them into the
# scoped parser would silently SPLIT each group across old/new keys
# (old residents invisible to new pods' terms — anti-affinity would
# degrade open without an event), so pre-v6 is REFUSED rather than
# migrated; the ledger is reconstructable from the API server.
FORMAT_VERSION = 6
_ACCEPTED_VERSIONS = (6,)


@dataclasses.dataclass(frozen=True)
class Decision:
    """One scheduling outcome, as logged: ``node == ""`` means
    unschedulable (the reference's analog is the "Scheduled" k8s Event,
    scheduler.go:214-233 — we keep those too; this log is the replayable
    record)."""

    seq: int
    pod: str
    node: str

    def to_json(self) -> str:
        return json.dumps({"seq": self.seq, "pod": self.pod,
                           "node": self.node})


class DecisionLog:
    """Append-only decision record with optional streaming to disk."""

    def __init__(self, path: str | None = None) -> None:
        self.decisions: list[Decision] = []
        self._fh = open(path, "a", encoding="utf-8") if path else None

    def append(self, pod: str, node: str) -> None:
        d = Decision(len(self.decisions), pod, node)
        self.decisions.append(d)
        if self._fh is not None:
            self._fh.write(d.to_json() + "\n")
            self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __len__(self) -> int:
        return len(self.decisions)

    def __iter__(self) -> Iterator[Decision]:
        return iter(self.decisions)

    @staticmethod
    def load(path: str) -> "DecisionLog":
        log = DecisionLog()
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                if line.strip():
                    obj = json.loads(line)
                    log.decisions.append(
                        Decision(obj["seq"], obj["pod"], obj["node"]))
        return log

    def same_as(self, other: "DecisionLog") -> bool:
        return [dataclasses.astuple(d) for d in self.decisions] == \
            [dataclasses.astuple(d) for d in other.decisions]


# ---------------------------------------------------------------------------
# Manifest protocol (r10): per-file SHA-256 digests, one commit point.
# ---------------------------------------------------------------------------

MANIFEST = "MANIFEST.json"
PREVIOUS_DIR = "previous"
_STAGING_DIR = ".staging"


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def verify_manifest(path: str) -> "list[str] | None":
    """Digest-check a checkpoint directory against its manifest.

    Returns ``None`` when no manifest exists (a pre-r10 checkpoint —
    the caller decides whether to trust it), ``[]`` when every listed
    file is present with a matching SHA-256, and a list of
    human-readable mismatch descriptions otherwise."""
    mpath = os.path.join(path, MANIFEST)
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath, encoding="utf-8") as fh:
            manifest = json.load(fh)
        files = dict(manifest["files"])
    except Exception as exc:  # noqa: BLE001 — unreadable manifest IS
        # a verification failure, not a missing one
        return [f"manifest unreadable: {exc}"]
    errors: list[str] = []
    for name, digest in files.items():
        fpath = os.path.join(path, name)
        if not os.path.exists(fpath):
            errors.append(f"{name}: listed in manifest but missing")
        elif _sha256_file(fpath) != digest:
            errors.append(f"{name}: SHA-256 mismatch")
    return errors


def update_manifest(path: str) -> None:
    """Recompute the manifest digests for the files currently in
    ``path`` (keeping the existing file list).  For tooling and tests
    that legitimately edit a checkpoint in place — production writers
    go through :func:`save_checkpoint`'s staged commit."""
    mpath = os.path.join(path, MANIFEST)
    with open(mpath, encoding="utf-8") as fh:
        manifest = json.load(fh)
    manifest["files"] = {
        name: _sha256_file(os.path.join(path, name))
        for name in manifest["files"]
        if os.path.exists(os.path.join(path, name))}
    tmp = mpath + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(manifest, fh, indent=2)
    os.replace(tmp, mpath)


def resolve_checkpoint_dir(path: str) -> str:
    """The directory restore should actually read: ``path`` when its
    manifest verifies (or predates manifests), else the preserved
    ``previous/`` good set, else a :class:`ValueError` — garbage is
    REFUSED, never loaded."""
    errors = verify_manifest(path)
    if errors is None:
        # Pre-r10 checkpoint: no digests to check.  Loaded as before
        # (np.load/json still fail loudly on gross truncation).
        return path
    if not errors:
        return path
    prev = os.path.join(path, PREVIOUS_DIR)
    prev_errors = verify_manifest(prev)
    if prev_errors == []:
        import sys

        print(f"WARNING: checkpoint {path} failed verification "
              f"({'; '.join(errors)}); falling back to the previous "
              "good checkpoint", file=sys.stderr)
        return prev
    raise ValueError(
        f"checkpoint {path} is corrupt ({'; '.join(errors)}) and no "
        "verified previous checkpoint is available — refusing to "
        "restore (start fresh; state rebuilds from the API server)")


def read_state_arrays(path: str) -> "dict[str, np.ndarray]":
    """Load (and digest-verify) just the ``state.npz`` plane arrays
    from a checkpoint — the integrity repair ladder's
    checkpoint-restore rung reads staging planes without rebuilding a
    whole Encoder."""
    base = resolve_checkpoint_dir(path)
    out: dict[str, np.ndarray] = {}
    with np.load(os.path.join(base, "state.npz")) as data:
        for name in _STATE_ARRAYS:
            key = name.lstrip("_")
            if key not in data:
                raise ValueError(
                    f"checkpoint state.npz is missing array {name!r}")
            out[key] = np.array(data[key])
    return out


# ---------------------------------------------------------------------------
# Encoder snapshot <-> directory.
# ---------------------------------------------------------------------------


def save_checkpoint(path: str, encoder: Encoder,
                    policy=None,
                    extra_meta: dict | None = None) -> None:
    """Write the encoder's full staging state (the host mirror of the
    HBM matrices) + naming/interning tables under ``path``.

    ``policy``, when given, is the loop's learned
    :class:`~kubernetesnetawarescheduler_tpu.policy.ScoringPolicy`:
    its parameters/optimizer/example ring land in ``policy.npz``
    beside the encoder state, and the promotion provenance (which
    parameter version shipped, under which gate decision) rides the
    manifest-verified meta so tools/state_audit.py can cross-check
    them offline.

    ``extra_meta`` (r15): caller-owned top-level meta entries — the
    fleet server stamps ``{"fleet": {"cluster_id": ...}}`` so a
    tenant's checkpoint directory is self-identifying.  Keys must not
    collide with the reserved encoder/policy meta; collisions raise.
    The MANIFEST protocol (staging, previous/ rotation, digest
    verification) is unchanged.

    Multi-cycle serving (r16) rides the same seam: serve.py stamps
    ``{"multicycle": {"k", "waves_inflight", "last_retired_cycle"}}``
    (SchedulerLoop.multicycle_meta()).  Usage commits only at wave
    RETIRE, so the ledger here never contains a dispatched-but-
    unretired wave — a mid-window crash restores to
    ``last_retired_cycle`` by construction, and the unretired waves'
    pods re-arrive Pending through the informer resync.  Optional
    key, read via .get: no format bump, pre-r16 checkpoints load
    unchanged."""
    os.makedirs(path, exist_ok=True)
    with encoder._lock:
        # Deep copies under the lock: serialization happens after the
        # lock is released, and live ingest threads (scrape pool /
        # probe orchestrator) may keep writing the staging arrays — a
        # reference snapshot would tear mid-savez.
        arrays = {name.lstrip("_"): getattr(encoder, name).copy()
                  for name in _STATE_ARRAYS}
        meta = {
            "format_version": FORMAT_VERSION,
            "config": config_to_dict(encoder.cfg),
            "node_names": list(encoder._node_names),
            # Raw label sets per node index (lazy interning: the bit
            # arrays only carry selector-referenced labels; the raw
            # strings are needed so future selectors can backfill).
            "node_labels": {
                str(idx): sorted(labels)
                for idx, labels in encoder._node_labels.items()},
            "interners": {
                "labels": dict(encoder.labels._bits),
                "taints": dict(encoder.taints._bits),
                "groups": dict(encoder.groups._bits),
            },
            # Usage ledger: without it a restored daemon could not
            # release usage for pods bound before the restart.  The
            # commit stamp is not persisted — pre-restart commits are
            # by definition older than any post-restart listing.
            "committed": {
                uid: [rec.node, [float(x) for x in rec.req],
                      rec.priority, rec.namespace, rec.name,
                      int(rec.group_bit), int(rec.anti_bits),
                      int(rec.pdb_min), int(rec.group_slot),
                      int(rec.zone), int(rec.zanti_bits),
                      int(rec.member_bits),
                      (sorted(rec.labels) if rec.labels is not None
                       else None),
                      rec.gang_key]
                for uid, rec in encoder._committed.items()
            },
            # Gangs inside their assume->bind window at snapshot time:
            # restore ROLLS THESE BACK (all-or-nothing must hold
            # across a crash — the bind outcome is unknown, and a
            # half-bound gang resurrected from the ledger would
            # violate the atomicity invariant).  Optional key, read
            # via .get: no format bump needed.
            "gangs_inflight": {
                key: [list(e) for e in entries]
                for key, entries in encoder._inflight_gangs.items()},
            # Live migrations inside their evict->rebind window
            # (core/rebalance.py): restore rolls back the TARGET
            # commits of every member so a crashed move lands
            # fully-reverted, never half-evicted.  Optional key, read
            # via .get: no format bump needed, pre-r12 checkpoints
            # load unchanged.
            "migrations_inflight": {
                key: [list(e) for e in entries]
                for key, entries in
                encoder._inflight_migrations.items()},
            # Elastic reshapes inside their evict->re-pin window
            # (r17): restore settles the gang to fully-the-old-shape
            # (rolls back every affected member; resync re-places the
            # gang as a unit) — never a hybrid realization.  Optional
            # key, read via .get: no format bump needed, pre-r17
            # checkpoints load unchanged.
            "reshapes_inflight": {
                key: [v[0], v[1], [list(e) for e in v[2]]]
                for key, v in encoder._inflight_reshapes.items()},
            # Committed realization per shaped gang ([chosen_count,
            # declared_count]) — tools/state_audit.py cross-checks it
            # against the committed member placements.  Optional key.
            "gang_realizations": {
                key: list(v)
                for key, v in encoder._gang_realizations.items()},
            # Zone interner (topology-spread domains).
            "zones": dict(encoder._zone_index),
            # Numeric-label columns (v5): Gt/Lt key -> column of
            # _node_numeric.
            "numeric_keys": dict(encoder._numeric_keys),
            # Selector-group registry (v5): group key -> canonical
            # labelSelector structure, as nested lists.
            "selector_defs": {
                key: [[list(p) for p in ml],
                      [[op, k2, list(vals)] for op, k2, vals in exprs]]
                for key, (ml, exprs)
                in encoder._selector_defs.items()},
        }
    if policy is not None:
        meta["policy"] = {
            "version": int(policy.version),
            "promoted_version": int(policy.promoted_version),
            "last_promotion": policy.last_promotion,
        }
    if extra_meta:
        clash = set(extra_meta) & set(meta)
        if clash:
            raise ValueError(
                f"extra_meta keys collide with reserved checkpoint "
                f"meta: {sorted(clash)}")
        meta.update(extra_meta)
    # Staged commit (r10): every payload file is written to .staging/
    # first, the CURRENT good set is preserved under previous/, the
    # payload files rename into place, and the MANIFEST rename is the
    # single commit point.  A crash anywhere leaves either the old
    # committed set intact or a digest mismatch restore detects and
    # falls back from — never the pre-r10 torn mixed-version window
    # (state.npz and meta.json os.replace'd independently).
    staging = os.path.join(path, _STAGING_DIR)
    shutil.rmtree(staging, ignore_errors=True)
    os.makedirs(staging)
    with open(os.path.join(staging, "state.npz"), "wb") as fh:
        np.savez_compressed(fh, **arrays)
    with open(os.path.join(staging, "meta.json"), "w",
              encoding="utf-8") as fh:
        json.dump(meta, fh, indent=2)
    payload = ["state.npz", "meta.json"]
    # Learned topology model (netmodel/): beside the encoder state, so
    # restarts resume learning instead of re-learning 54 hours of
    # probes from scratch.  Written only when attached; a stale file
    # from a since-detached model is dropped from the manifest and
    # removed post-commit so restore cannot resurrect it.
    if encoder.netmodel is not None:
        encoder.netmodel.save(os.path.join(staging, "netmodel.npz"))
        payload.append("netmodel.npz")
    # Learned scoring policy (policy/): same attach-only discipline as
    # the netmodel file — written when the loop runs one, dropped from
    # the manifest (and removed post-commit) when it does not.
    if policy is not None:
        policy.save(os.path.join(staging, "policy.npz"))
        payload.append("policy.npz")
    manifest = {
        "format_version": FORMAT_VERSION,
        "files": {name: _sha256_file(os.path.join(staging, name))
                  for name in payload},
    }
    with open(os.path.join(staging, MANIFEST), "w",
              encoding="utf-8") as fh:
        json.dump(manifest, fh, indent=2)
    # Preserve the current committed set — only if it verifies (a torn
    # current set must not overwrite an older good previous/).  Copies,
    # not renames: a crash mid-rotation must leave the committed set in
    # place, and a torn previous/ is detected by ITS manifest (copied
    # last).
    if verify_manifest(path) == []:
        prev = os.path.join(path, PREVIOUS_DIR)
        os.makedirs(prev, exist_ok=True)
        with open(os.path.join(path, MANIFEST),
                  encoding="utf-8") as fh:
            cur_files = list(json.load(fh)["files"])
        for name in cur_files:
            tmp = os.path.join(prev, name + ".tmp")
            shutil.copy2(os.path.join(path, name), tmp)
            os.replace(tmp, os.path.join(prev, name))
        tmp = os.path.join(prev, MANIFEST + ".tmp")
        shutil.copy2(os.path.join(path, MANIFEST), tmp)
        os.replace(tmp, os.path.join(prev, MANIFEST))
    # Commit: payload first, manifest LAST.
    for name in payload:
        os.replace(os.path.join(staging, name),
                   os.path.join(path, name))
    os.replace(os.path.join(staging, MANIFEST),
               os.path.join(path, MANIFEST))
    npz = os.path.join(path, "netmodel.npz")
    if encoder.netmodel is None and os.path.exists(npz):
        os.remove(npz)
    pol_npz = os.path.join(path, "policy.npz")
    if policy is None and os.path.exists(pol_npz):
        os.remove(pol_npz)
    shutil.rmtree(staging, ignore_errors=True)


def load_checkpoint(path: str,
                    cfg: SchedulerConfig | None = None,
                    settle_inflight: bool = True) -> Encoder:
    """Reconstruct an :class:`Encoder` from :func:`save_checkpoint`
    output.  ``cfg`` overrides the checkpointed config (shapes must
    match the stored arrays).  ``settle_inflight=False`` skips the
    gang/migration rollback passes and restores the ledger EXACTLY as
    written — the offline auditor's pristine read (a restore that will
    actually serve must keep the default and settle).

    Restore resolves through the r10 MANIFEST: a committed set whose
    digests verify loads as-is; a torn/corrupted set falls back to the
    ``previous/`` good set; if neither verifies the load REFUSES
    (:class:`ValueError`) rather than deserialize garbage into hard
    allocation constraints.  Legacy checkpoints (no manifest) load
    exactly as before."""
    path = resolve_checkpoint_dir(path)
    with open(os.path.join(path, "meta.json"), encoding="utf-8") as fh:
        meta = json.load(fh)
    if meta.get("format_version") not in _ACCEPTED_VERSIONS:
        raise ValueError(
            f"unsupported checkpoint format "
            f"{meta.get('format_version')} (this build reads "
            f"{_ACCEPTED_VERSIONS}; pre-v6 group keys predate "
            "namespace scoping and cannot be restored faithfully — "
            "start fresh, the ledger rebuilds from the API server)")
    stored_cfg = config_from_dict(meta["config"])
    cfg = cfg or stored_cfg
    if (cfg.max_nodes, cfg.num_metrics, cfg.num_resources,
            cfg.mask_words) != (
            stored_cfg.max_nodes, stored_cfg.num_metrics,
            stored_cfg.num_resources, stored_cfg.mask_words):
        raise ValueError(
            "config shapes do not match checkpoint: "
            f"{(cfg.max_nodes, cfg.num_metrics, cfg.num_resources, cfg.mask_words)} vs "
            f"{(stored_cfg.max_nodes, stored_cfg.num_metrics, stored_cfg.num_resources, stored_cfg.mask_words)}")
    enc = Encoder(cfg)
    with np.load(os.path.join(path, "state.npz")) as data:
        for name in _STATE_ARRAYS:
            if name.lstrip("_") not in data:
                # v6 writes every array; a file missing one is corrupt
                # and must fail loudly, not restore hard constraints
                # against silently-empty state.  (The pre-v6
                # missing-array tolerances died with their versions.)
                raise ValueError(
                    f"checkpoint state.npz is missing array {name!r}")
            stored = data[name.lstrip("_")]
            target = getattr(enc, name)
            if stored.shape != target.shape:
                raise ValueError(
                    f"checkpoint array {name} has shape {stored.shape}, "
                    f"expected {target.shape}")
            target[...] = stored
    enc._node_names = list(meta["node_names"])
    # "" entries are tombstones of removed nodes: not indexable, and
    # their slots go back on the free list (order preserved).
    enc._node_index = {n: i for i, n in enumerate(enc._node_names) if n}
    enc._free_slots = [i for i, n in enumerate(enc._node_names) if not n]
    # Generations/stamps are process-local guards (in-flight cycles,
    # reconcile races) — a fresh process has neither, so zeros suffice.
    enc._node_gen = [0] * len(enc._node_names)
    enc._node_stamp = [0.0] * len(enc._node_names)
    for attr, table in meta["interners"].items():
        getattr(enc, attr)._bits = {k: int(v) for k, v in table.items()}
    enc._zone_index = {k: int(v)
                       for k, v in meta.get("zones", {}).items()}
    for idx_s, labels in meta.get("node_labels", {}).items():
        idx = int(idx_s)
        enc._node_labels[idx] = frozenset(labels)
        for s in labels:
            enc._label_nodes.setdefault(s, set()).add(idx)
    from kubernetesnetawarescheduler_tpu.core.encode import CommitRecord

    def _rec(entry) -> CommitRecord:
        idx, req = entry[0], entry[1]
        prio = float(entry[2]) if len(entry) > 2 else 0.0
        ns = entry[3] if len(entry) > 3 else "default"
        name = entry[4] if len(entry) > 4 else ""
        gbit = int(entry[5]) if len(entry) > 5 else 0
        abits = int(entry[6]) if len(entry) > 6 else 0
        pdb = int(entry[7]) if len(entry) > 7 else 0
        gslot = int(entry[8]) if len(entry) > 8 else -1
        zone = int(entry[9]) if len(entry) > 9 else -1
        zanti = int(entry[10]) if len(entry) > 10 else 0
        member = int(entry[11]) if len(entry) > 11 else 0
        # Pre-v5 entries (or null): labels unknown — never re-claim.
        labels = (frozenset(entry[12])
                  if len(entry) > 12 and entry[12] is not None
                  else None)
        gang_key = str(entry[13]) if len(entry) > 13 and entry[13] else ""
        return CommitRecord(int(idx), np.asarray(req, np.float32), 0.0,
                            prio, ns, name, gbit, abits, pdb,
                            group_slot=gslot, zone=zone,
                            zanti_bits=zanti, member_bits=member,
                            labels=labels, gang_key=gang_key)

    enc._committed = {uid: _rec(entry)
                      for uid, entry in meta.get("committed", {}).items()}
    # Selector-group registry (v5; absent pre-v5).
    enc._selector_defs = {
        key: (tuple((str(k2), str(v)) for k2, v in ml),
              tuple((str(op), str(k2), tuple(str(x) for x in vals))
                    for op, k2, vals in exprs))
        for key, (ml, exprs)
        in meta.get("selector_defs", {}).items()}
    enc._selector_gen = len(enc._selector_defs)
    enc._numeric_keys = {k: int(v) for k, v
                         in meta.get("numeric_keys", {}).items()}
    # Group/anti refcounts and cluster-wide member counts are derived
    # state: rebuild from the ledger (member_bits when present, the
    # legacy single group_bit otherwise).
    for rec in enc._committed.values():
        member = rec.member_bits or rec.group_bit
        if member:
            enc._ref_add(enc._group_refs, rec.node, member)
            m = member
            while m:
                b = m & -m
                m ^= b
                enc._group_member_counts[b.bit_length() - 1] += 1
        if rec.anti_bits:
            enc._ref_add(enc._anti_refs, rec.node, rec.anti_bits)
        if rec.zanti_bits and rec.zone >= 0:
            enc._ref_add(enc._az_anti_refs, rec.zone, rec.zanti_bits)
    # Bits set in the restored arrays with NO ledger member (ledger
    # entries written before group bits were persisted) get a phantom
    # +1 so a later same-group commit+release cycle cannot clear a bit
    # whose pre-upgrade member may still be running — sticky-
    # conservative, exactly the pre-refcount behavior for those bits.
    for refs, bit_arr, rows in (
            (enc._group_refs, enc._group_bits, len(enc._node_names)),
            (enc._anti_refs, enc._resident_anti, len(enc._node_names)),
            (enc._az_anti_refs, enc._az_anti, enc._az_anti.shape[0])):
        for row in range(rows):
            unaccounted = words_to_int(bit_arr[row])
            while unaccounted:
                b = unaccounted & -unaccounted
                pos = b.bit_length() - 1
                if refs[row, pos] == 0:
                    refs[row, pos] = 1
                unaccounted ^= b
    # Gangs that were inside their assume->bind window when the
    # checkpoint was taken: the bind's outcome is unknown (the process
    # died holding it), so the all-or-nothing contract says ROLL BACK
    # every member — deterministically, via the same ledger-driven
    # release the live rollback path uses (refcounts above are already
    # rebuilt, so _release_record reverses them consistently).  The
    # members' pods are still Pending on the API server and re-arrive
    # through the informer's initial resync to re-gate.
    if settle_inflight:
        for key, entries in meta.get("gangs_inflight", {}).items():
            enc.rollback_gang_members(e[0] for e in entries)
    # Live migrations inside their evict->rebind window (optional
    # key, pre-r12 checkpoints carry none): the move's outcome is
    # unknown, so revert it whole — pop every member's TARGET commit
    # (the rebalancer pins the target before eviction completes) and
    # let the informer resync re-place the gang as a unit.  Either
    # every member re-binds (the move had already completed and the
    # members are Bound — rollback then strands nothing because
    # resync re-commits from the API server's truth) or none do;
    # never a half-moved gang (tests/test_rebalance.py chaos drill).
    if settle_inflight:
        for key, entries in meta.get("migrations_inflight", {}).items():
            enc.rollback_gang_members(e[0] for e in entries)
    # Committed realizations per shaped gang (r17, optional key).
    enc._gang_realizations = {
        key: [int(v[0]), int(v[1])]
        for key, v in meta.get("gang_realizations", {}).items()
        if isinstance(v, (list, tuple)) and len(v) >= 2}
    # Elastic reshapes inside their evict->re-pin window (r17,
    # optional key): the reshape's outcome is unknown, so settle the
    # gang WHOLE — pop every affected member's commit (targets the
    # reshape may have pinned, sources it may not have evicted yet)
    # and drop the realization record; the informer resync re-places
    # the gang as a unit at whichever shape is then feasible.  Either
    # way the restored ledger holds fully-the-old-shape or
    # fully-the-new-shape via resync — NEVER a hybrid (zero
    # half-shaped gangs, the r17 chaos drill's invariant).
    if settle_inflight:
        for key, v in meta.get("reshapes_inflight", {}).items():
            entries = v[2] if len(v) > 2 else []
            enc.rollback_gang_members(e[0] for e in entries)
            enc._gang_realizations.pop(key, None)
    # Multi-cycle provenance (r16, optional): the ledger already holds
    # only RETIRED waves (commit-at-retire), so there is nothing to
    # settle — but a checkpoint taken mid-window names its restore
    # point, and saying so out loud makes the "lands on the last
    # retired cycle" contract auditable in restore logs.
    mc = meta.get("multicycle")
    if isinstance(mc, dict) and mc.get("waves_inflight"):
        import sys

        print(f"checkpoint taken mid multicycle window "
              f"(K={mc.get('k')}, {mc.get('waves_inflight')} waves "
              f"unretired): restoring to last retired cycle "
              f"{mc.get('last_retired_cycle')}; unretired waves' pods "
              "re-arrive Pending via resync", file=sys.stderr)
    # Learned topology model: restore beside the encoder when the
    # config wants one and the checkpoint carries it.  A shape mismatch
    # (dims/rank/max_nodes changed) starts the model fresh rather than
    # failing the whole restore — the encoder state is still good.
    npz = os.path.join(path, "netmodel.npz")
    if cfg.enable_netmodel and os.path.exists(npz):
        from kubernetesnetawarescheduler_tpu.netmodel import TopologyModel

        try:
            enc.attach_netmodel(TopologyModel.load(npz, cfg))
        except ValueError as exc:
            import sys

            print(f"WARNING: netmodel checkpoint not restored: {exc}; "
                  "starting with a fresh model", file=sys.stderr)
            enc.attach_netmodel(TopologyModel(cfg))
    # Everything is freshly loaded: first snapshot() must upload all.
    for key in enc._dirty:
        enc._dirty[key] = True
    return enc


def load_policy(path: str, cfg: SchedulerConfig, seed: int = 0):
    """Restore the learned scoring policy saved beside the encoder
    state.  Returns None when the config does not want one or the
    checkpoint carries none; a shape mismatch (explain_top_k /
    max_zones / policy_ring changed) starts the policy fresh rather
    than failing — same degradation contract as the netmodel
    restore."""
    if not cfg.enable_learned_score:
        return None
    path = resolve_checkpoint_dir(path)
    npz = os.path.join(path, "policy.npz")
    if not os.path.exists(npz):
        return None
    from kubernetesnetawarescheduler_tpu.policy import ScoringPolicy

    try:
        return ScoringPolicy.load(npz, cfg, seed=seed)
    except ValueError as exc:
        import sys

        print(f"WARNING: policy checkpoint not restored: {exc}; "
              "starting with a fresh policy", file=sys.stderr)
        return ScoringPolicy(cfg, seed=seed)


def replay_decisions(encoder: Encoder, pods: Sequence,
                     cfg: SchedulerConfig,
                     method: str = "parallel") -> DecisionLog:
    """Deterministically re-run the scheduling of ``pods`` against a
    (restored) encoder state, recording decisions.  Used by tests and
    the benchmark replay harness to prove restart-determinism — the
    property the reference cannot have (its scoring depends on live
    scrapes at call time, scheduler.go:275-279)."""
    import jax.numpy as jnp

    from kubernetesnetawarescheduler_tpu.core.assign import (
        assign_greedy,
        assign_parallel,
    )
    from kubernetesnetawarescheduler_tpu.core.state import (
        commit_assignments,
    )

    assign = {"greedy": assign_greedy, "parallel": assign_parallel}[method]
    log = DecisionLog()
    state = encoder.snapshot()
    placed_node: dict[str, str] = {}

    def node_of(name: str) -> str:
        return placed_node.get(name, "")

    for i in range(0, len(pods), cfg.max_pods):
        chunk = list(pods[i:i + cfg.max_pods])
        batch = encoder.encode_pods(chunk, node_of=node_of)
        assignment = np.asarray(assign(state, batch, cfg))
        state = commit_assignments(state, batch,
                                   jnp.asarray(assignment))
        placed_pods, placed_idx = [], []
        for j, pod in enumerate(chunk):
            idx = int(assignment[j])
            node = encoder.node_name(idx) if idx >= 0 else ""
            if node:
                placed_node[pod.name] = node
                placed_pods.append(pod)
                placed_idx.append(idx)
            log.append(pod.name, node)
        # Mirror the live loop's ENCODER-side commits (bind →
        # encoder.commit): encode-time state — group member counts
        # behind the first-pod affinity waiver, selector memberships —
        # must evolve identically or the replayed decisions diverge
        # from the live log.  Device-side scoring still reads the
        # locally-threaded `state`, so this cannot double-count usage.
        if placed_pods:
            encoder.commit_many(placed_pods, placed_idx)
    return log
