"""Continuous rebalancing: a budgeted, crash-safe live-migration
descheduler.

Placement was one-shot before r12: once a pod bound, it kept its node
forever while links degraded underneath it (netmodel's residual
monitor and the ingest quarantine already *detect* that — r11's
QualityObserver even measures the resulting regret — but nothing ever
*acted*).  This module closes the loop:

- :meth:`Rebalancer.tick` runs at maintain cadence on all four loop
  paths.  It first settles in-flight moves (completion / timeout
  revert), then scans every bound pod ON DEVICE: one vmapped+jitted
  reduction computes each pod's current-placement net score against
  the best feasible alternative node, reusing
  :func:`core.score.net_desirability` (same normalization, same
  loopback pin the scorer optimized) and the winner tie-break
  contract of :func:`core.score.winner_from_scores` (lowest index of
  the max — candidate targets are bit-identical with what a fresh
  schedule of the pod would pick under the frozen snapshot).
- Candidates pass through hysteresis — minimum relative gain, minimum
  placement age (CommitRecord.stamp), per-pod move cooldown — so a
  healthy cluster stays quiet, plus trigger inputs that make a sick
  one loud: LinkDegraded/LinkQuarantined streaks (serve.py feeds the
  structured ``(src, dst, reason, streak)`` Event payload back in),
  QualityObserver outcome-ring regret over the SLO ceiling, and node
  drain (current node no longer valid) which bypasses the gain bar
  entirely.
- Execution is bounded by an explicit eviction budget
  (``rebalance_evictions_per_hour`` sliding window +
  ``rebalance_max_moves_per_cycle``) and PDB-style per-group
  disruption limits (CommitRecord.pdb_min live-member floors, the
  same accounting the preemption planner enforces).
- Every move is staged in the encoder's migration ledger
  (``note_migration_inflight``) BEFORE the first eviction and cleared
  only when every member is re-bound.  Checkpoints persist the ledger
  (``migrations_inflight`` in meta, riding the MANIFEST protocol), and
  restore rolls back every staged member — so a crash mid-move lands
  fully-moved or fully-reverted, never a half-evicted gang
  (tests/test_rebalance.py proves it with state_chaos drills).

Move mechanics (the API server cannot rebind a bound pod):
a single-pod move = stage ledger -> evict (the deletion fans through
the client's pod-deleted signal, releasing old usage exactly once,
same path as preemption) -> pin the target by committing the pod at
the new node -> re-add the cleared pod; when it re-arrives Pending,
``SchedulerLoop._redirect_committed`` redirects its bind to the
ledger's pinned node — the exact mechanism checkpoint restore already
uses.  A gang moves as a unit: all members staged, all evicted
(preempt's evict-as-a-unit reuse), all re-added; the gang path's
atomic assume-all/bind_gang/rollback seam re-places them jointly
all-or-nothing.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any

import numpy as np

from kubernetesnetawarescheduler_tpu.config import SchedulerConfig
from kubernetesnetawarescheduler_tpu.k8s.types import Pod

__all__ = ["Rebalancer"]

_EPS = 1e-9


def _round_pow2(n: int, floor: int = 8) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


def _scan(lat, bw, valid, free, chosen, peers, traffic, req,
          w_bw, w_lat):
    """Device-side improvement scan, vmapped over the bound-pod batch.

    Inputs: staging planes ``lat/bw f32[N, N]``, ``valid bool[N]``,
    ``free f32[N, R]`` (capacity - used); per-pod ``chosen i32[B]``,
    ``peers i32[B, K]`` (-1 = empty), ``traffic f32[B, K]``, ``req
    f32[B, R]``; traced scalar score weights.  Returns ``(mine f32[B],
    best f32[B], target i32[B])`` where ``target`` follows the
    winner_from_scores tie-break (-1 = no feasible node at all)."""
    import jax
    import jax.numpy as jnp

    from kubernetesnetawarescheduler_tpu.core.score import (
        NEG_INF,
        net_desirability,
        winner_from_scores,
    )

    c = net_desirability(lat, bw, valid, w_bw, w_lat)

    def one(ch, pk, tk, rq):
        m = pk >= 0
        safe = jnp.where(m, pk, 0)
        w = jnp.where(m, tk, 0.0)
        # Net score of EVERY node against this pod's peers — the same
        # reduction network_scores does per candidate at decision
        # time, under the frozen desirability matrix.
        cost = jnp.sum(c[:, safe] * w[None, :], axis=1)        # [N]
        # A candidate must be valid and fit the pod's request; the
        # CURRENT node is exempt from the fit check (its free already
        # excludes this pod's own usage).
        cols = jnp.arange(cost.shape[0], dtype=jnp.int32)
        fits = jnp.all(free >= rq[None, :], axis=1) | (cols == ch)
        scores = jnp.where(valid & fits, cost, NEG_INF)
        return cost[ch], scores

    mine, scores = jax.vmap(one)(chosen, peers, traffic, req)
    best, target = winner_from_scores(scores)
    return mine, best, target


# Module-level jit cache shared by every rebalancer (bench warmups on
# a throwaway instance warm the executable the measured one hits).
_SCAN_JIT = None


@dataclasses.dataclass
class _Move:
    """One staged live migration (single pod, or a whole gang)."""

    key: str
    gang_key: str                     # "" = single-pod move
    members: list[list]               # [uid, ns, name, from, to] each
    deadline: float                   # monotonic revert deadline
    trigger: str                      # gain | link | regret | drain
    gain: float


@dataclasses.dataclass
class _Reshape:
    """One staged elastic reshape (r17): a gang transitioning between
    declared realizations through the crash-safe reshape ledger."""

    gang_key: str
    members: list[list]               # [uid, ns, name, from, to] each
    old_count: int
    new_count: int
    declared: int                     # full declared member count
    family: tuple                     # ((count, priority), ...)
    deadline: float                   # monotonic revert deadline
    trigger: str                      # shrink | regrow | retile
    gain: float


class Rebalancer:
    """Budgeted descheduler over the encoder's committed ledger.

    Single-threaded by construction: ``tick`` runs on the maintain
    path of whichever loop variant owns the encoder, and the trigger
    feeds (``note_link_event``) only append to a lock-free dict of
    floats — worst case a racing scan reads a slightly stale trigger.
    """

    def __init__(self, cfg: SchedulerConfig, encoder, client) -> None:
        self.cfg = cfg
        self.encoder = encoder
        self.client = client
        self._seq = 0
        self._inflight: dict[str, _Move] = {}
        self._last_move: dict[str, float] = {}      # uid -> monotonic
        self._evictions: collections.deque[float] = collections.deque()
        # Trigger feeds: node name -> (monotonic stamp, reason).
        self._hot_nodes: dict[str, tuple[float, str]] = {}
        self._last_tick = 0.0
        # Counters (exact; selfmetrics/debug/bench read these).
        self.scans_total = 0
        self.candidates_total = 0
        self.moves_total = 0
        self.pods_evicted_total = 0
        self.moves_completed = 0
        self.moves_reverted = 0
        self.half_moved_gangs = 0
        self.pins_skipped = 0
        self.skipped_gain = 0
        self.skipped_age = 0
        self.skipped_cooldown = 0
        self.skipped_budget = 0
        self.skipped_disruption = 0
        self.triggers_link = 0
        self.triggers_regret = 0
        self.triggers_drain = 0
        self.last_scan_pods = 0
        self.last_scan_candidates = 0
        self.last_scan_moves = 0
        # Elastic reshaping (r17): staged reshapes keyed by gang key
        # (one gang may never be in two concurrent reshapes — the
        # encoder ledger raises, and tools/state_audit.py treats it
        # as fatal corruption) plus their counters.
        self._inflight_reshapes: dict[str, _Reshape] = {}
        self.reshapes_total = 0
        self.reshapes_completed = 0
        self.reshapes_reverted = 0
        self.half_shaped_gangs = 0
        self.reshape_shrinks = 0
        self.reshape_regrows = 0
        self.reshape_retiles = 0
        self.skipped_reshape_gain = 0
        self.skipped_reshape_budget = 0

    # -- trigger feeds ----------------------------------------------

    def note_link_event(self, src: str, dst: str, reason: str,
                        streak: int = 1) -> None:
        """Feed a LinkDegraded/LinkQuarantined Event's structured
        payload back in: pods currently placed on either endpoint get
        trigger priority (and a relaxed gain bar) at the next scan."""
        now = time.monotonic()
        for node in (src, dst):
            if node:
                self._hot_nodes[node] = (now, reason)

    def _node_hot(self, node: str, now: float) -> bool:
        entry = self._hot_nodes.get(node)
        if entry is None:
            return False
        # Trigger heat decays after two scan intervals (a link that
        # stopped degrading stops forcing moves), floored at 30s so a
        # fast-ticking deployment doesn't expire the evidence between
        # the Event arriving and the very next scan.
        ttl = max(2.0 * self.cfg.rebalance_interval_s, 30.0)
        if now - entry[0] > ttl:
            del self._hot_nodes[node]
            return False
        return True

    # -- the maintain-cadence entry point ---------------------------

    def tick(self, loop, now: float | None = None) -> int:
        """Settle in-flight moves, scan, execute.  Returns the number
        of moves EXECUTED this tick (0 on a quiet cluster)."""
        now = time.monotonic() if now is None else now
        if now - self._last_tick < self.cfg.rebalance_interval_s:
            return 0
        self._last_tick = now
        self._settle(now)
        self._settle_reshapes(now)
        moved = 0
        if self.cfg.rebalance_max_moves_per_cycle > 0:
            # Budget 0 is a complete no-op for the move scan (tests
            # pin bit-identical placements): no scan, no device work,
            # no Events.
            moved = self._scan_and_move(loop, now)
        if (getattr(self.cfg, "enable_gang_reshaping", False)
                and getattr(self.cfg, "reshape_max_per_cycle", 0) > 0):
            moved += self._reshape_pass(loop, now)
        return moved

    # -- in-flight settlement ---------------------------------------

    def _settle(self, now: float) -> None:
        """Completion / timeout pass over staged moves.  A move
        completes when every member is bound again (the gang seam
        guarantees all-or-nothing, so mixed states are transient); a
        timed-out move is reverted: unbound members' target pins are
        rolled back so the pods re-place freely, and the ledger entry
        clears either way."""
        enc, client = self.encoder, self.client
        for key, mv in list(self._inflight.items()):
            bound = []
            for uid, _ns, name, _frm, _to in mv.members:
                try:
                    bound.append(bool(client.node_of(name)))
                except KeyError:
                    bound.append(False)
            if all(bound):
                enc.clear_migration_inflight(key)
                del self._inflight[key]
                self.moves_completed += 1
                continue
            if now < mv.deadline:
                continue
            # Timeout revert.  A gang observed part-bound at its
            # deadline is exactly the half-moved state the ledger
            # exists to prevent — count it loudly (the chaos drill
            # asserts this stays 0) and roll the unbound rest back.
            if mv.gang_key and any(bound) and not all(bound):
                self.half_moved_gangs += 1
            unbound = [m[0] for m, b in zip(mv.members, bound)
                       if not b]
            enc.rollback_gang_members(unbound)
            enc.clear_migration_inflight(key)
            del self._inflight[key]
            self.moves_reverted += 1

    # -- scan --------------------------------------------------------

    def _scan_and_move(self, loop, now: float) -> int:
        enc = self.encoder
        inflight_uids = {m[0] for mv in self._inflight.values()
                         for m in mv.members}
        inflight_uids |= {m[0]
                          for rs in self._inflight_reshapes.values()
                          for m in rs.members}
        pods_all = self.client.list_all_pods() or []
        rows: list[tuple[Pod, Any, int]] = []   # (pod, rec, node_idx)
        with enc._lock:
            committed = dict(enc._committed)
        for pod in pods_all:
            if not pod.node_name or pod.uid in inflight_uids:
                continue
            rec = committed.get(pod.uid)
            if rec is None:
                continue
            idx = enc.node_slot(pod.node_name)
            if idx is None or idx != rec.node:
                continue
            rows.append((pod, rec, int(idx)))
        self.scans_total += 1
        self.last_scan_pods = len(rows)
        self.last_scan_candidates = 0
        self.last_scan_moves = 0
        if not rows:
            return 0

        with enc._lock:
            lat = np.array(enc._lat, dtype=np.float32)
            bw = np.array(enc._bw, dtype=np.float32)
            valid = np.array(enc._node_valid, dtype=bool)
            free = np.maximum(
                enc._cap - enc._used, 0.0).astype(np.float32)

        b = len(rows)
        bpad = _round_pow2(b)
        k = self.cfg.max_peers
        r = free.shape[1]
        chosen = np.zeros((bpad,), np.int32)
        peers = np.full((bpad, k), -1, np.int32)
        traffic = np.zeros((bpad, k), np.float32)
        req = np.zeros((bpad, r), np.float32)
        for i, (pod, rec, idx) in enumerate(rows):
            chosen[i] = idx
            req[i, :] = rec.req
            kk = 0
            for peer_name, weight in pod.peers.items():
                if kk >= k:
                    break
                peer_node = loop._peer_node(peer_name)
                if not peer_node:
                    continue
                pidx = enc.node_slot(peer_node)
                if pidx is None:
                    continue
                peers[i, kk] = int(pidx)
                traffic[i, kk] = float(weight)
                kk += 1

        global _SCAN_JIT
        if _SCAN_JIT is None:
            import jax

            _SCAN_JIT = jax.jit(_scan)
        import jax.numpy as jnp

        mine, best, target = (np.asarray(x) for x in _SCAN_JIT(
            jnp.asarray(lat), jnp.asarray(bw), jnp.asarray(valid),
            jnp.asarray(free), jnp.asarray(chosen),
            jnp.asarray(peers), jnp.asarray(traffic),
            jnp.asarray(req),
            jnp.float32(self.cfg.weights.peer_bw),
            jnp.float32(self.cfg.weights.peer_lat)))

        # -- hysteresis + triggers (host) ---------------------------
        cfg = self.cfg
        candidates = []        # (priority, gain, i, trigger)
        regrets = self._regret_by_uid(loop)
        for i, (pod, rec, idx) in enumerate(rows):
            tgt = int(target[i])
            gain = float(best[i] - mine[i])
            if tgt < 0 or tgt == idx or gain <= 0.0:
                continue
            trigger = ""
            if not valid[idx]:
                trigger = "drain"
            elif self._node_hot(pod.node_name, now):
                trigger = "link"
            elif regrets.get(pod.uid, 0.0) > cfg.slo_regret_ceiling:
                trigger = "regret"
            # Hysteresis discipline: an UNTRIGGERED candidate is pure
            # opportunism (healthy clusters carry structural net
            # regret — the scheduler trades the net term against
            # balance/fit, r11's quality bench measures it), so it
            # faces every gate.  A candidate with degradation
            # EVIDENCE (link event streak, regret over the SLO
            # ceiling) bypasses the gain and age bars — the trigger
            # is the justification — but still honors the per-pod
            # cooldown; only drain bypasses that too.
            # Relative gain against the score MAGNITUDE (not the
            # current score, which sits near zero for marginal
            # placements and would make any epsilon look huge).
            rel = gain / max(abs(float(best[i])),
                             abs(float(mine[i])), _EPS)
            if not trigger and rel < cfg.rebalance_min_gain:
                self.skipped_gain += 1
                continue
            age = now - rec.stamp
            if not trigger and age < cfg.rebalance_min_age_s:
                self.skipped_age += 1
                continue
            last = self._last_move.get(pod.uid)
            if (trigger != "drain" and last is not None
                    and now - last < cfg.rebalance_cooldown_s):
                self.skipped_cooldown += 1
                continue
            candidates.append((bool(trigger), gain, i,
                               trigger or "gain"))
        self.candidates_total += len(candidates)
        self.last_scan_candidates = len(candidates)
        if not candidates:
            return 0
        candidates.sort(key=lambda c: (c[0], c[1]), reverse=True)

        # -- budgets + execution ------------------------------------
        moves = 0
        group_evicted: dict[Any, int] = {}
        for triggered, gain, i, trigger in candidates:
            if moves >= cfg.rebalance_max_moves_per_cycle:
                self.skipped_budget += 1
                continue
            pod, rec, idx = rows[i]
            members = self._move_members(pod, rec)
            if members is None:
                continue
            n_evict = len(members)
            if not self._eviction_budget_ok(n_evict, now):
                self.skipped_budget += 1
                continue
            charges = self._disruption_charges(members, group_evicted,
                                               committed)
            if charges is None:
                self.skipped_disruption += 1
                continue
            ok = self._execute(loop, pod, rec, members,
                               int(target[i]), gain, trigger, now)
            if ok:
                # Charge PDB headroom only for moves that actually
                # happened — a failed _execute (raced node delete,
                # partial eviction) must not consume the group's
                # budget for later valid candidates this cycle.
                for gk, n in charges.items():
                    group_evicted[gk] = group_evicted.get(gk, 0) + n
                moves += 1
                if trigger == "link":
                    self.triggers_link += 1
                elif trigger == "regret":
                    self.triggers_regret += 1
                elif trigger == "drain":
                    self.triggers_drain += 1
        self.last_scan_moves = moves
        return moves

    def _regret_by_uid(self, loop) -> dict[str, float]:
        quality = getattr(loop, "quality", None)
        if quality is None:
            return {}
        try:
            return {o["pod_uid"]: float(o.get("regret", 0.0))
                    for o in quality.outcomes()}
        except Exception:  # noqa: BLE001 — triggers are advisory
            return {}

    # -- budget gates ------------------------------------------------

    def _eviction_budget_ok(self, n: int, now: float) -> bool:
        budget = self.cfg.rebalance_evictions_per_hour
        if budget <= 0:
            return False
        while self._evictions and now - self._evictions[0] > 3600.0:
            self._evictions.popleft()
        return len(self._evictions) + n <= budget

    def _disruption_charges(self, members: list[tuple[Pod, Any]],
                            group_evicted: dict[Any, int],
                            committed: dict[str, Any]) -> (
            dict[Any, int] | None):
        """PDB-style floor: a group with ``pdb_min`` live members may
        not drop below it, counting every eviction this cycle already
        charged against the group (same accounting the preemption
        planner's group_budget enforces).  Returns the per-group
        charges for the caller to apply AFTER the move executes (a
        failed move must not consume the group's headroom), or None
        when any group would drop below its floor."""
        charges: dict[Any, int] = {}
        for _pod, rec in members:
            gk = rec.gang_key or (rec.group_bit or None)
            if gk is None or rec.pdb_min <= 0:
                continue
            charges[gk] = charges.get(gk, 0) + 1
        for gk, n in charges.items():
            live = sum(
                1 for r in committed.values()
                if (r.gang_key or (r.group_bit or None)) == gk)
            already = group_evicted.get(gk, 0)
            pdb_min = max(r.pdb_min for _p, r in members
                          if (r.gang_key or (r.group_bit or None))
                          == gk)
            if live - already - n < pdb_min:
                return None
        return charges

    # -- move construction / execution ------------------------------

    def _move_members(self, pod: Pod, rec) -> (
            list[tuple[Pod, Any]] | None):
        """Expand a candidate to the unit that must move together: the
        pod alone, or its whole gang (evicting one slice-job member
        strands the rest — the preemption planner's rule)."""
        if not rec.gang_key:
            return [(pod, rec)]
        members = []
        for uid, mrec in self.encoder.gang_members(rec.gang_key):
            mpod = self.client.get_pod(mrec.name)
            if mpod is None or not mpod.node_name:
                return None     # gang mid-churn: not a safe unit now
            members.append((mpod, mrec))
        return members or None

    def _execute(self, loop, pod: Pod, rec,
                 members: list[tuple[Pod, Any]], target_idx: int,
                 gain: float, trigger: str, now: float) -> bool:
        """Stage -> evict -> pin -> re-add.  The ledger entry lands
        BEFORE the first eviction so every crash window restores to
        fully-reverted; it clears in ``_settle`` once every member is
        bound again."""
        from kubernetesnetawarescheduler_tpu.core.preempt import (
            Victim,
            evict_as_unit,
        )

        enc, client = self.encoder, self.client
        single = len(members) == 1 and not rec.gang_key
        try:
            to_node = enc.node_name(target_idx) if single else ""
        except Exception:  # noqa: BLE001 — slot raced a node delete
            return False
        if single and not to_node:
            return False
        self._seq += 1
        key = f"mv{self._seq}-{pod.uid[:8]}"
        entries = [[p.uid, p.namespace, p.name, p.node_name,
                    to_node if single else ""]
                   for p, _r in members]
        enc.note_migration_inflight(key, entries)
        victims = [Victim(uid=p.uid, namespace=p.namespace,
                          name=p.name, priority=r.priority,
                          node=p.node_name) for p, r in members]
        done = evict_as_unit(client, enc, victims)
        if len(done) != len(victims):
            # Partial eviction failure: the deleted members re-add
            # below and re-place freely; nothing stays pinned.  Their
            # deletions were still real disruption, so they count
            # against the sliding budget window and the eviction
            # totals — otherwise repeated partial failures would churn
            # pods invisibly and unboundedly.
            enc.clear_migration_inflight(key)
            self.moves_reverted += 1
            done_uids = {v.uid for v in done}
            for _v in done:
                self._evictions.append(now)
                self.pods_evicted_total += 1
            for p, _r in members:
                if p.uid in done_uids:
                    self._readd(client, p)
            return False
        cleared = [dataclasses.replace(p, node_name="")
                   for p, _r in members]
        if single:
            # Pin the target: the pod re-arrives Pending and
            # _redirect_committed routes its bind to this node (the
            # checkpoint-restore mechanism, reused verbatim).
            # commit_many silently skips uids that are still committed
            # (its duplicate-delivery guard), and with a watch-based
            # client the eviction's DELETED event — which releases the
            # old record — can land AFTER this point.  Only commit
            # once the old record is gone, then VERIFY the pin took;
            # a miss is counted (pins_skipped) rather than hidden, and
            # the move degrades to a bare eviction that reverts at its
            # deadline.
            if enc.committed_node(pod.uid) is None:
                enc.commit_many(cleared, [target_idx])
            if enc.committed_node(pod.uid) != to_node:
                self.pins_skipped += 1
        added = all(self._readd(client, p) for p in cleared)
        if not added:
            # No add_pod surface (live cluster): the eviction IS the
            # move — the workload controller recreates the pod (new
            # uid), the pin can never match, and the entry reverts at
            # its deadline, releasing any pinned usage.
            pass
        self._inflight[key] = _Move(
            key=key, gang_key=rec.gang_key or "", members=entries,
            deadline=now + self.cfg.rebalance_move_timeout_s,
            trigger=trigger, gain=gain)
        for p, _r in members:
            self._last_move[p.uid] = now
            # The sliding-hour window lives entirely on the monotonic
            # clock tick() runs on — mixing in time.time() here would
            # make _eviction_budget_ok's prune comparison (monotonic
            # minus epoch, hugely negative) never fire, silently
            # turning the per-hour budget into a lifetime cap.
            self._evictions.append(now)
            self.pods_evicted_total += 1
        self.moves_total += 1
        return True

    @staticmethod
    def _readd(client, pod: Pod) -> bool:
        add = getattr(client, "add_pod", None)
        if add is None:
            return False
        cleared = (pod if not pod.node_name
                   else dataclasses.replace(pod, node_name=""))
        try:
            add(cleared)
            return True
        except Exception:  # noqa: BLE001 — re-add is best-effort
            return False

    # -- elastic reshaping (r17) -------------------------------------

    def _settle_reshapes(self, now: float) -> None:
        """Completion / timeout pass over staged reshapes.  A reshape
        completes when at least ``new_count`` of the gang's members
        are bound again (the shape-aware gang path may even have
        regrown past the target when capacity returned — record the
        realization it actually committed).  At the deadline, a gang
        resting at SOME declared realization completes at that count
        (shrunk-further is still never-hybrid); a gang resting at a
        count the family never declared is the half-shaped corruption
        the drill pins at zero — counted loudly, unbound members
        rolled back."""
        enc, client = self.encoder, self.client
        for key, rs in list(self._inflight_reshapes.items()):
            bound = []
            for uid, _ns, name, _frm, _to in rs.members:
                try:
                    bound.append(bool(client.node_of(name)))
                except KeyError:
                    bound.append(False)
            n_bound = sum(bound)
            if n_bound >= rs.new_count:
                enc.clear_reshape_inflight(
                    key, committed_count=n_bound,
                    declared_count=rs.declared)
                del self._inflight_reshapes[key]
                self.reshapes_completed += 1
                continue
            if now < rs.deadline:
                continue
            family_counts = {c for c, _p in rs.family}
            if n_bound == 0:
                # Fully reverted: nothing bound; lingering commits
                # (pins the crash window left) roll back and the
                # members re-place freely via resync.
                enc.rollback_gang_members(m[0] for m in rs.members)
                enc.clear_reshape_inflight(key)
                enc.drop_gang_realization(key)
                self.reshapes_reverted += 1
            elif n_bound in family_counts:
                # Landed on a DECLARED (if unintended) realization —
                # still never-hybrid; record what actually committed.
                enc.clear_reshape_inflight(
                    key, committed_count=n_bound,
                    declared_count=rs.declared)
                self.reshapes_reverted += 1
            else:
                # Part-bound at an undeclared count at the deadline:
                # the exact half-shaped state the ledger exists to
                # prevent (the chaos drill asserts this stays 0).
                self.half_shaped_gangs += 1
                unbound = [m[0] for m, b in zip(rs.members, bound)
                           if not b]
                enc.rollback_gang_members(unbound)
                enc.clear_reshape_inflight(key)
                self.reshapes_reverted += 1
            del self._inflight_reshapes[key]

    def _gang_units(self, loop) -> dict[str, dict]:
        """Group the cluster's shaped gangs: gang key -> {"bound":
        [(pod, rec)], "pending": [pod], "family": ((count, prio),...),
        "declared": n}.  Only gangs declaring MORE than the rigid full
        shape are returned — everything else is invisible to the
        reshape pass (the bit-identical-when-undeclared property)."""
        from kubernetesnetawarescheduler_tpu.core.gang import (
            gang_key_of,
            gang_shapes_of,
        )

        enc = self.encoder
        with enc._lock:
            committed = dict(enc._committed)
        units: dict[str, dict] = {}
        pods_all = self.client.list_all_pods() or []
        by_gang: dict[str, list[Pod]] = {}
        for pod in pods_all:
            gk = gang_key_of(pod)
            if gk:
                by_gang.setdefault(gk, []).append(pod)
        for gk, pods in by_gang.items():
            pods = sorted(pods, key=lambda p: p.name)
            bound, pending = [], []
            for pod in pods:
                rec = committed.get(pod.uid)
                if pod.node_name and rec is not None:
                    bound.append((pod, rec))
                elif not pod.node_name:
                    pending.append(pod)
            if not bound:
                continue
            members = [p for p, _r in bound] + pending
            family = gang_shapes_of(members)
            if len(family) < 2:
                continue
            units[gk] = {"bound": bound, "pending": pending,
                         "family": family,
                         "declared": len(members)}
        return units

    def evaluate_reshape(self, loop, gang_key: str, unit: dict,
                         now: float) -> dict | None:
        """Score the gang's current realization against the best
        declared alternative under the FROZEN snapshot.  Returns an
        executable plan dict (new_count/assignment/gain/kind/...)
        only when the alternative STRICTLY improves realized
        desirability (:func:`core.gang.realization_key` ordering,
        with the ``reshape_min_gain`` bar on equal-weight re-tiles),
        else None.  Public so the property suite can pin the
        strictly-improves contract without executing evictions."""
        from kubernetesnetawarescheduler_tpu.core.gang import (
            place_gang_shaped,
            realization_key,
            realization_scores,
        )

        enc = self.encoder
        bound, pending = unit["bound"], unit["pending"]
        family, declared = unit["family"], unit["declared"]
        family_map = dict(family)
        members = [p for p, _r in bound] + pending
        if len(members) > loop.cfg.max_pods:
            return None

        # Current realization, measured over members on VALID nodes
        # only (a zonal outage's stranded members realize nothing).
        with enc._lock:
            valid = np.array(enc._node_valid, dtype=bool)
        cur_idx = []
        for pod, _rec in bound:
            i = enc.node_slot(pod.node_name)
            if i is not None and valid[int(i)]:
                cur_idx.append(int(i))
        cur_target = len(bound)
        cur_prio = family_map.get(
            cur_target, max(cur_target / max(declared, 1), 1e-3))

        # Fresh shape-aware placement of the WHOLE member set under
        # the frozen snapshot (same encode/assign path the gang
        # scheduler uses; members' own usage stays committed, which
        # only under-reports capacity — conservative).
        cleared = [dataclasses.replace(p, node_name="")
                   for p in members]
        batch = loop.encoder.encode_pods(
            cleared, node_of=loop._peer_node, lenient=True)
        state, static_version = loop.encoder.snapshot_versioned()
        if getattr(loop, "_assign_takes_static", False):
            static = loop._static_for(state, static_version)
            assign_fn = loop._assign
        else:
            from kubernetesnetawarescheduler_tpu.core.assign import (
                assign_greedy,
                assign_parallel,
            )

            static = None
            assign_fn = {"greedy": assign_greedy,
                         "parallel": assign_parallel}[loop.method]
        assignment, chosen, info = place_gang_shaped(
            state, batch, loop.cfg, static, assign_fn, len(members),
            family)
        if chosen <= 0:
            return None

        # One padded/vmapped dispatch scores BOTH realizations on the
        # same frozen scale.
        mmax = max(len(cur_idx), chosen, 1)
        nodes = np.full((2, mmax), -1, np.int32)
        vmask = np.zeros((2, mmax), bool)
        nodes[0, :len(cur_idx)] = cur_idx
        vmask[0, :len(cur_idx)] = True
        nodes[1, :chosen] = assignment[:chosen]
        vmask[1, :chosen] = True
        scores = realization_scores(state, nodes, vmask, loop.cfg)
        cur_key = realization_key(cur_target, len(cur_idx), cur_prio,
                                  float(scores[0]))
        new_prio = family_map.get(chosen, 1.0)
        new_key = realization_key(chosen, chosen, new_prio,
                                  float(scores[1]))
        if not new_key > cur_key:
            return None
        if new_key[:2] == cur_key[:2]:
            # Same feasibility and priority-weighted width: a pure
            # re-tile must clear the relative gain bar, or a healthy
            # gang would churn on score noise.
            rel = (new_key[2] - cur_key[2]) / max(
                abs(new_key[2]), abs(cur_key[2]), _EPS)
            if rel < getattr(self.cfg, "reshape_min_gain", 0.0):
                self.skipped_reshape_gain += 1
                return None
        kind = ("shrink" if chosen < cur_target
                else "regrow" if chosen > cur_target else "retile")
        return {"gang_key": gang_key, "new_count": chosen,
                "old_count": cur_target, "declared": declared,
                "family": family, "kind": kind,
                "gain": float(new_key[2] - cur_key[2]),
                "cur_key": cur_key, "new_key": new_key}

    def _reshape_pass(self, loop, now: float) -> int:
        """Find degraded shaped gangs and reshape the best candidates
        under the shared eviction budget.  A gang is CONSIDERED when
        it shows degradation evidence (a member node invalid or hot)
        or sits below its declared full shape (regrow opportunity);
        healthy full-shape gangs are only ever re-tiled over the
        reshape_min_gain bar."""
        inflight_uids = {m[0] for mv in self._inflight.values()
                         for m in mv.members}
        executed = 0
        evaluated = 0
        for gk, unit in sorted(self._gang_units(loop).items()):
            if executed >= self.cfg.reshape_max_per_cycle:
                break
            if gk in self._inflight_reshapes:
                continue
            if any(p.uid in inflight_uids for p, _r in unit["bound"]):
                continue
            last = self._last_move.get(gk)
            if (last is not None
                    and now - last < self.cfg.rebalance_cooldown_s):
                continue
            degraded = False
            with self.encoder._lock:
                valid = np.array(self.encoder._node_valid, dtype=bool)
            for pod, _rec in unit["bound"]:
                i = self.encoder.node_slot(pod.node_name)
                if (i is None or not valid[int(i)]
                        or self._node_hot(pod.node_name, now)):
                    degraded = True
                    break
            below_full = len(unit["bound"]) < unit["declared"]
            if not degraded and not below_full:
                continue
            if evaluated >= max(8, 2 * self.cfg.reshape_max_per_cycle):
                break
            evaluated += 1
            plan = self.evaluate_reshape(loop, gk, unit, now)
            if plan is None:
                continue
            n_evict = len(unit["bound"])
            if not self._eviction_budget_ok(n_evict, now):
                self.skipped_reshape_budget += 1
                continue
            if self._execute_reshape(loop, unit, plan, now):
                executed += 1
        return executed

    def _execute_reshape(self, loop, unit: dict, plan: dict,
                         now: float) -> bool:
        """Stage the reshape ledger -> evict every bound member ->
        re-add -> wake parked surplus.  The ledger entry lands BEFORE
        the first eviction, so every crash window restores to
        fully-the-old-shape; the shape-aware gang path re-places the
        re-gated members jointly all-or-nothing at the best feasible
        realization, and ``_settle_reshapes`` records what committed."""
        from kubernetesnetawarescheduler_tpu.core.preempt import (
            Victim,
            evict_as_unit,
        )

        enc, client = self.encoder, self.client
        gk = plan["gang_key"]
        bound = unit["bound"]
        entries = [[p.uid, p.namespace, p.name, p.node_name, ""]
                   for p, _r in bound]
        try:
            enc.note_reshape_inflight(gk, plan["old_count"],
                                      plan["new_count"], entries)
        except ValueError:
            return False        # raced into a concurrent reshape
        victims = [Victim(uid=p.uid, namespace=p.namespace,
                          name=p.name, priority=r.priority,
                          node=p.node_name) for p, r in bound]
        done = evict_as_unit(client, enc, victims)
        if len(done) != len(victims):
            # Partial eviction: revert the staging, re-add what was
            # evicted (they re-place freely), and charge the real
            # disruption against the budget window.
            enc.clear_reshape_inflight(gk)
            self.reshapes_reverted += 1
            done_uids = {v.uid for v in done}
            for _v in done:
                self._evictions.append(now)
                self.pods_evicted_total += 1
            for p, _r in bound:
                if p.uid in done_uids:
                    self._readd(client, p)
            return False
        for p, _r in bound:
            self._readd(client,
                        dataclasses.replace(p, node_name=""))
            self._evictions.append(now)
            self.pods_evicted_total += 1
            self._last_move[p.uid] = now
        # Wake parked surplus members (a regrow needs them to re-gate
        # alongside the evicted members so the gang completes at the
        # larger shape).
        requeue = getattr(loop, "_requeue_parked", None)
        if requeue is not None:
            requeue()
        self._last_move[gk] = now
        self._inflight_reshapes[gk] = _Reshape(
            gang_key=gk, members=entries,
            old_count=plan["old_count"],
            new_count=plan["new_count"],
            declared=plan["declared"], family=plan["family"],
            deadline=now + self.cfg.rebalance_move_timeout_s,
            trigger=plan["kind"], gain=plan["gain"])
        self.reshapes_total += 1
        if plan["kind"] == "shrink":
            self.reshape_shrinks += 1
        elif plan["kind"] == "regrow":
            self.reshape_regrows += 1
        else:
            self.reshape_retiles += 1
        return True

    # -- reads -------------------------------------------------------

    def disruption_per_pod_hour(self, n_pods: int) -> float:
        """Evictions per pod per hour over the sliding window — the
        number the bench reports beside recovered bandwidth and
        bench_check Rule 12 compares against the budget.  Prunes with
        the same monotonic clock the window's stamps use."""
        now = time.monotonic()
        while self._evictions and now - self._evictions[0] > 3600.0:
            self._evictions.popleft()
        return len(self._evictions) / max(1, n_pods)

    def summary(self) -> dict[str, Any]:
        """One-shot stats block for /debug/rebalance, /metrics and
        bench artifacts."""
        return {
            "enabled": True,
            "scans_total": self.scans_total,
            "candidates_total": self.candidates_total,
            "moves_total": self.moves_total,
            "moves_completed": self.moves_completed,
            "moves_reverted": self.moves_reverted,
            "moves_inflight": len(self._inflight),
            "pods_evicted_total": self.pods_evicted_total,
            "half_moved_gangs": self.half_moved_gangs,
            "pins_skipped": self.pins_skipped,
            "skipped_gain": self.skipped_gain,
            "skipped_age": self.skipped_age,
            "skipped_cooldown": self.skipped_cooldown,
            "skipped_budget": self.skipped_budget,
            "skipped_disruption": self.skipped_disruption,
            "triggers_link": self.triggers_link,
            "triggers_regret": self.triggers_regret,
            "triggers_drain": self.triggers_drain,
            "last_scan_pods": self.last_scan_pods,
            "last_scan_candidates": self.last_scan_candidates,
            "last_scan_moves": self.last_scan_moves,
            "evictions_window": len(self._evictions),
            "budget_per_hour":
                self.cfg.rebalance_evictions_per_hour,
            # Elastic reshaping (r17) sub-block: bench artifacts embed
            # this as detail.reshape and bench_check Rule 17 pins
            # half_shaped_gangs == 0 wherever it appears.
            "reshape": {
                "enabled": bool(getattr(self.cfg,
                                        "enable_gang_reshaping",
                                        False)),
                "reshapes_total": self.reshapes_total,
                "reshapes_completed": self.reshapes_completed,
                "reshapes_reverted": self.reshapes_reverted,
                "reshapes_inflight": len(self._inflight_reshapes),
                "half_shaped_gangs": self.half_shaped_gangs,
                "shrinks": self.reshape_shrinks,
                "regrows": self.reshape_regrows,
                "retiles": self.reshape_retiles,
                "skipped_gain": self.skipped_reshape_gain,
                "skipped_budget": self.skipped_reshape_budget,
            },
        }
