"""Priority preemption: make room for a pod no feasible node can hold.

The reference has no notion of priority or preemption (its scoring
ignores the pod entirely, scheduler/scheduler.go:248); stock
kube-scheduler's preemption is the capability users expect from a
scheduler at this position, so the framework provides the same shape:
when a pod is unschedulable, find the node where evicting the
cheapest set of strictly-lower-priority pods frees enough capacity,
evict them, and requeue the pod.

The planner is host-side and ledger-driven: the usage ledger
(:class:`~.encode.CommitRecord`) already knows, per bound pod, its
node, request vector and priority — exactly the victim-candidate
table.  Node-level static feasibility (taints/selector/validity) is
checked against the encoder's host mirrors, mirroring the device
kernel's mask (core/score.py feasibility_mask) so a plan is never made
for a node the scorer would reject anyway.

Semantics notes (documented deltas vs kube-scheduler):
- victims are chosen lowest-priority-first until the pod fits; the
  node is chosen to minimize (highest victim priority, victim count) —
  kube-scheduler's primary tie-breakers;
- PodDisruptionBudgets, graceful-termination waiting and nominated
  nodes are out of scope for now: eviction is a plain pod delete and
  the preemptor is requeued to be scored on a later cycle (after the
  deletion's release lands in the ledger).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from kubernetesnetawarescheduler_tpu.core.encode import (
    Encoder,
    _requests_vector,
)
from kubernetesnetawarescheduler_tpu.k8s.types import Pod


@dataclasses.dataclass(frozen=True)
class Victim:
    uid: str
    namespace: str
    name: str
    priority: float
    node: str


@dataclasses.dataclass(frozen=True)
class PreemptionPlan:
    pod_name: str
    node_name: str
    victims: tuple[Victim, ...]


def plan_preemption(encoder: Encoder, pod: Pod) -> PreemptionPlan | None:
    """Find the cheapest eviction set that makes ``pod`` fit somewhere.

    Returns None when no node can host the pod even after evicting
    every strictly-lower-priority pod (the scoring kernel's own
    verdict of "unschedulable" then stands).
    """
    cfg = encoder.cfg
    req = _requests_vector(pod.requests, cfg.num_resources)
    prio = float(pod.priority)

    with encoder._lock:
        n_real = len(encoder._node_names)
        if n_real == 0:
            return None
        valid = encoder._node_valid[:n_real].copy()
        cap = encoder._cap[:n_real].copy()
        used = encoder._used[:n_real].copy()
        taints = encoder._taint_bits[:n_real].copy()
        labels = encoder._label_bits[:n_real].copy()
        tol = np.uint32(encoder.taints.mask(pod.tolerations, lenient=True))
        sel = np.uint32(encoder.labels.mask(pod.node_selector,
                                            lenient=True))
        # Victim candidates per node: strictly lower priority only.
        victims_by_node: dict[int, list] = {}
        for uid, rec in encoder._committed.items():
            if rec.priority < prio and rec.node < n_real:
                victims_by_node.setdefault(rec.node, []).append((uid, rec))
        node_names = list(encoder._node_names)

    static_ok = (valid
                 & ((taints & ~tol) == 0)
                 & ((labels & sel) == sel))

    best: tuple[float, int, int] | None = None  # (max_vprio, count, node)
    best_set: list[Victim] = []
    for node in range(n_real):
        if not static_ok[node]:
            continue
        cands = victims_by_node.get(node, [])
        free = cap[node] - used[node]
        if np.all(req <= free + 1e-9):
            # Statically fits with free capacity, yet the kernel said
            # unschedulable — the block is something eviction cannot
            # lift (affinity masks, in-batch contention).  Skip.
            continue
        evictable = free + sum((rec.req for _, rec in cands),
                               np.zeros_like(free))
        if not np.all(req <= evictable + 1e-9):
            continue
        # Lowest-priority-first until the pod fits.
        cands = sorted(cands, key=lambda e: (e[1].priority, e[1].stamp))
        acc = free.copy()
        chosen: list[Victim] = []
        for uid, rec in cands:
            if np.all(req <= acc + 1e-9):
                break
            acc = acc + rec.req
            chosen.append(Victim(uid, rec.namespace, rec.name,
                                 rec.priority, node_names[node]))
        if not np.all(req <= acc + 1e-9):
            continue
        key = (max((v.priority for v in chosen), default=-np.inf),
               len(chosen), node)
        if best is None or key < best:
            best = key
            best_set = chosen
    if best is None:
        return None
    return PreemptionPlan(pod.name, node_names[best[2]],
                          tuple(best_set))


def execute_preemption(client, encoder: Encoder,
                       plan: PreemptionPlan) -> Sequence[Victim]:
    """Delete the plan's victims through the API server.

    Usage release is NOT done here: the deletion fans out through the
    client's pod-deleted signal (watch DELETED / FakeCluster handler),
    which routes into the ledger exactly once — the same path every
    other deletion takes.  Returns the victims actually deleted."""
    done = []
    for v in plan.victims:
        try:
            client.delete_pod(v.name, namespace=v.namespace)
            done.append(v)
        except Exception:  # noqa: BLE001 — best-effort per victim
            continue
    return done
