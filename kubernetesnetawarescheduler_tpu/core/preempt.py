"""Priority preemption: make room for a pod no feasible node can hold.

The reference has no notion of priority or preemption (its scoring
ignores the pod entirely, scheduler/scheduler.go:248); stock
kube-scheduler's preemption is the capability users expect from a
scheduler at this position, so the framework provides the same shape:
when a pod is unschedulable, find the node where evicting the
cheapest set of strictly-lower-priority pods frees enough capacity,
evict them, and requeue the pod.

The planner is host-side and ledger-driven: the usage ledger
(:class:`~.encode.CommitRecord`) already knows, per bound pod, its
node, request vector and priority — exactly the victim-candidate
table.  Node-level static feasibility (taints/selector/validity) is
checked against the encoder's host mirrors, mirroring the device
kernel's mask (core/score.py feasibility_mask) so a plan is never made
for a node the scorer would reject anyway.

Semantics notes (documented deltas vs kube-scheduler):
- victims are chosen lowest-priority-first until the pod fits; the
  node is chosen to minimize (highest victim priority, victim count) —
  kube-scheduler's primary tie-breakers;
- PodDisruptionBudgets come from TWO surfaces, strictest wins: real
  ``policy/v1`` PDB objects (watched from the API server, selectors
  canonicalized to label-driven selector-groups — Encoder.set_pdb)
  and the annotation (``netaware.io/pdb-min-available`` on the
  members of a ``group``).  The planner never disrupts a protected
  group below its bound, a pod matching several protected selectors
  consumes each one's budget, and a groupless pod with the annotation
  is outright unevictable.  Percentage bounds resolve against live
  member counts (kube uses the controller's expected scale — a
  documented delta);
- gangs (core/gang.py) are evicted all-or-nothing, mirroring how they
  are placed: a gang with any member at >= the preemptor's priority
  contributes NO victim candidates, and choosing any member of an
  evictable gang expands the plan to every live co-member (on any
  node) so no partially-placed gang survives a preemption;
- eviction is graceful (``cfg.preemption_grace_s`` becomes
  DeleteOptions.gracePeriodSeconds) and the preemptor is requeued only
  after every victim's deletion is CONFIRMED through the watch (or
  ``cfg.preemption_wait_s`` expires), holding a capacity reservation
  on the target node in the interim (nominatedNodeName semantics —
  Encoder.nominate) so the freed space is not stolen.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import numpy as np

from kubernetesnetawarescheduler_tpu.core.encode import (
    Encoder,
    _requests_vector,
    int_to_words,
)
from kubernetesnetawarescheduler_tpu.k8s.types import Pod


@dataclasses.dataclass(frozen=True)
class Victim:
    uid: str
    namespace: str
    name: str
    priority: float
    node: str


@dataclasses.dataclass(frozen=True)
class PreemptionPlan:
    pod_name: str
    node_name: str
    victims: tuple[Victim, ...]


def _refs_after(refs_row: np.ndarray, evicted_bits: list[int]) -> int:
    """Resident bit set remaining once the given members leave: bits
    whose refcount survives the subtraction.  Phantom refs (checkpoint
    restores without ledger members) keep their bit — conservative,
    matching Encoder._release_record semantics."""
    counts = refs_row.astype(np.int64).copy()
    for bits in evicted_bits:
        while bits:
            b = bits & -bits
            pos = b.bit_length() - 1
            if counts[pos] > 0:
                counts[pos] -= 1
            bits ^= b
    out = 0
    for pos in np.nonzero(counts > 0)[0]:
        out |= 1 << int(pos)
    return out


def _ns_ok_nodes(labels: np.ndarray, ns_any: np.ndarray,
                 ns_forb: np.ndarray, ns_used: np.ndarray,
                 node_numeric: np.ndarray | None = None,
                 ns_ncol: np.ndarray | None = None,
                 ns_nlo: np.ndarray | None = None,
                 ns_nhi: np.ndarray | None = None) -> np.ndarray:
    """Host mirror of the kernel's hard-nodeAffinity mask
    (score.ns_affinity_ok), ``bool[N]`` over label-bit rows — same
    bit rows the device sees, so the plan can never target a node the
    scoring kernel still rejects on matchExpressions (numeric Gt/Lt
    included; NaN fails, like the kernel)."""
    if not ns_used.any():
        return np.ones(labels.shape[0], bool)
    expr_unused = (ns_any == 0).all(axis=-1)                   # [T2, E]
    hit = ((labels[:, None, None, :] & ns_any[None]) != 0).any(axis=-1)
    expr_ok = expr_unused[None] | hit                          # [N, T2, E]
    clean = ((labels[:, None, :] & ns_forb[None]) == 0).all(axis=-1)
    term_ok = expr_ok.all(axis=2) & clean & ns_used[None]      # [N, T2]
    if ns_ncol is not None and (ns_ncol >= 0).any():
        vals = node_numeric[:, np.clip(ns_ncol, 0, None)]  # [N, T2, NE]
        with np.errstate(invalid="ignore"):
            in_range = (vals > ns_nlo[None]) & (vals < ns_nhi[None])
        term_ok &= ((ns_ncol[None] < 0) | in_range).all(axis=2)
    return term_ok.any(axis=1)


def plan_preemption(encoder: Encoder, pod: Pod) -> PreemptionPlan | None:
    """Find the cheapest eviction set that makes ``pod`` fit somewhere.

    Mirrors the scoring kernel's FULL feasibility mask (not just
    capacity): a plan is only made for a node where, after the chosen
    victims leave, taints/selector/affinity/anti-affinity (both
    directions) all pass — so real workloads are never evicted from a
    node the kernel would still reject (the round-1 advisor finding).
    Anti-affinity conflicts make their resident pods *mandatory*
    victims; an un-internable selector keeps the node infeasible
    (UNKNOWN sentinel, same as the kernel's lenient encode).

    Returns None when no node can host the pod even after evicting
    every strictly-lower-priority pod (the scoring kernel's own
    verdict of "unschedulable" then stands).
    """
    cfg = encoder.cfg
    w = cfg.mask_words
    req = _requests_vector(pod.requests, cfg.num_resources)
    prio = float(pod.priority)

    with encoder._lock:
        n_real = len(encoder._node_names)
        if n_real == 0:
            return None
        valid = encoder._node_valid[:n_real].copy()
        cap = encoder._cap[:n_real].copy()
        # Reservations count as used (the scoring snapshot does the
        # same): a second preemptor must not plan onto capacity an
        # earlier preemptor's nomination already holds.
        used = (encoder._used[:n_real] + encoder._reserved[:n_real])
        group_refs = encoder._group_refs[:n_real].copy()
        anti_refs = encoder._anti_refs[:n_real].copy()
        terminating = set(encoder._terminating)
        # Same interning (and overflow directions) as the kernel's
        # lenient encode — _constraint_bits is the single source of
        # truth; it also backfills lazily-interned selector labels,
        # so the label/taint snapshots are taken AFTER it runs.
        tol_i, sel_i, aff_i, anti_i, gbit_i = \
            encoder._constraint_bits(pod, lenient=True)
        # Hard nodeAffinity matchExpressions: encoded through the SAME
        # _ns_rows the kernel encode uses (interning + lazy backfill),
        # so the label snapshot below already carries any bits this
        # pod's terms just interned.
        ns_any = np.zeros((cfg.max_ns_terms, cfg.max_ns_exprs, w),
                          np.uint32)
        ns_forb = np.zeros((cfg.max_ns_terms, w), np.uint32)
        ns_used = np.zeros((cfg.max_ns_terms,), bool)
        ns_ncol = np.full((cfg.max_ns_terms, cfg.max_ns_num), -1,
                          np.int32)
        ns_nlo = np.full((cfg.max_ns_terms, cfg.max_ns_num), -np.inf,
                         np.float32)
        ns_nhi = np.full((cfg.max_ns_terms, cfg.max_ns_num), np.inf,
                         np.float32)
        encoder._ns_rows(pod, ns_any, ns_forb, ns_used, ns_ncol,
                         ns_nlo, ns_nhi, lenient=True, record=False)
        zaff_i, zanti_i = encoder._zone_bits(pod, lenient=True,
                                             record=False)
        gz_full = encoder._gz_counts.copy()
        az_refs = encoder._az_anti_refs.copy()
        taints = encoder._taint_bits[:n_real].copy()
        labels = encoder._label_bits[:n_real].copy()
        ns_ok = _ns_ok_nodes(labels, ns_any, ns_forb, ns_used,
                             encoder._node_numeric[:n_real],
                             ns_ncol, ns_nlo, ns_nhi)
        # Topology spread (hard mode only — soft never blocks): the
        # preemptor's zone-count row and the zone map, so a plan is
        # never made for a node the spread filter would still mask
        # after the victims leave.
        node_zone = encoder._node_zone[:n_real].copy()
        gslot = gbit_i.bit_length() - 1 if gbit_i else -1
        spread_skew = int(getattr(pod, "spread_maxskew", 0))
        spread_gate = (spread_skew > 0 and gslot >= 0
                       and bool(getattr(pod, "spread_hard", True)))
        counts0 = (encoder._gz_counts[gslot].copy() if spread_gate
                   else None)
        # Eligible domains for the spread min (Honor policy, matching
        # score.spread_terms): zones holding >= 1 valid node that
        # passes the POD's taints/selector — loop-invariant, computed
        # once (not per candidate node).
        elig_zones: list[int] = []
        if spread_gate:
            tol_w = int_to_words(tol_i, w)
            sel_w = int_to_words(sel_i, w)
            tol_ok = ((taints & ~tol_w) == 0).all(axis=1)
            sel_ok = ((labels & sel_w) == sel_w).all(axis=1)
            elig_nodes = (valid & tol_ok & sel_ok & ns_ok
                          & (node_zone >= 0))
            elig_zones = sorted({int(z) for z in node_zone[elig_nodes]})
        # Victim candidates per node: strictly lower priority only.
        # Disruption accounting is per group bit-SLOT over FULL
        # membership masks (a pod matching two protected selectors
        # consumes both budgets, kube semantics).  Two protection
        # surfaces merge, strictest wins: the annotation
        # (``netaware.io/pdb-min-available`` on members of a group)
        # and REAL policy/v1 PodDisruptionBudget objects
        # (Encoder.set_pdb — selector-group member counting).  A
        # groupless pod with the annotation is simply not a candidate
        # (it protects itself).
        # Gang all-or-nothing holds for eviction too (core/gang.py): a
        # bound gang is evictable only as a UNIT.  Pre-pass: collect
        # live members per gang key and decide evictability — every
        # member must be strictly lower priority than the preemptor
        # and not self-protecting, else evicting any subset would
        # leave a partially-placed gang, the exact state gang
        # scheduling exists to prevent.  Members of a non-evictable
        # gang are simply not victim candidates.
        gang_members_all: dict[str, list[tuple[str, object]]] = {}
        for uid, rec in encoder._committed.items():
            if rec.gang_key and uid not in terminating:
                gang_members_all.setdefault(rec.gang_key, []).append(
                    (uid, rec))
        gang_evictable = {
            key: all(r.priority < prio
                     and not (r.pdb_min and not r.group_bit)
                     for _, r in mem)
            for key, mem in gang_members_all.items()}
        victims_by_node: dict[int, list] = {}
        members_by_slot: dict[int, int] = {}
        ann_min_by_slot: dict[int, int] = {}
        for uid, rec in encoder._committed.items():
            if uid in terminating:
                # Graceful deletion in flight: not live for PDB
                # accounting, not evictable again (re-deleting a
                # terminating pod frees nothing).
                continue
            m = rec.member_bits or rec.group_bit
            while m:
                b = m & -m
                m ^= b
                s = b.bit_length() - 1
                members_by_slot[s] = members_by_slot.get(s, 0) + 1
            if rec.pdb_min and rec.group_bit:
                s = rec.group_bit.bit_length() - 1
                ann_min_by_slot[s] = max(ann_min_by_slot.get(s, 0),
                                         rec.pdb_min)
            if rec.priority < prio and rec.node < n_real:
                if rec.pdb_min and not rec.group_bit:
                    continue  # self-protecting singleton
                if rec.gang_key and not gang_evictable.get(
                        rec.gang_key, True):
                    continue  # gang holds a non-evictable member
                victims_by_node.setdefault(rec.node, []).append((uid, rec))
        # Allowed disruptions per protected slot (never negative: an
        # already-underprovisioned group cannot be disrupted at all).
        # Percentages resolve against live members — ceil for
        # minAvailable, floor for maxUnavailable, both conservative.
        group_budget: dict[int, int] = {}

        def _bound(slot: int, allowed: float) -> None:
            allowed = max(int(allowed), 0)
            group_budget[slot] = min(
                group_budget.get(slot, allowed), allowed)

        for s, mn in ann_min_by_slot.items():
            _bound(s, members_by_slot.get(s, 0) - mn)
        for pdb in encoder._pdbs.values():
            if not pdb.selector_key:
                continue
            bit = encoder.groups.bit(pdb.selector_key, lenient=True)
            if not bit:
                # Interner exhausted: bound untrackable, the PDB
                # degrades OPEN.  Not silent — Encoder.set_pdb already
                # emitted a ConstraintDegraded event naming this PDB
                # when the registration failed (ADVICE r3 low #2).
                continue
            s = bit.bit_length() - 1
            members = members_by_slot.get(s, 0)
            if pdb.min_available is not None:
                _bound(s, members - int(pdb.min_available))
            if pdb.min_available_pct is not None:
                _bound(s, members - math.ceil(
                    members * pdb.min_available_pct / 100.0))
            if pdb.max_unavailable is not None:
                _bound(s, int(pdb.max_unavailable))
            if pdb.max_unavailable_pct is not None:
                _bound(s, math.floor(
                    members * pdb.max_unavailable_pct / 100.0))
        node_names = list(encoder._node_names)

    tol_w = int_to_words(tol_i, w)
    sel_w = int_to_words(sel_i, w)
    static_ok = (valid
                 & np.all((taints & ~tol_w) == 0, axis=-1)
                 & np.all((labels & sel_w) == sel_w, axis=-1)
                 & ns_ok)

    best: tuple[float, int, int] | None = None  # (max_vprio, count, node)
    best_set: list[Victim] = []
    best_gangs: list[str] = []
    for node in range(n_real):
        if not static_ok[node]:
            continue
        cands = victims_by_node.get(node, [])
        free = cap[node] - used[node]

        # Per-plan PDB budget: evicting a pod consumes one allowed
        # disruption of EVERY protected group it is a member of.
        budget = dict(group_budget)

        def _prot_slots(rec) -> list[int]:
            m = rec.member_bits or rec.group_bit
            out = []
            while m:
                b = m & -m
                m ^= b
                s = b.bit_length() - 1
                if s in budget:
                    out.append(s)
            return out

        def takeable(rec) -> bool:
            return all(budget[s] > 0 for s in _prot_slots(rec))

        def take(rec) -> None:
            for s in _prot_slots(rec):
                budget[s] -= 1

        # Mandatory victims: residents whose group conflicts with the
        # pod's anti-affinity, or who declared anti-affinity against
        # the pod's group (the symmetric direction) — at host scope
        # AND zone scope (a zone-conflicting resident ON THIS NODE is
        # evictable; only cross-node zone residents force the skip in
        # the zone post-check below).  Only committed (ledgered,
        # strictly-lower-priority) pods are evictable; a PDB-protected
        # mandatory victim makes the node infeasible.
        mandatory: list[tuple[str, object]] = []
        # Zone terms only bind on zoned nodes (a zone-less node is its
        # own empty domain — the kernel enforces nothing there, so
        # evicting for a zone conflict would be a wasted eviction).
        zanti_here = zanti_i if node_zone[node] >= 0 else 0
        if anti_i or gbit_i or zanti_here:
            mandatory = [
                (uid, rec) for uid, rec in cands
                if (rec.group_bit & (anti_i | zanti_here))
                or ((rec.anti_bits
                     | (rec.zanti_bits if node_zone[node] >= 0 else 0))
                    & gbit_i)]
        ok_budget = True
        for _, rec in mandatory:
            if not takeable(rec):
                ok_budget = False
                break
            take(rec)
        if not ok_budget:
            continue

        chosen_recs = list(mandatory)
        chosen_uids = {uid for uid, _ in chosen_recs}

        # After the mandatory set leaves, do the conflict bits clear?
        # (A higher-priority member or a phantom ref keeps the bit —
        # node infeasible.)
        rem_group = _refs_after(
            group_refs[node], [rec.group_bit for _, rec in chosen_recs])
        rem_anti = _refs_after(
            anti_refs[node], [rec.anti_bits for _, rec in chosen_recs])
        if (rem_group & anti_i) or (rem_anti & gbit_i):
            continue

        # Capacity: free + chosen victims' requests; extend
        # lowest-priority-first until the pod fits.
        acc = free + sum((rec.req for _, rec in chosen_recs),
                         np.zeros_like(free))
        if not np.all(req <= acc + 1e-9):
            extras = sorted(
                (e for e in cands if e[0] not in chosen_uids),
                key=lambda e: (e[1].priority, e[1].stamp))
            for uid, rec in extras:
                if np.all(req <= acc + 1e-9):
                    break
                if not takeable(rec):
                    continue  # PDB budget exhausted for its group
                take(rec)
                acc = acc + rec.req
                chosen_recs.append((uid, rec))
                chosen_uids.add(uid)
            if not np.all(req <= acc + 1e-9):
                continue
        elif not chosen_recs:
            # Statically fits with free capacity and no conflicting
            # residents, yet the kernel said unschedulable — the block
            # is something eviction cannot lift (unsatisfied affinity,
            # in-batch contention).  Skip.
            continue

        # Required pod affinity must still hold after ALL evictions
        # (capacity victims may carry the last member of a required
        # group off the node).
        if aff_i:
            rem_group = _refs_after(
                group_refs[node],
                [rec.group_bit for _, rec in chosen_recs])
            if not (rem_group & aff_i):
                continue

        # Zone-scoped (anti-)affinity, CONSERVATIVE: victims are only
        # ever chosen on the candidate node, so a zone conflict held
        # up by residents on OTHER nodes of the zone makes the node
        # infeasible (no cross-node victim hunting).  Checks mirror
        # score.zone_affinity_ok, evaluated on post-eviction counts.
        if zaff_i or zanti_i or gbit_i:
            z = int(node_zone[node])
            if z < 0:
                if zaff_i:
                    continue  # empty domain: required zaff unsatisfiable
            else:
                def _cnt_after(slot: int) -> int:
                    c = int(gz_full[slot, z])
                    c -= sum(1 for _, rec in chosen_recs
                             if rec.group_slot == slot and rec.zone == z)
                    return max(0, c)

                def _slots(bits: int):
                    while bits:
                        b = bits & -bits
                        yield b.bit_length() - 1
                        bits ^= b

                if zaff_i and not any(_cnt_after(s) > 0
                                      for s in _slots(zaff_i)):
                    continue
                if zanti_i and any(_cnt_after(s) > 0
                                   for s in _slots(zanti_i)):
                    continue
                if gbit_i:
                    rem_az = _refs_after(
                        az_refs[z],
                        [rec.zanti_bits for _, rec in chosen_recs
                         if rec.zone == z])
                    if rem_az & gbit_i:
                        continue

        # Hard topology spread must pass AFTER the chosen set leaves
        # (victims of the preemptor's own group lower their recorded
        # zone's count); otherwise the eviction would be wasted on a
        # node the spread filter still masks.  Unknown-zone nodes
        # degrade open, matching score.spread_terms.
        if spread_gate and node_zone[node] >= 0:
            counts = counts0.copy()
            for _, rec in chosen_recs:
                if rec.group_slot == gslot and rec.zone >= 0:
                    counts[rec.zone] = max(0, counts[rec.zone] - 1)
            min_c = (min(int(counts[z]) for z in elig_zones)
                     if elig_zones else 0)
            if int(counts[node_zone[node]]) + 1 - min_c > spread_skew:
                continue

        chosen = [Victim(uid, rec.namespace, rec.name, rec.priority,
                         node_names[node]) for uid, rec in chosen_recs]
        key = (max((v.priority for v in chosen), default=-np.inf),
               len(chosen), node)
        if best is None or key < best:
            best = key
            best_set = chosen
            best_gangs = sorted({rec.gang_key for _, rec in chosen_recs
                                 if rec.gang_key})
    if best is None:
        return None
    # Preempting one gang member releases the WHOLE gang: expand the
    # winning set with every live co-member (wherever it is bound) so
    # the survivors don't linger as a partially-placed gang burning
    # capacity without their peers.  Co-members re-arrive through the
    # informer and re-gate as a fresh gang.  The plan key above counts
    # only node-local victims — a documented approximation: gang
    # expansion is a consequence of the choice, not a cost the
    # node-ranking trades off.
    victims = list(best_set)
    have = {v.uid for v in victims}
    for gkey in best_gangs:
        for uid, rec in gang_members_all.get(gkey, []):
            if uid not in have and rec.node < n_real:
                have.add(uid)
                victims.append(Victim(uid, rec.namespace, rec.name,
                                      rec.priority,
                                      node_names[rec.node]))
    return PreemptionPlan(pod.name, node_names[best[2]],
                          tuple(victims))


def execute_preemption(client, encoder: Encoder,
                       plan: PreemptionPlan,
                       grace_seconds: int | None = None
                       ) -> Sequence[Victim]:
    """Delete the plan's victims through the API server (graceful:
    ``grace_seconds`` becomes DeleteOptions.gracePeriodSeconds).

    Usage release is NOT done here: the deletion fans out through the
    client's pod-deleted signal (watch DELETED / FakeCluster handler),
    which routes into the ledger exactly once — the same path every
    other deletion takes.  The loop holds the preemptor until those
    confirmations land (see SchedulerLoop._try_preempt).  Returns the
    victims actually deleted."""
    return evict_as_unit(client, encoder, plan.victims,
                         grace_seconds=grace_seconds)


def evict_as_unit(client, encoder: Encoder,
                  victims: Sequence[Victim],
                  grace_seconds: int | None = None
                  ) -> Sequence[Victim]:
    """Evict a set of pods as one unit — the shared eviction primitive
    of preemption (victim sets) and the rebalancer (live-migration
    member sets, core/rebalance.py).  Best-effort per pod; callers
    that need all-or-nothing compare ``len(returned)`` against
    ``len(victims)`` and compensate (the rebalancer reverts the move
    and re-adds the already-deleted members)."""
    done = []
    for v in victims:
        try:
            client.delete_pod(v.name, namespace=v.namespace,
                              grace_seconds=grace_seconds)
            # Planner-side bookkeeping: this pod is no longer live
            # (PDB accounting) nor re-evictable while it terminates.
            encoder.mark_terminating(v.uid)
            done.append(v)
        except Exception:  # noqa: BLE001 — best-effort per pod
            continue
    return done
