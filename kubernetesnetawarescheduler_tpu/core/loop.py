"""The watch -> queue -> score -> bind loop.

The reference's ``Schedule()`` cycle (scheduler.go:189-237) popped ONE
pod, re-scraped the whole cluster synchronously, picked a node and
POSTed a Binding plus a "Scheduled" Event.  This loop keeps the same
external contract — pods in, Bindings + Events out — but pops a *batch*
from the queue, encodes it once, runs the fused score/assign kernel on
device, then emits one Binding/Event per pod.  Telemetry arrives
asynchronously through the :class:`~.encode.Encoder`, never inside the
cycle.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Sequence

import numpy as np

from kubernetesnetawarescheduler_tpu.config import SchedulerConfig
from kubernetesnetawarescheduler_tpu.core.assign import (
    assign_greedy,
    assign_parallel,
)
from kubernetesnetawarescheduler_tpu.core.encode import Encoder
from kubernetesnetawarescheduler_tpu.core.gang import (
    GangRegistry,
    gang_key_of,
    gang_shapes_of,
    place_gang,
    place_gang_shaped,
)
from kubernetesnetawarescheduler_tpu.k8s.client import ClusterClient
from kubernetesnetawarescheduler_tpu.k8s.informer import Informer, PodQueue
from kubernetesnetawarescheduler_tpu.k8s.types import (
    Binding,
    Node,
    Pod,
    failed_event,
    scheduled_event,
)
from kubernetesnetawarescheduler_tpu.utils.flight import (
    NULL_SPAN,
    FlightRecorder,
)
from kubernetesnetawarescheduler_tpu.utils.timeseries import (
    HistogramPhaseTimer,
    LogHistogram,
)


def _tracked_jit_fns():
    """The serving-path jitted entry points whose executable-cache
    growth feeds ``jit_cache_miss_total``.  Lazy import of the fused
    step so a loop constructed before core.assign finishes importing
    (test doubles) still works; ``_cache_size`` is jax's public
    per-function compile-cache counter and every tracked fn is a
    ``jax.jit`` product that has it."""
    from kubernetesnetawarescheduler_tpu.core.assign import (
        fused_schedule_step,
    )

    return (assign_greedy, assign_parallel, fused_schedule_step)


class SchedulerLoop:
    """Owns the informer, encoder and queue; drives scheduling cycles."""

    def __init__(self, client: ClusterClient, cfg: SchedulerConfig,
                 method: str = "parallel", decision_log=None,
                 encoder: Encoder | None = None, mesh=None,
                 async_bind: bool = False,
                 burst_batches: int = 8,
                 pipelined: bool = False,
                 multicycle: int | None = None) -> None:
        self.cfg = cfg
        self.client = client
        self.method = method
        # Three-stage software pipeline over the burst cycle: encode
        # of burst k+1 (host thread) overlaps the device step of burst
        # k, whose assignments are only fetched when the NEXT cycle
        # starts, while the network bind of burst k-1 drains on the
        # async-bind worker.  Commit/assume semantics are unchanged —
        # usage is committed at retire time, after the fetch, exactly
        # as the serial burst does — so assignments are bit-identical
        # to serial mode on the same feed (tests/test_pipeline.py).
        # Implies async_bind: without the bind worker the third stage
        # would re-serialize behind the cycle.
        self.pipelined = bool(pipelined)
        async_bind = async_bind or self.pipelined
        # Backlog burst mode: when the queue holds at least two full
        # batches, drain up to ``burst_batches`` of them through ONE
        # device dispatch (the replay's scanned per-batch step) and
        # ONE device->host assignment fetch.  The per-batch cycle pays
        # a dispatch + fetch round-trip per ``max_pods`` pods — ~65 ms
        # through a tunnel-attached device — which caps live serving
        # two orders below the replay throughput on the same kernels
        # (VERDICT r3 weak #3).  Semantics are the per-batch cycle's:
        # the scanned step is the SAME score->assign->commit body, and
        # in-stream peers resolve against earlier batches' placements
        # exactly as sequential cycles would (pinned by
        # tests/test_replay.py and test_burst.py).  0 or 1 disables.
        self.burst_batches = burst_batches
        # Persistent multi-cycle serving program (ISSUE 17): under a
        # deep backlog, encode a K-wave window ONCE, stage the waves
        # through a device ring (core/encode.DeviceWaveRing) and run
        # ONE donated scan over all K logical cycles — per-dispatch
        # overhead amortizes to 1/K of a cycle, the same move the
        # scan-amortized bench methodology proves out.  Waves are
        # RETIRED asynchronously (fetch + assume + bind enqueue), one
        # logical cycle per retire; usage commits ONLY at retire, so a
        # mid-window crash restores to the last retired cycle with no
        # half-committed wave.  K=1 (the default) is today's path,
        # bit-identical by construction; K>1 is test-pinned placement-
        # bit-identical to K sequential fused steps
        # (tests/test_multicycle.py).
        self.multicycle = int(multicycle if multicycle is not None
                              else getattr(cfg, "multicycle", 1))
        # Assume-then-bind (kube-scheduler's own cache pattern): the
        # cycle commits usage to the encoder IMMEDIATELY after the
        # kernel decides ("assume") and hands the network bind to a
        # worker thread, so the next cycle's snapshot sees the
        # placements without waiting a bind_many round-trip.  A bind
        # that the API server later rejects is rolled back via the
        # ledger-driven encoder.release.  Off by default: the
        # synchronous cycle is the reference's shape
        # (scheduler.go:196-233) and what most tests pin; serve.py
        # enables it via --async-bind / config.
        self.async_bind = async_bind
        # Optional core.checkpoint.DecisionLog: records the kernel's
        # choice per pod (node or "" for unschedulable) at decision
        # time, the replayable record behind restart-determinism.
        self.decision_log = decision_log
        # A restored encoder (core.checkpoint.load_checkpoint) can be
        # injected to resume from a snapshot instead of re-ingesting.
        self.encoder = encoder if encoder is not None else Encoder(cfg)
        self.queue = PodQueue(cfg.queue_capacity)
        # HistogramPhaseTimer = PhaseTimer + per-phase log-bucketed
        # histograms (utils/timeseries.py): the summary families keep
        # their series while /metrics gains native _hist buckets.
        self.timer = HistogramPhaseTimer()
        # Decision-level tracing (utils/flight.py): every serving cycle
        # commits one CycleSpan into this bounded ring buffer, and
        # (with cfg.enable_explain) every serving path — serial, gang,
        # burst, pipelined — retains a per-pod score-decomposition
        # record at its commit seam.  Observation only — nothing here
        # feeds back into scoring.  cfg.flight_recorder_size=0
        # disables the recorder entirely (NULL_SPAN no-ops).
        self.flight: FlightRecorder | None = (
            FlightRecorder(cfg.flight_recorder_size, cfg.explain_retain)
            if cfg.flight_recorder_size > 0 else None)
        # Last-seen cumulative snapshot-upload byte counters, so spans
        # carry per-cycle delta-vs-full increments.
        self._flight_bytes = (0, 0)
        # serve.py --jax-profile-dir flips this on: the device step is
        # then wrapped in jax.profiler.StepTraceAnnotation so device
        # traces correlate with flight-recorder cycle ids.
        self.jax_profile = False
        self.scheduled = 0
        self.unschedulable = 0
        self.burst_cycles = 0  # backlog bursts served (observability)
        self.bind_failures = 0
        self.preemptions = 0
        # Control-plane brownout resilience (see k8s/chaos.py and
        # docs/OPERATIONS.md "Failure modes & runbook"): the
        # transport's circuit breaker (None for plain in-memory
        # clients — every degraded-mode path is then dormant).  OPEN
        # means degraded mode: scoring/encode continue, decided binds
        # PARK (usage stays committed at assume, so later cycles score
        # exactly what the serial oracle would), and the backlog
        # drains FIFO on half-open/closed.
        self.breaker = getattr(client, "breaker", None)
        self.parked_dropped = 0    # _unsched_parked maxlen evictions
        self.watch_gaps = 0        # gap notifications from the client
        self.relists = 0           # relist audits run
        self.relist_repairs = 0    # drift items repaired by audits
        self.binds_parked_total = 0  # pods whose bind parked (breaker)
        self.binds_adopted = 0     # bound-elsewhere conflicts adopted
        self.binds_redirected = 0  # re-routed to the ledger's node
        self._relist_needed = False
        # State integrity & self-healing (core/integrity.py): serve.py
        # attaches the anti-entropy auditor under --audit-interval and
        # the seeded fault injector under --state-chaos; /metrics and
        # the chaos soak read the counters through these handles.
        self.integrity = None
        self.state_chaos = None
        # Outcome observability (obs/, ISSUE 11): the placement-
        # quality observer joins score-time predictions against later
        # probe truth at the commit seam; the SLO engine evaluates the
        # declarative objectives over multi-window burn rates.  Both
        # are observation-only (placements bit-identical on or off,
        # tests/test_quality.py) and cfg-gated off by default.
        if cfg.enable_quality_obs:
            from kubernetesnetawarescheduler_tpu.obs.quality import (
                QualityObserver,
            )

            self.quality: "QualityObserver | None" = (
                QualityObserver(cfg))
        else:
            self.quality = None
        if cfg.enable_slo:
            from kubernetesnetawarescheduler_tpu.obs.slo import (
                SLOEngine,
            )

            self.slo: "SLOEngine | None" = SLOEngine(cfg)
        else:
            self.slo = None
        self._slo_last_eval = 0.0
        self._quality_last_harvest = 0.0
        # Learned scoring policy (policy/, ISSUE 15): trains term
        # multipliers off the explain/outcome join and shadow-scores
        # recorded decisions; candidate weights reach the live scorer
        # ONLY through the counterfactual promotion gate (a seeded
        # scenario replay it must WIN).  Disabled (default) nothing is
        # constructed and scoring is bit-identical to cfg.weights
        # (tests/test_policy.py).
        if cfg.enable_learned_score:
            from kubernetesnetawarescheduler_tpu.policy import (
                PolicyDataset,
                ScoringPolicy,
            )

            self.policy: "ScoringPolicy | None" = ScoringPolicy(cfg)
            self.policy_dataset: "PolicyDataset | None" = (
                PolicyDataset(cfg, self.policy.k_pad))
        else:
            self.policy = None
            self.policy_dataset = None
        # Replay trace the eval tick's promotion gate replays; no
        # trace -> the gate refuses (shadow-only fail-safe).  serve.py
        # --policy-eval-trace sets it.
        self.policy_eval_trace: str | None = None
        self._policy_last_train = 0.0
        self._policy_last_eval = 0.0
        # Last-seen cumulative shadow-disagreement count, for the
        # per-span delta (the rebalance accounting pattern), and the
        # newest explain t_wall already shadow-ranked (the eval tick
        # must not re-count retained records).
        self._policy_shadow_last = 0
        self._policy_shadow_twall = 0.0
        # Continuous rebalancing (core/rebalance.py, ISSUE 12): the
        # budgeted descheduler acts on the degradation signals the
        # observers above only measure.  Off by default; with budget 0
        # or the flag off, placements are bit-identical to no
        # rebalancer at all (tests/test_rebalance.py).
        if cfg.enable_rebalance:
            from kubernetesnetawarescheduler_tpu.core.rebalance import (
                Rebalancer,
            )

            self.rebalance: "Rebalancer | None" = Rebalancer(
                cfg, self.encoder, self.client)
        else:
            self.rebalance = None
        self._rebalance_last = (0, 0)
        # One-shot span tag set by StateChaosInjector._record: the
        # next committed cycle span carries the injected fault class,
        # so a trace reader sees WHICH cycle first ran on corrupted
        # state.
        self._state_fault_pending: str | None = None
        # Scenario replay (scenario/replay.py) sets these before each
        # cycle so committed spans carry the trace join key; outside a
        # replay they keep their pre-r13 defaults and spans serialize
        # unchanged.
        self.scenario_phase: str | None = None
        self.trace_offset = 0
        # Fleet tenancy (r15): the FleetServer stamps each tenant
        # loop with its logical cluster name so committed spans carry
        # the tenant join key; solo loops keep None and spans
        # serialize unchanged (pre-r15 traces still lint clean).
        self.cluster_id: str | None = None
        # "fresh" | "restored" | "ignored": serve.py records its
        # checkpoint-restore decision here; /readyz reports it.
        self.checkpoint_state = "fresh"
        self.max_bind_retries = 3
        self._bind_retries: dict[str, int] = {}
        self._preempt_attempts: dict[str, int] = {}
        # Preemptors waiting for victim-deletion confirmation:
        # uid -> (pod, outstanding victim uids, deadline).  Requeued by
        # _on_pod_gone when the set drains, or by maintain() past the
        # deadline.  Mutated from both the loop thread and the watch
        # thread — every structural access holds _preempt_lock (the
        # encoder's own lock is always acquired inside it, never the
        # reverse).
        self._awaiting_preemption: dict[
            str, tuple[Pod, set, float]] = {}
        self._preempt_lock = threading.Lock()
        if mesh is not None:
            # Mesh-sharded serving (multi-chip / multi-host): the same
            # cycle, with score+assign jitted under the canonical
            # (dp, tp) shardings — see parallel.sharding.  The
            # extender webhook path picks up sharded_score (node axis
            # over every chip, pods replicated) via the batcher.
            from kubernetesnetawarescheduler_tpu.parallel.sharding import (
                serving_fns,
            )

            (self._assign, self.sharded_score,
             self._sharded_burst) = serving_fns(cfg, mesh, method)
        else:
            self.sharded_score = None
            self._sharded_burst = None
            self._assign = {"greedy": assign_greedy,
                            "parallel": assign_parallel}[method]
            # Batch-invariant static prep cache (the same explicit
            # (state, version) threading the extender batcher's
            # _static_for uses): the O(N^2) metric-vote/network
            # normalization depends only on metrics/network/validity —
            # never on placements — so serving cycles reuse it until
            # the encoder's static version moves.  Without this every
            # watch-loop cycle re-derived ~3 HBM passes over the N x N
            # matrix (tens of ms at N=5120 on the CPU fallback).
            self._static_version: int | None = None
            self._static_val = None
        # Incremental static refresh (cfg.enable_delta_state /
        # cfg.enable_async_static): the running net extrema that make
        # delta rebuilds exact, the background refresh worker, and the
        # observability counters the bench/selfmetrics read.  The
        # worker NEVER blocks a serving batch: _static_for hands the
        # rebuild off and keeps scoring against the last static until
        # the staleness contract (static_max_staleness_s /
        # static_max_versions_behind) forces a synchronous build.
        self._static_ex = None
        self._static_lock = threading.Lock()
        self._static_cv = threading.Condition(self._static_lock)
        self._static_req: tuple | None = None
        self._static_stop = False
        self._static_thread: threading.Thread | None = None
        self._static_stale_since: float | None = None
        self.static_refresh_total = 0
        self.static_sync_builds = 0
        # Log-bucketed histograms (utils/timeseries.py) replacing the
        # r7 ad-hoc deques: same drop-in window surface (append /
        # list / clear / len / [-1]) for existing consumers, plus
        # exact never-evicting bucket counts exported as native
        # Prometheus histograms.  Bounds in the RECORDED unit
        # (milliseconds / seconds respectively).
        self._static_refresh_ms = LogHistogram(
            lo=1e-2, hi=1e6, window=2048)
        self._staleness_samples = LogHistogram(
            lo=1e-3, hi=1e5, window=8192)
        # The mesh serving fns keep their own leaf-placer transfer
        # cache; only the plain path threads an explicit static pair.
        self._assign_takes_static = mesh is None
        # Conflict-round samples from serving cycles (parallel method,
        # one per batch) — the same observable the bench reports
        # (rounds_p50/p99), exposed through /metrics so an operator
        # sees round-bound latency without a replay harness.
        import queue as queue_mod
        from collections import deque

        self._bind_q: queue_mod.Queue | None = None
        self._bind_worker: threading.Thread | None = None
        self._bind_worker_err: list[BaseException] = []
        # Uids assumed by THIS process (duplicate-delivery filter for
        # the assume path).  Deliberately not the encoder ledger: a
        # restored checkpoint could, after an unclean shutdown, carry
        # a committed-but-never-bound pod, and filtering on the ledger
        # would drop its re-delivery before the network forever — the
        # sync path heals exactly that case via bind + commit dedup,
        # and with a process-local set the assume path does too.
        # Mutated from the cycle thread (add) and the bind worker
        # (discard on rollback); both are GIL-atomic set ops.
        self._assumed_uids: set[str] = set()
        # Assumed placements by pod NAME: the scheduler's own cache
        # for peer resolution (kube-scheduler style).  In async mode
        # client.node_of lags the bind worker, so resolving peers from
        # the API-server view made encode-time peer resolution RACE
        # bind latency — nondeterministic scores for pods whose peers
        # were decided but not yet confirmed.  Written at assume time,
        # dropped on rollback and on pod deletion; reads fall back to
        # the API-server view.  Values are (namespace, node) so the
        # bare-name alias (annotation peers use bare names) can be
        # dropped owner-checked — popping it unconditionally on pod
        # deletion would evict a same-named pod from another
        # namespace.  _alias_lock guards the compound read-modify-
        # write sequences (refcounted bare-alias poisoning below);
        # single-key reads stay lock-free (GIL-atomic), same threading
        # contract as _assumed_uids.
        self._assumed_node: dict[str, tuple[str, str]] = {}
        # Namespaces with a LIVE assumption per bare pod name.  While
        # two or more namespaces hold the same bare name, the bare
        # alias is ambiguous and stays dropped ("poisoned") — the
        # refcount makes the poison sticky across re-assumes (a dict
        # probe alone cannot distinguish "never collided" from
        # "poisoned then popped") and restores the survivor's alias
        # when the collision clears.
        self._bare_ns: dict[str, set[str]] = {}
        self._alias_lock = threading.Lock()
        # Pods the kernel rejected while unconfirmed assumptions held
        # capacity: requeued when a rollback frees some (bounded; the
        # periodic resync re-delivers anything dropped).
        self._unsched_parked: "deque[Pod]" = deque(maxlen=1024)
        # O(1) membership alongside the deque (PodQueue._queued's
        # pattern) so the per-deletion purge check in _on_pod_gone is
        # a set probe, not a 1024-entry scan under the lock.  May
        # over-approximate (a maxlen-evicted pod's uid lingers until
        # its deletion) — harmless: the rebuild just finds nothing.
        self._parked_uids: set[str] = set()
        # Guards every _unsched_parked iteration/mutation: the cycle
        # thread appends, the bind worker and node-add callback drain,
        # and _on_pod_gone rebuilds — same mid-iteration RuntimeError
        # hazard _round_lock documents for round_samples.
        self._parked_lock = threading.Lock()
        # Bind batches parked under an OPEN breaker (degraded mode):
        # complete _bind_q items whose usage is already assumed.
        # Unbounded on purpose — backpressure comes from the queue and
        # _bind_q bounds upstream, and dropping an ASSUMED batch would
        # leak committed usage.  Guarded by _parked_lock.
        self._parked_binds: deque = deque()
        # In-flight pipelined burst: (pods, device out, with_stats,
        # node_table, n_real, dispatch t0, snapshot state, static).
        # Owned by the cycle thread
        # (run_once / flush_binds callers); retired before any state
        # read that must see its placements.
        self._pipe_inflight: tuple | None = None
        # The in-flight burst's span builder + static version, committed
        # at retire alongside the usage commit (crash-safety parity:
        # a span only exists for cycles whose placements landed).
        self._pipe_span: tuple | None = None
        # Multicycle retire queue: one record per LOGICAL cycle of the
        # in-flight window, sharing a single device output (fetched
        # once, at the first retire).  Owned by the cycle thread, like
        # _pipe_inflight; drained by _retire_multicycle before any
        # state read that must see its placements.
        self._mc_inflight: "deque" = deque()
        # Device wave ring (core/encode.DeviceWaveRing), built lazily
        # at first multicycle window so K=1 loops never touch it.
        self._wave_ring = None
        self.multicycle_windows = 0        # windows dispatched
        self.multicycle_overflow_total = 0  # waves past ring capacity
        # Last RETIRED logical cycle id: the restore point a mid-window
        # crash lands on (checkpoint meta provenance; -1 = none yet).
        self.multicycle_last_retired = -1
        # Retire lag in logical cycles (wave j of a window retires j
        # cycles after the window head) — small ints, so doubling
        # buckets from 1 keep them exact (round_samples pattern).
        self._retire_lag = LogHistogram(
            lo=1.0, hi=1024.0, growth=2.0, window=2048)
        # Coalesced async binds (ISSUE 17): items folded into an
        # earlier batch's fanout, and how many workers are inside a
        # bind fanout right now (gauge + high-water mark; bounded by
        # cfg.bind_max_inflight).
        self.bind_coalesced_total = 0
        self.bind_inflight = 0
        self.bind_inflight_peak = 0
        self._bind_inflight_lock = threading.Lock()
        self._encode_pool = None
        if self.pipelined:
            import concurrent.futures

            self._encode_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="encode-ahead")
        self._bind_workers: list[threading.Thread] = []
        if async_bind:
            # Bounded: a dead/slow API server must apply backpressure
            # to the cycle, not buffer unbounded assumed state.
            self._bind_q = queue_mod.Queue(maxsize=8)
            # Bounded-inflight worker pool (cfg.bind_max_inflight,
            # default 1 = the pre-r16 single worker).  Each worker may
            # additionally coalesce up to cfg.bind_coalesce_window
            # queued batches into one fanout — see _bind_worker_main.
            n_workers = max(1, int(getattr(cfg, "bind_max_inflight",
                                           1)))
            for wi in range(n_workers):
                w = threading.Thread(
                    target=self._bind_worker_main, daemon=True,
                    name=f"bind-worker-{wi}")
                self._bind_workers.append(w)
                w.start()
            self._bind_worker = self._bind_workers[0]

        # Gang scheduling (core/gang.py): annotated pods are diverted
        # into the registry's gate by run_once and scheduled as whole
        # groups through _schedule_gang once minMember have arrived.
        self.gangs = (GangRegistry(cfg)
                      if cfg.enable_gang_scheduling else None)
        self.gangs_bound = 0
        self.gangs_rolled_back = 0
        # Elastic reshaping (r17): gangs committed at a DEGRADED
        # declared realization (fewer members than arrived) by the
        # shape-aware placement path, plus the per-span delta baseline
        # for the rebalancer's reshape counters.
        self.gangs_shaped_degraded = 0
        self._reshape_last = (0, 0)

        # Conflict-round window as a LogHistogram (rounds are small
        # ints; doubling buckets from 1 keep them exact): drop-in for
        # the old deque, with one-lock internal snapshots.
        self.round_samples = LogHistogram(
            lo=1.0, hi=1024.0, growth=2.0, window=256)
        # Appends happen on the serving thread while /metrics scrapes
        # from the UDS/gRPC threads; iterating a deque mid-append
        # raises RuntimeError, so both sides take this lock.
        self._round_lock = threading.Lock()
        # Fused-step accounting (ISSUE 9).  The serving loop never
        # donates: its snapshot leaves belong to the encoder's
        # delta-ingest cache (patched in place across cycles, r7), so
        # routing them through fused_schedule_step's donate_argnums
        # would hand XLA buffers the encoder still owns — every device
        # dispatch here counts a donation SKIP instead, and
        # donated_total moves only on paths that own their state (the
        # bench chain, replay folds).  jit_cache_miss_total is the
        # executable-cache growth across the tracked serving-path
        # entry points (``_cache_size`` deltas): after warmup, any
        # motion is a recompile the bucketed batch-size ladder was
        # supposed to prevent (scraped as
        # ``netaware_jit_cache_miss_total``; regression-tested in
        # tests/test_winner_fusion.py).
        self.donated_total = 0
        self.donation_skipped_total = 0
        self.jit_cache_miss_total = 0
        self._jit_cache_last = 0
        # is_parked keeps resync/watch re-deliveries of a preemptor
        # that is waiting for victim confirmation out of the queue —
        # scoring it early would drop its reservation and burn its
        # attempt budget against usage the victims still hold.
        # is_parked also covers _parked_uids: gang members parked
        # after a rollback (and async-mode unschedulable pods) are
        # woken by _requeue_parked, not resync — a resync re-delivery
        # would duplicate them in the queue while parked.
        self.informer = Informer(
            client, self.queue, cfg.scheduler_name,
            on_node=self._on_node,
            is_parked=lambda p: (p.uid in self._awaiting_preemption
                                 or p.uid in self._parked_uids))
        # Usage release on pod termination/deletion: without this a
        # long-running daemon's committed usage grows monotonically
        # until every node looks full.  Clients deliver at most once
        # per pod (KubeClient dedups terminal-MODIFIED vs DELETED).
        client.on_pod_deleted(self._on_pod_gone)
        # Node scale-down: free the encoder slot (round 1 leaked slots
        # and kept binding to deleted nodes).
        client.on_node_deleted(self._on_node_gone)
        # Watch-gap detection -> relist audit: clients that can tell
        # us a stream lost events (410 Gone, reset resourceVersion)
        # arm a full relist on the next cycle.  getattr-guarded for
        # third-party ClusterClients predating the surface.
        gap_reg = getattr(client, "on_watch_gap", None)
        if callable(gap_reg):
            try:
                gap_reg(self._on_watch_gap)
            except Exception:  # noqa: BLE001 — optional surface
                pass
        # Real policy/v1 PodDisruptionBudgets: watch + initial sync
        # (events missed while down), feeding the preemption planner.
        # Optional per ClusterClient contract, and defensive: a
        # cluster (or test double) without policy/v1 access must not
        # fail serving — the annotation surface still protects.
        try:
            client.on_pdb_changed(self._on_pdb)
            initial_pdbs = client.list_pdbs()
        except Exception:  # noqa: BLE001 — no policy/v1: degrade
            initial_pdbs = None
        if initial_pdbs:
            for pdb in initial_pdbs:
                self.encoder.set_pdb(pdb)

    def _on_pdb(self, pdb, deleted: bool) -> None:
        if deleted:
            self.encoder.remove_pdb(pdb.uid or
                                    f"{pdb.namespace}/{pdb.name}")
        else:
            self.encoder.set_pdb(pdb)

    def _on_node(self, node: Node) -> None:
        try:
            self.encoder.node_index(node.name)
            is_new = False
        except KeyError:
            is_new = True
        self.encoder.upsert_node(node)
        if is_new:
            # New capacity: retry pods rejected while the cluster was
            # full (kube's unschedulable-queue flush on NodeAdd).
            # Only genuinely NEW nodes — requeueing on every node
            # UPDATE would churn the queue on routine status traffic.
            self._requeue_parked()

    def _on_node_gone(self, node: Node) -> None:
        self.encoder.remove_node(node.name)

    def _on_pod_gone(self, pod: Pod) -> None:
        self._preempt_attempts.pop(pod.uid, None)
        # A gated gang member deleted before its gang completed must
        # not count toward minMember forever.
        if self.gangs is not None:
            self.gangs.pod_gone(pod)
        # Keep the assume-dedup set bounded by live-pod lifetime.
        self._assumed_uids.discard(pod.uid)
        self._drop_assumed_node(pod)
        # A deleted pod must not be revived by _requeue_parked (the
        # spurious assume/bind would roll back via the bind failure,
        # but inflates counters and emits a bogus event first).
        with self._parked_lock:
            if pod.uid in self._parked_uids:
                from collections import deque

                self._parked_uids.discard(pod.uid)
                self._unsched_parked = deque(
                    (p for p in self._unsched_parked
                     if p.uid != pod.uid),
                    maxlen=self._unsched_parked.maxlen)
        # A deleted preemptor abandons its reservation and wait.
        with self._preempt_lock:
            if self._awaiting_preemption.pop(pod.uid, None) is not None:
                self.encoder._drop_nomination(pod.uid)
        # Release BEFORE the confirmation drain below: a requeued
        # preemptor must never be scored against usage its just-
        # terminated victim still held.
        # No scheduler_name filter: extender-path binds commit usage
        # for pods whose schedulerName is the stock scheduler's, and
        # their deletions must release it.  The uid-keyed ledger makes
        # release a no-op for pods we never committed, so foreign pods
        # cost at most an early-release marker (bounded set).
        if pod.node_name:
            self.encoder.release(pod, pod.node_name)
        # Victim-deletion confirmation: requeue preemptors whose last
        # outstanding victim just terminated.  A failed push is fine —
        # the entry is gone, so the pod is no longer parked and the
        # next resync re-delivers it.
        ready: list[Pod] = []
        with self._preempt_lock:
            for puid, (pp, vset, _dl) in list(
                    self._awaiting_preemption.items()):
                if pod.uid in vset:
                    vset.discard(pod.uid)
                    if not vset:
                        del self._awaiting_preemption[puid]
                        ready.append(pp)
        for pp in ready:
            self.queue.push(pp)

    # ------------------------------------------------------------------
    # Decision-level tracing (utils/flight.py)

    def _span_begin(self, path: str):
        """Start a cycle span, or the shared no-op when the recorder
        is disabled — call sites keep one code shape either way."""
        if self.flight is None:
            return NULL_SPAN
        return self.flight.begin(path)

    def _profile_step(self, step_num: int):
        """Opt-in jax.profiler step annotation around the device step
        (serve.py --jax-profile-dir): device trace steps then carry the
        flight recorder's cycle id, so a Perfetto device timeline and
        /debug/trace line up by number."""
        if not self.jax_profile:
            return contextlib.nullcontext()
        import jax

        return jax.profiler.StepTraceAnnotation(
            "netaware_cycle", step_num=step_num)

    def _poll_jit_misses(self) -> None:
        """Fold executable-cache growth across the tracked jitted
        entry points into ``jit_cache_miss_total``.  Called once per
        device dispatch (cheap: three int reads); after warmup the
        delta must be zero — the bucketed batch-size ladder exists so
        every steady-state shape hits a warm cache."""
        total = 0
        for fn in _tracked_jit_fns():
            size = getattr(fn, "_cache_size", None)
            if size is None:
                continue
            try:
                total += int(size())
            except Exception:  # noqa: BLE001 — accounting only
                continue
        if total > self._jit_cache_last:
            self.jit_cache_miss_total += total - self._jit_cache_last
        self._jit_cache_last = total

    def _note_dispatch(self) -> None:
        """Per-device-dispatch fused-step accounting: the serving
        loop's snapshot is encoder-owned (delta-ingest patches it in
        place), so its dispatches never donate — count the skip, and
        poll the jit caches for recompiles while we're here."""
        self.donation_skipped_total += 1
        self._poll_jit_misses()

    def _span_commit(self, sb, pods: Sequence[Pod],
                     static_version: int | None = None,
                     rounds: int = 0,
                     donated: int = 0,
                     donation_skipped: int = 1,
                     scan_window_k: int | None = None,
                     retire_lag_cycles: int | None = None) -> None:
        """Freeze and commit a cycle span.  Called where the cycle's
        effects commit: end of the serial/burst/gang cycle, or at
        RETIRE for the pipelined path — so a crash never leaves a span
        claiming a cycle whose placements were lost.

        Also THE outcome-observability seam (obs/, ISSUE 11): quality
        capture and the time-gated SLO evaluation ride here — before
        the recorder guard, so they run on all four paths even with
        the flight recorder off.  Both are exception-guarded:
        observation never breaks serving."""
        if self.quality is not None:
            try:
                self.quality.note_commit(self, pods,
                                         cycle_id=sb.cycle_id)
            except Exception:  # noqa: BLE001 — observation only
                pass
        slo_burning = None
        if self.slo is not None:
            try:
                now = time.monotonic()
                if (now - self._slo_last_eval
                        >= self.cfg.slo_eval_interval_s):
                    self._slo_last_eval = now
                    self.slo.evaluate(self)
                b = self.slo.burning()
                slo_burning = b[0] if b else None
            except Exception:  # noqa: BLE001 — observation only
                slo_burning = None
        if self.flight is None or sb is NULL_SPAN:
            return
        enc = self.encoder
        db = int(getattr(enc, "snapshot_delta_bytes_total", 0))
        fb = int(getattr(enc, "snapshot_full_bytes_total", 0))
        last_db, last_fb = self._flight_bytes
        self._flight_bytes = (db, fb)
        built = getattr(self, "_static_version", None)
        behind = 0
        if static_version is not None and built is not None:
            behind = max(0, int(static_version) - int(built))
        stale = 0.0
        if self.cfg.enable_async_static and self._staleness_samples:
            try:
                stale = float(self._staleness_samples[-1])
            except IndexError:
                stale = 0.0
        breaker = self.breaker
        bstate = (str(getattr(breaker, "state", "closed"))
                  if breaker is not None else "closed")
        degraded = self.degraded
        # Injected state faults outrank transport faults on the span:
        # corrupted state is the rarer, more actionable signal, and the
        # tag is one-shot (consumed by the first committed span).
        state_fault = self._state_fault_pending
        self._state_fault_pending = None
        fault = (f"state_{state_fault}" if state_fault
                 else "apiserver_brownout" if degraded
                 else "watch_gap" if self._relist_needed else None)
        # Rebalance accounting: cumulative counters turned into
        # per-span deltas (the descheduler runs on the maintain path,
        # so a span carries whatever moved since the previous span).
        rb_moves = rb_reverts = 0
        gang_reshapes = reshape_reverts = None
        if self.rebalance is not None:
            mt = int(self.rebalance.moves_total)
            rt = int(self.rebalance.moves_reverted)
            last_mt, last_rt = self._rebalance_last
            self._rebalance_last = (mt, rt)
            rb_moves = max(mt - last_mt, 0)
            rb_reverts = max(rt - last_rt, 0)
            # r17 reshape accounting: carried only when the feature is
            # live (None off-path, so pre-r17 trace consumers and old
            # dumps stay byte-identical — same only-when-present
            # contract trace_check enforces).
            if self.cfg.enable_gang_reshaping or getattr(
                    self.rebalance.cfg, "enable_gang_reshaping",
                    False):
                rs = int(getattr(self.rebalance, "reshapes_total", 0))
                rr = int(getattr(self.rebalance,
                                 "reshapes_reverted", 0))
                last_rs, last_rr = self._reshape_last
                self._reshape_last = (rs, rr)
                gang_reshapes = max(rs - last_rs, 0)
                reshape_reverts = max(rr - last_rr, 0)
        # Policy accounting: same cumulative->per-span-delta shape
        # (shadow ranking runs on the maintain path).
        pol_disagree = pol_version = 0
        if self.policy is not None:
            sd = int(self.policy.shadow_disagreement_total)
            pol_disagree = max(sd - self._policy_shadow_last, 0)
            self._policy_shadow_last = sd
            pol_version = int(self.policy.version)
        # Cap the per-span uid list: a whole-workload bench drain can
        # retire tens of thousands of pods in one span, and the ring
        # holds `capacity` spans — n_pods still carries the true count.
        span = sb.finish(
            n_pods=len(pods),
            pod_uids=tuple(p.uid for p in pods[:64]),
            queue_depth=len(self.queue),
            static_staleness_s=stale,
            static_versions_behind=behind,
            breaker_state=bstate,
            degraded=degraded,
            fault_class=fault,
            delta_bytes=max(db - last_db, 0),
            full_bytes=max(fb - last_fb, 0),
            rounds=int(rounds),
            # Cycle-level donation disposition mirrors the loop-wide
            # counters: solo serving dispatches never donate (snapshot
            # is encoder-owned), so spans carry donated=0 and one skip
            # — a trace reader sees WHY the single-dispatch step still
            # copies state, per cycle, not just in aggregate.  Fleet
            # cycles (r15) override both: the batched FleetState is
            # fleet-owned, so its dispatches DO donate.
            donated=int(donated),
            donation_skipped=int(donation_skipped),
            slo_burning=slo_burning,
            outcome_ring_depth=(self.quality.ring_depth()
                                if self.quality is not None else 0),
            rebalance_moves=rb_moves,
            rebalance_reverts=rb_reverts,
            gang_reshapes=gang_reshapes,
            reshape_reverts=reshape_reverts,
            scenario_phase=self.scenario_phase,
            trace_offset=int(self.trace_offset),
            policy_shadow_disagreements=pol_disagree,
            policy_version=pol_version,
            cluster_id=self.cluster_id,
            scan_window_k=scan_window_k,
            retire_lag_cycles=retire_lag_cycles,
        )
        self.flight.commit(span)

    def _capture_explains(self, pods: Sequence[Pod], batch,
                          assignment: np.ndarray, state, static,
                          node_table, cycle_id: int, path: str,
                          extra: dict | None = None) -> None:
        """Retain a per-pod placement-explain record (top-k candidates
        with the score decomposition and the gates that filtered the
        rest).  Host-side, AFTER the jitted score/assign already ran —
        gated by cfg.enable_explain, so when off the serving path is
        untouched and placements are bit-identical.  All four serving
        paths call this at their retire/commit seam: serial and gang
        pass the exact cycle batch (the decomposition reproduces the
        winner's score, tests/test_score.py); burst/pipelined pass
        per-chunk re-encodes built at commit time (see
        :meth:`_capture_explains_burst`), whose in-stream peers
        resolve against the now-published placements — the totals are
        a post-hoc decomposition there, not the in-scan score."""
        if (self.flight is None or not self.cfg.enable_explain
                or not pods):
            return
        from kubernetesnetawarescheduler_tpu.core.score import (
            explain_scores,
        )

        try:
            comps = explain_scores(state, batch, self.cfg, static)
        except Exception:  # noqa: BLE001 — observation never breaks serving
            return
        table_names, _gens = node_table
        valid = np.asarray(state.node_valid, dtype=bool)
        gate_keys = ("static_ok", "fits", "affinity", "anti",
                     "sym_anti", "zone_ok", "spread_ok")
        netmodel = getattr(self.encoder, "netmodel", None)
        if netmodel is not None:
            prov = {"network": "netmodel_blend",
                    "pair_coverage": float(netmodel.coverage_fraction(
                        self.encoder.num_nodes))}
        else:
            prov = {"network": "direct_probe"}
        k = min(self.cfg.explain_top_k, len(table_names))
        total = comps["total"]
        # Node class for the learned policy's per-class adjustment:
        # the encoder's interned zone index (-1 = no zone label).
        zones = np.asarray(state.node_zone, dtype=np.int64)
        now = time.time()
        for i, pod in enumerate(pods):
            row = total[i]
            idx = int(assignment[i])
            order = np.argsort(row, kind="stable")[::-1][:k]
            candidates = []
            for j in order:
                j = int(j)
                name = (table_names[j]
                        if j < len(table_names) and table_names[j]
                        else f"slot-{j}")
                candidates.append({
                    "node": name,
                    "node_index": j,
                    "zone": int(zones[j]) if j < len(zones) else -1,
                    "total": float(row[j]),
                    "feasible": bool(comps["ok"][i, j]),
                    "components": {
                        "base": float(comps["base"][i, j]),
                        "net": float(comps["net"][i, j]),
                        "soft": float(comps["soft"][i, j]),
                        "balance": -float(comps["balance"][i, j]),
                        "spread": -float(comps["spread"][i, j]),
                    },
                    "gates": {g: bool(comps[g][i, j])
                              for g in gate_keys},
                })
            record = {
                "pod_uid": pod.uid,
                "pod": f"{pod.namespace}/{pod.name}",
                "cycle_id": cycle_id,
                "path": path,
                "t_wall": now,
                "decision": "bound" if idx >= 0 else "unschedulable",
                "node": (table_names[idx]
                         if 0 <= idx < len(table_names) else None),
                "node_index": idx,
                "score": float(row[idx]) if idx >= 0 else None,
                "candidates": candidates,
                "feasible_nodes": int(np.sum(valid & comps["ok"][i])),
                "gates_filtered": {
                    g: int(np.sum(valid & ~comps[g][i]))
                    for g in gate_keys},
                "provenance": prov,
            }
            if extra:
                record.update(extra)
            self.flight.put_explain(record)

    def _capture_explains_burst(self, pods: Sequence[Pod],
                                assignment: np.ndarray, state, static,
                                node_table, cycle_id: int,
                                path: str) -> None:
        """Explain capture for the burst/pipelined paths, run at the
        retire/commit seam AFTER the assume/bind published this
        burst's placements.  The scanned device step never
        materializes per-batch score planes, so each max_pods chunk is
        re-encoded here — in-stream peers then resolve against the
        placements the scan actually produced — and decomposed through
        the same :meth:`_capture_explains` body.  Observation only:
        encode errors drop the remaining chunks, never the cycle."""
        if (self.flight is None or not self.cfg.enable_explain
                or not pods):
            return
        cap = self.cfg.max_pods
        for off in range(0, len(pods), cap):
            chunk = list(pods[off:off + cap])
            try:
                batch = self.encoder.encode_pods(
                    chunk, node_of=self._peer_node, lenient=True)
            except Exception:  # noqa: BLE001 — observation never breaks serving
                return
            self._capture_explains(chunk, batch,
                                   assignment[off:off + cap],
                                   state, static, node_table,
                                   cycle_id, path)

    # ------------------------------------------------------------------

    def run_once(self, timeout: float | None = 0.0) -> int:
        """One cycle: pop up to ``max_pods`` pods, schedule, bind.
        Returns the number of pods bound.

        Backlog burst: with at least two full batches queued (and
        ``burst_batches`` > 1), pops up to ``burst_batches`` batches
        and drains them through one device dispatch + one fetch
        (see __init__)."""
        budget = getattr(self.client, "retry_budget", None)
        if budget is not None:
            # Shared per-cycle retry allowance: whatever list-GET
            # retries this cycle spends, it spends from one pool.
            budget.begin_cycle()
        if self._relist_needed:
            self.relist_audit()
        if self._parked_binds:
            self._drain_parked_binds()
        batch = self.cfg.max_pods
        # Persistent multi-cycle window (r16): with K>1 and a deep
        # backlog, pop up to K batches and serve them as ONE scanned
        # device program — run_once still counts/retires per logical
        # cycle.  Plain path only: the mesh burst fn compiles for the
        # burst shape, and gang groups retire the window first (same
        # snapshot ordering as the pipelined path).
        if (self.multicycle > 1 and self._sharded_burst is None
                and len(self.queue) >= 2 * batch):
            pods = self.queue.pop_batch(self.multicycle * batch,
                                        timeout)
            pods, ready = self._gang_gate(pods)
            bound = 0
            if pods:
                bound = self.schedule_pods_multicycle(pods)
            if ready:
                bound += self._retire_multicycle()
            for key, members in ready:
                bound += self._schedule_gang(key, members)
            return bound
        if (self.burst_batches > 1
                and len(self.queue) >= 2 * batch):
            pods = self.queue.pop_batch(self.burst_batches * batch,
                                        timeout)
            pods, ready = self._gang_gate(pods)
            bound = 0
            if len(pods) > batch:
                if self.pipelined:
                    bound = self._pipeline_cycle(pods)
                    if ready:
                        # A gang's joint placement snapshots the
                        # encoder itself; retire the burst just
                        # dispatched so the gang never races its
                        # uncommitted placements.
                        bound += self._retire_inflight()
                else:
                    bound = self.schedule_pods_burst(pods)
            elif pods:  # raced down to a single batch: normal path
                bound = self._retire_inflight()
                bound += self.schedule_pods(pods)
            for key, members in ready:
                bound += self._schedule_gang(key, members)
            return bound
        # Shallow queue: a pipelined burst or multicycle window still
        # in flight is retired first — its placements must land before
        # (or instead of) any per-batch cycle.
        bound = self._retire_multicycle() if self._mc_inflight else 0
        bound += self._retire_inflight()
        pods = self.queue.pop_batch(batch, timeout)
        pods, ready = self._gang_gate(pods)
        if not pods and not ready:
            # Still drain degradation records: in extender-only
            # deployments the watch queue stays empty while the
            # webhook/bind paths keep encoding (and possibly
            # degrading) pods.
            self._emit_degraded_events()
            return bound
        if pods:
            bound += self.schedule_pods(pods)
        for key, members in ready:
            bound += self._schedule_gang(key, members)
        return bound

    def _gang_gate(self, pods: Sequence[Pod]
                   ) -> tuple[list[Pod], list[tuple[str, list[Pod]]]]:
        """The gang gate AHEAD of per-pod scheduling: pods carrying a
        pod-group annotation are absorbed into the registry instead of
        scheduled; a pod that completes its gang releases the whole
        group as a ``(key, members)`` unit for :meth:`_schedule_gang`.
        Annotation-free pods pass through untouched (and pay nothing —
        one ``gang_key_of`` string probe each)."""
        if self.gangs is None:
            return list(pods), []
        passthrough: list[Pod] = []
        ready: list[tuple[str, list[Pod]]] = []
        for pod in pods:
            key = gang_key_of(pod)
            if not key:
                passthrough.append(pod)
                continue
            members = self.gangs.admit(pod)
            if members is not None:
                ready.append((key, members))
        return passthrough, ready

    def schedule_pods_burst(self, pods: Sequence[Pod]) -> int:
        """Schedule several batches' worth of pods in ONE device
        dispatch and ONE assignment fetch, via the replay's scanned
        per-batch step.  Same score->assign->commit semantics as
        sequential :meth:`schedule_pods` cycles — in-stream peers
        resolve against earlier batches' placements through the scan
        carry, exactly as they would across sequential cycles."""
        from kubernetesnetawarescheduler_tpu.core.replay import (
            pad_stream,
            replay_stream_static,
        )

        # Timer samples are per-batch-NORMALIZED (wall / n_real per
        # phase): the percentile streams feed host-mode density and
        # /metrics as per-batch latency, and an un-normalized burst
        # sample would read as an 8x regression (the pipeline replay
        # normalizes its per-chunk samples the same way).  Each phase
        # records the normalized value with WEIGHT n_real so a burst
        # carries its full per-batch weight in the percentile streams
        # (one averaged sample per burst structurally under-reported
        # the tail), and the un-normalized cycle wall goes to
        # ``burst_wall`` — the latency the last batch in the burst
        # actually observed end-to-end.
        n_real = -(-len(pods) // self.cfg.max_pods)
        sb = self._span_begin("burst")
        cycle_t0 = time.perf_counter()
        t0 = cycle_t0
        stream = self.encoder.encode_stream(
            pods, node_of=self._peer_node, lenient=True)
        # Pad to the FULL burst shape, not just a batch multiple:
        # the replay compiles per batch-count, so variable depths
        # would each pay a fresh XLA compile (a measured 6x
        # serving regression); padded batches are fully masked
        # and cost ~nothing on device.
        stream = pad_stream(stream,
                            self.burst_batches * self.cfg.max_pods)
        state, version = self.encoder.snapshot_versioned()
        node_table = self.encoder.node_table()
        sb.add_phase("encode", t0, time.perf_counter() - t0)
        self.timer.record("encode",
                          (time.perf_counter() - t0) / n_real,
                          count=n_real)
        self._emit_degraded_events()
        t0 = time.perf_counter()
        static = None
        with self._profile_step(sb.cycle_id):
            if self._sharded_burst is not None:
                # Mesh path: the shared-placer sharded scan (node axis
                # on tp, batch axis on dp); static prep runs inside
                # the dispatch like the mesh per-batch path, amortized
                # over the burst.
                out, with_stats = self._sharded_burst(state, stream)
            else:
                with_stats = self.method == "parallel"
                # Same version-keyed static cache as the per-batch
                # cycle — recomputing the O(N²) prep inside every
                # burst dispatch halved serving throughput on the CPU
                # fallback.
                static = self._static_for(state, version)
                out = replay_stream_static(state, stream, static,
                                           self.cfg, self.method,
                                           with_stats=with_stats)
        cycle_rounds = 0
        if with_stats:
            assignment_dev, _final_state, rounds_dev = out
            assignment = np.asarray(jax_block(assignment_dev))
            rounds = np.asarray(rounds_dev)
            cycle_rounds = int(rounds[:n_real].max()) if n_real else 0
            with self._round_lock:
                self.round_samples.extend(
                    int(r) for r in rounds[:n_real])
        else:
            assignment_dev, _final_state = out
            assignment = np.asarray(jax_block(assignment_dev))
        self._note_dispatch()
        sb.add_phase("score_assign", t0, time.perf_counter() - t0)
        self.timer.record("score_assign",
                          (time.perf_counter() - t0) / n_real,
                          count=n_real)
        assignment = assignment[:len(pods)]
        t0 = time.perf_counter()
        if self.async_bind:
            bound = self._assume_and_enqueue(pods, assignment,
                                             node_table)
        else:
            bound = self._bind_all(pods, assignment, node_table)
        sb.add_phase("bind", t0, time.perf_counter() - t0)
        self.timer.record("bind",
                          (time.perf_counter() - t0) / n_real,
                          count=n_real)
        self.timer.record("burst_wall",
                          time.perf_counter() - cycle_t0)
        self.burst_cycles += 1
        self._capture_explains_burst(pods, assignment, state, static,
                                     node_table, sb.cycle_id, "burst")
        self._span_commit(sb, pods, static_version=version,
                          rounds=cycle_rounds)
        return bound

    def _pipeline_cycle(self, pods: Sequence[Pod]) -> int:
        """One pipelined burst cycle: encode-prepare of THIS burst on
        the host thread overlaps the retire (fetch + assume + bind
        enqueue) of the PREVIOUS burst, whose device step has been
        running since its own cycle dispatched it.  Returns pods
        assumed from the retired burst; this burst's own count is
        returned by the cycle that retires it.

        Ordering (the determinism contract, tests/test_pipeline.py):
        peers and the first-pod escape are finalized AFTER the
        previous burst's assume publishes its placements, and the
        snapshot is taken after the same point — exactly what a
        serial burst cycle would have seen."""
        from kubernetesnetawarescheduler_tpu.core.replay import (
            pad_stream,
            replay_stream_static,
        )

        n_real = -(-len(pods) // self.cfg.max_pods)
        sb = self._span_begin("pipelined")

        def _timed_prepare():
            t = time.perf_counter()
            prep = self.encoder.encode_stream_prepare(pods,
                                                      lenient=True)
            return prep, time.perf_counter() - t

        fut = self._encode_pool.submit(_timed_prepare)
        # Stage overlap: previous burst's retire runs while the encode
        # worker prepares this burst's arrays.
        bound = self._retire_inflight()
        prepared, encode_s = fut.result()
        sb.add_phase("encode", time.perf_counter() - encode_s,
                     encode_s)
        self.timer.record("encode", encode_s / n_real, count=n_real)
        t0 = time.perf_counter()
        stream = self.encoder.finalize_stream(prepared,
                                              self._peer_node)
        # Full burst shape for one stable XLA compile — same
        # reasoning as schedule_pods_burst.
        stream = pad_stream(stream,
                            self.burst_batches * self.cfg.max_pods)
        state, version = self.encoder.snapshot_versioned()
        node_table = self.encoder.node_table()
        self._emit_degraded_events()
        static = None
        with self._profile_step(sb.cycle_id):
            if self._sharded_burst is not None:
                out, with_stats = self._sharded_burst(state, stream)
            else:
                with_stats = self.method == "parallel"
                static = self._static_for(state, version)
                out = replay_stream_static(state, stream, static,
                                           self.cfg, self.method,
                                           with_stats=with_stats)
        # JAX async dispatch: the device step runs from here until
        # the fetch in _retire_inflight; "dispatch" records only the
        # host-side cost of getting it launched (finalize + snapshot
        # + trace/launch), the pipeline's exposed serial share.
        sb.add_phase("dispatch", t0, time.perf_counter() - t0)
        self.timer.record("dispatch",
                          (time.perf_counter() - t0) / n_real,
                          count=n_real)
        self._note_dispatch()
        self._pipe_inflight = (pods, out, with_stats, node_table,
                               n_real, time.perf_counter(),
                               state, static)
        self._pipe_span = (sb, version)
        self.burst_cycles += 1
        return bound

    def _retire_inflight(self) -> int:
        """Fetch the in-flight pipelined burst's assignments and run
        the assume/bind-enqueue tail.  No-op without one.  Usage is
        committed HERE — never at dispatch — so a crash between
        encode-ahead/dispatch and retire leaves no committed residue
        to double-commit after a checkpoint restore."""
        inflight = self._pipe_inflight
        if inflight is None:
            return 0
        self._pipe_inflight = None
        (pods, out, with_stats, node_table, n_real, t_dispatch,
         state, static) = inflight
        sb, span_version = (self._pipe_span
                            if self._pipe_span is not None
                            else (NULL_SPAN, None))
        self._pipe_span = None
        t0 = time.perf_counter()
        cycle_rounds = 0
        if with_stats:
            assignment_dev, _final_state, rounds_dev = out
            assignment = np.asarray(jax_block(assignment_dev))
            rounds = np.asarray(rounds_dev)
            cycle_rounds = int(rounds[:n_real].max()) if n_real else 0
            with self._round_lock:
                self.round_samples.extend(
                    int(r) for r in rounds[:n_real])
        else:
            assignment_dev, _final_state = out
            assignment = np.asarray(jax_block(assignment_dev))
        # The exposed device wait: whatever of the step did NOT
        # overlap host work since dispatch.  Feeds the same
        # score_assign percentile stream as the serial cycle.
        sb.add_phase("score_assign", t0, time.perf_counter() - t0)
        self.timer.record("score_assign",
                          (time.perf_counter() - t0) / n_real,
                          count=n_real)
        assignment = assignment[:len(pods)]
        t0 = time.perf_counter()
        bound = self._assume_and_enqueue(pods, assignment, node_table)
        sb.add_phase("bind", t0, time.perf_counter() - t0)
        self.timer.record("bind",
                          (time.perf_counter() - t0) / n_real,
                          count=n_real)
        self.timer.record("burst_wall",
                          time.perf_counter() - t_dispatch)
        self._capture_explains_burst(pods, assignment, state, static,
                                     node_table, sb.cycle_id,
                                     "pipelined")
        self._span_commit(sb, pods, static_version=span_version,
                          rounds=cycle_rounds)
        return bound

    def schedule_pods_multicycle(self, pods: Sequence[Pod]) -> int:
        """Serve up to ``multicycle`` batches as ONE persistent device
        program: encode the whole K-wave window once (global in-stream
        peer index space — waves must NOT be encoded separately, or
        cross-wave peers would miss earlier waves' placements), stage
        the waves through the device ring, and run one donated scan
        over all of them.  Waves retire asynchronously through
        :meth:`_retire_multicycle`; usage commits only at retire.

        Returns pods bound/assumed from the PREVIOUS window's retire
        plus any ring-overflow fallback; this window's own waves are
        counted by the cycles that retire them (the next window, the
        shallow-queue path, or flush_binds).  Placements are
        bit-identical to K sequential fused per-batch steps: the
        replay scan threads commits across waves exactly as
        sequential cycles would (tests/test_multicycle.py)."""
        from kubernetesnetawarescheduler_tpu.core.encode import (
            DeviceWaveRing,
            split_stream_waves,
        )
        from kubernetesnetawarescheduler_tpu.core.replay import (
            pad_stream,
            replay_stream_static,
        )

        # Previous window first: its placements must be published
        # before this window's encode resolves peers (the sequential
        # snapshot-ordering contract, same as the pipelined path).
        bound = self._retire_multicycle()
        k = self.multicycle
        cap = self.cfg.max_pods
        t_enc = time.perf_counter()
        stream = self.encoder.encode_stream(
            pods, node_of=self._peer_node, lenient=True)
        # Fixed K*cap window shape: one XLA compile per K (the burst
        # path's padding rationale — variable depths each pay a fresh
        # compile; masked pad waves cost ~nothing on device).
        stream = pad_stream(stream, k * cap)
        state, version = self.encoder.snapshot_versioned()
        node_table = self.encoder.node_table()
        encode_s = time.perf_counter() - t_enc
        self._emit_degraded_events()

        depth = int(getattr(self.cfg, "multicycle_queue_depth", k))
        ring = self._wave_ring
        if ring is None or ring.capacity != depth:
            ring = self._wave_ring = DeviceWaveRing(depth)
        waves = split_stream_waves(stream, cap)
        staged = 0
        for wave in waves:
            if not ring.push(wave):
                break
            staged += 1
        if staged < len(waves):
            self.multicycle_overflow_total += len(waves) - staged
        window = ring.pop_window()
        real_in_window = min(len(pods), staged * cap)
        n_live = max(1, -(-real_in_window // cap))
        # One span builder PER logical cycle, opened at dispatch and
        # committed at the retire seam (spans stay one-per-logical-
        # cycle; phase costs are amortized shares of the window's).
        sbs = [self._span_begin("multicycle") for _ in range(n_live)]
        for sb in sbs:
            sb.add_phase("encode", t_enc, encode_s / n_live)
        self.timer.record("encode", encode_s / n_live, count=n_live)
        t0 = time.perf_counter()
        with_stats = self.method == "parallel"
        static = self._static_for(state, version)
        with self._profile_step(sbs[0].cycle_id):
            out = replay_stream_static(state, window, static,
                                       self.cfg, self.method,
                                       with_stats=with_stats)
        self._note_dispatch()
        dispatch_s = time.perf_counter() - t0
        for sb in sbs:
            sb.add_phase("dispatch", t0, dispatch_s / n_live)
        shared = {"out": out, "with_stats": with_stats,
                  "fetched": None, "rounds": None, "n_live": n_live,
                  "state": state, "static": static,
                  "t_dispatch": time.perf_counter()}
        for j in range(n_live):
            a = j * cap
            self._mc_inflight.append(
                (sbs[j], list(pods[a:min(a + cap, real_in_window)]),
                 j, staged, shared, node_table, version))
        self.multicycle_windows += 1
        if len(pods) > staged * cap:
            # Ring overflow: waves past the device-queue depth fall
            # back to the per-cycle/burst dispatch path AFTER the
            # window retires, so their re-encode sees the window's
            # published placements — a mis-tuned depth degrades
            # amortization, never placements (counter above is the
            # observability seam).
            bound += self._retire_multicycle()
            leftover = list(pods[staged * cap:])
            if len(leftover) > cap and self.burst_batches > 1:
                bound += self.schedule_pods_burst(leftover)
            else:
                for a in range(0, len(leftover), cap):
                    bound += self.schedule_pods(leftover[a:a + cap])
        return bound

    def _retire_multicycle(self, max_waves: int | None = None) -> int:
        """Retire pending multicycle waves: fetch the window's device
        output ONCE (at the first retire), then per wave run the
        assume/bind tail and commit its span.  Usage lands HERE —
        never at dispatch — so a crash mid-window restores to the
        last retired cycle with no half-committed wave (checkpoint
        contract, tests/test_multicycle.py).  ``max_waves`` bounds how
        many waves retire this call (the mid-window checkpoint seam);
        default drains all.  Returns pods bound/assumed."""
        bound = 0
        retired = 0
        cap = self.cfg.max_pods
        shared = None
        while self._mc_inflight:
            if max_waves is not None and retired >= max_waves:
                break
            (sb, wave_pods, j, k_eff, shared, node_table,
             version) = self._mc_inflight.popleft()
            t0 = time.perf_counter()
            if shared["fetched"] is None:
                if shared["with_stats"]:
                    a_dev, _final, r_dev = shared["out"]
                    shared["fetched"] = np.asarray(jax_block(a_dev))
                    shared["rounds"] = np.asarray(r_dev)
                    with self._round_lock:
                        self.round_samples.extend(
                            int(r) for r in
                            shared["rounds"][:shared["n_live"]])
                else:
                    a_dev, _final = shared["out"]
                    shared["fetched"] = np.asarray(jax_block(a_dev))
                shared["out"] = None
                # The exposed device wait, amortized over the
                # window's logical cycles: the device-boundary score
                # latency the bench compares to the in-kernel number.
                self.timer.record(
                    "score_assign",
                    (time.perf_counter() - t0) / shared["n_live"],
                    count=shared["n_live"])
            sb.add_phase("score_assign", t0,
                         time.perf_counter() - t0)
            assignment = shared["fetched"][
                j * cap:j * cap + len(wave_pods)]
            rounds_j = 0
            if (shared["rounds"] is not None
                    and j < len(shared["rounds"])):
                rounds_j = int(shared["rounds"][j])
            t0 = time.perf_counter()
            if self.async_bind:
                bound += self._assume_and_enqueue(
                    wave_pods, assignment, node_table)
            else:
                bound += self._bind_all(wave_pods, assignment,
                                        node_table)
            sb.add_phase("bind", t0, time.perf_counter() - t0)
            self.timer.record("bind", time.perf_counter() - t0)
            self._retire_lag.append(float(j))
            self._capture_explains_burst(
                wave_pods, assignment, shared["state"],
                shared["static"], node_table, sb.cycle_id,
                "multicycle")
            self._span_commit(sb, wave_pods, static_version=version,
                              rounds=rounds_j, scan_window_k=k_eff,
                              retire_lag_cycles=j)
            self.multicycle_last_retired = sb.cycle_id
            retired += 1
        if retired and not self._mc_inflight and shared is not None:
            self.timer.record(
                "burst_wall",
                time.perf_counter() - shared["t_dispatch"])
        return bound

    def _cycle_inputs(self, sb, pods: Sequence[Pod]):
        """Encode half of a serial cycle: batch encode + atomic state
        snapshot + node table, degraded-constraint events emitted.

        Split out of :meth:`schedule_pods` (r15) so the fleet server
        can run the SAME host-side semantics per tenant, dispatch all
        tenants in ONE batched device call, then hand each tenant back
        to :meth:`_cycle_outputs` — host behavior identical to solo
        serving by construction."""
        with sb.phase("encode"), self.timer.phase("encode"):
            # Lenient: pods arrive from the watch (untrusted
            # manifests), and one pod with un-internable constraints
            # must degrade ITSELF (conservative bit directions +
            # a ConstraintDegraded event), not raise and take the
            # whole batch's cycle down with it.
            batch = self.encoder.encode_pods(
                pods, node_of=self._peer_node, lenient=True)
            # Atomic (state, version) pair — a separate version read
            # on either side of snapshot() can mispair them when an
            # ingest thread dirties state in between (the same hazard
            # the extender batcher documents), and the assign static
            # cache would then serve stale normalizers against fresh
            # state.
            state, static_version = self.encoder.snapshot_versioned()
            # Name/generation table captured WITH the snapshot: the
            # bind path resolves indices against this table, so a slot
            # freed+reused mid-cycle binds to the old (gone) name —
            # rejected upstream — instead of silently landing on the
            # slot's new tenant.
            node_table = self.encoder.node_table()
        self._emit_degraded_events()
        return batch, state, static_version, node_table

    def _cycle_outputs(self, sb, pods: Sequence[Pod], batch, state,
                       static, node_table, assignment: np.ndarray,
                       rounds: int, static_version: int, *,
                       donated: int = 0, donation_skipped: int = 1,
                       path: str = "serial") -> int:
        """Bind half of a serial cycle: bind/assume, explain capture,
        span commit.  The fleet server calls this per tenant after the
        shared batched dispatch (see :meth:`_cycle_inputs`)."""
        with sb.phase("bind"), self.timer.phase("bind"):
            if self.async_bind:
                bound = self._assume_and_enqueue(pods, assignment,
                                                 node_table)
            else:
                bound = self._bind_all(pods, assignment, node_table)
        self._capture_explains(pods, batch, assignment, state, static,
                               node_table, sb.cycle_id, path)
        self._span_commit(sb, pods, static_version=static_version,
                          rounds=rounds, donated=donated,
                          donation_skipped=donation_skipped)
        return bound

    def schedule_pods(self, pods: Sequence[Pod]) -> int:
        sb = self._span_begin("serial")
        batch, state, static_version, node_table = \
            self._cycle_inputs(sb, pods)
        static = None
        with sb.phase("score_assign"), self.timer.phase("score_assign"):
            stats = self.method == "parallel"
            # assign_greedy has no with_stats parameter — pass the kw
            # only when asking for it (stats implies parallel).
            kw = {"with_stats": True} if stats else {}
            with self._profile_step(sb.cycle_id):
                if self._assign_takes_static:
                    static = self._static_for(state, static_version)
                    out = self._assign(state, batch, self.cfg, static,
                                       **kw)
                else:
                    out = self._assign(state, batch, self.cfg, **kw)
                cycle_rounds = 0
                if stats:
                    assignment_dev, rounds = out
                    assignment = np.asarray(jax_block(assignment_dev))
                    cycle_rounds = int(rounds)
                    with self._round_lock:
                        self.round_samples.append(cycle_rounds)
                else:
                    assignment = np.asarray(jax_block(out))
                self._note_dispatch()
        return self._cycle_outputs(sb, pods, batch, state, static,
                                   node_table, assignment,
                                   cycle_rounds, static_version)

    def _static_for(self, state, version: int):
        """Version-keyed cache of the batch-invariant assign static
        (see __init__); ``version`` must come from the SAME
        ``snapshot_versioned`` call that produced ``state``.

        Refresh policy (the tentpole of the 5 ms Score() p99 work):

        * Current version -> return the cached value, no device work.
        * ``cfg.enable_async_static`` off (default): rebuild HERE, but
          delta-aware — the encoder's dirty descriptor usually reduces
          the O(N²) re-normalization to an O(|dirty|) patch that is
          bit-identical to the full rebuild.
        * Async on: hand the rebuild to a background worker and keep
          serving the previous static, UNLESS the staleness contract
          is breached (no static yet, more than
          ``static_max_versions_behind`` versions or
          ``static_max_staleness_s`` seconds behind) — then build
          synchronously so staleness stays bounded even if the worker
          wedges."""
        if self._static_version == version:
            if self.cfg.enable_async_static:
                self._staleness_samples.append(0.0)
            return self._static_val
        if not self.cfg.enable_async_static:
            self._static_rebuild(state, version)
            return self._static_val
        now = time.monotonic()
        with self._static_cv:
            if self._static_stale_since is None:
                self._static_stale_since = now
            behind = (version - self._static_version
                      if self._static_version is not None else None)
            staleness = now - self._static_stale_since
        if (self._static_val is None or behind is None
                or behind > self.cfg.static_max_versions_behind
                or staleness > self.cfg.static_max_staleness_s):
            self.static_sync_builds += 1
            self._static_rebuild(state, version)
            self._staleness_samples.append(0.0)
            return self._static_val
        self._ensure_static_worker()
        with self._static_cv:
            # Latest-wins: a newer snapshot supersedes any rebuild
            # still queued (the worker always builds toward the
            # freshest version it has seen).
            self._static_req = (state, version)
            self._static_cv.notify()
        self._staleness_samples.append(staleness)
        return self._static_val

    def _static_rebuild(self, state, version: int) -> None:
        """Build (delta-aware when possible) and publish the static
        for ``version`` on the calling thread."""
        from kubernetesnetawarescheduler_tpu.core.pallas_score import (
            compute_assign_static_incremental,
        )

        t0 = time.perf_counter()
        dirty = None
        if self.cfg.enable_delta_state and self._static_version is not None:
            # The descriptor may span past ``version`` if the encoder
            # moved again already; the extra indices just re-patch
            # values ``state`` already holds — still bit-identical.
            dirty = self.encoder.static_delta_since(self._static_version)
        static, ex = compute_assign_static_incremental(
            state, self.cfg, self._static_val, self._static_ex, dirty)
        with self._static_cv:
            # Version monotonicity: never replace a fresher static
            # (the sync-fallback path can overtake a queued rebuild).
            if (self._static_version is None
                    or version > self._static_version):
                self._static_val = static
                self._static_ex = ex
                self._static_version = version
                self._static_stale_since = None
        self.static_refresh_total += 1
        self._static_refresh_ms.append(
            (time.perf_counter() - t0) * 1e3)

    def _ensure_static_worker(self) -> None:
        t = self._static_thread
        if t is None or not t.is_alive():
            self._static_stop = False
            self._static_thread = threading.Thread(
                target=self._static_worker_loop,
                name="static-refresh", daemon=True)
            self._static_thread.start()

    def _static_worker_loop(self) -> None:
        while True:
            with self._static_cv:
                while self._static_req is None and not self._static_stop:
                    self._static_cv.wait(0.5)
                if self._static_stop:
                    return
                state, version = self._static_req
                self._static_req = None
            try:
                self._static_rebuild(state, version)
            except Exception:  # noqa: BLE001 — a wedged worker must
                # not kill serving: the staleness contract routes
                # batches to the synchronous fallback, which surfaces
                # the error on the serving thread.
                pass

    def stop_static_refresher(self, timeout: float | None = 10.0) -> None:
        """Stop the background static-refresh worker (shutdown path;
        idempotent, no-op when async refresh never ran)."""
        t = self._static_thread
        if t is None:
            return
        with self._static_cv:
            self._static_stop = True
            self._static_req = None
            self._static_cv.notify_all()
        t.join(timeout)
        self._static_thread = None

    def _schedule_gang(self, key: str, members: list[Pod]) -> int:
        """Jointly place and ATOMICALLY commit one complete gang.

        Score: two-pass joint placement (:func:`core.gang.place_gang`)
        — the normal batched assigner, then a re-score of every member
        row with the C[N, N]-derived co-placement bias, keeping the
        pass that wins the group objective.  Commit: assume-all (usage
        into the encoder up front, in-flight record for the
        checkpoint) then bind-all through the client's transactional
        ``bind_gang``; ANY member failure rolls back EVERY member.
        Returns members bound (the whole gang, or 0)."""
        comp = self.cfg.scheduler_name
        if len(members) > self.cfg.max_pods:
            # A gang wider than the batch shape cannot be scored
            # jointly in one dispatch: degrade LOUDLY to independent
            # placement rather than deadlock the job in the gate.
            from kubernetesnetawarescheduler_tpu.k8s.types import Event

            self.client.create_event(Event(
                message=(f"pod group {key} has {len(members)} members "
                         f"> max_pods={self.cfg.max_pods}; placed "
                         "independently (no all-or-nothing guarantee)"),
                reason="GangDegraded", involved_pod=members[0].name,
                namespace=members[0].namespace, component=comp,
                type="Warning"))
            total = 0
            for i in range(0, len(members), self.cfg.max_pods):
                total += self.schedule_pods(
                    members[i:i + self.cfg.max_pods])
            return total
        sb = self._span_begin("gang")
        with sb.phase("encode"), self.timer.phase("encode"):
            batch = self.encoder.encode_pods(
                members, node_of=self._peer_node, lenient=True)
            state, static_version = self.encoder.snapshot_versioned()
            node_table = self.encoder.node_table()
        self._emit_degraded_events()
        with sb.phase("score_assign"), self.timer.phase("score_assign"):
            if self._assign_takes_static:
                static = self._static_for(state, static_version)
                assign_fn = self._assign
            else:
                # Mesh path: serving_fns' closures take no static —
                # gang re-scoring needs the {"raw","ok"} seam, so fall
                # back to the single-device assigners for the (rare,
                # small) gang batches.
                static = None
                assign_fn = {"greedy": assign_greedy,
                             "parallel": assign_parallel}[self.method]
            # Elastic realizations (r17): when the gang declares
            # alternative shapes AND the feature is on, score every
            # declared realization and commit the winner; otherwise
            # the pre-r17 rigid path runs bit-identically.
            shapes = (gang_shapes_of(members)
                      if self.cfg.enable_gang_reshaping else ())
            shaped = len(shapes) > 1
            with self._profile_step(sb.cycle_id):
                if shaped:
                    assignment, chosen, shape_info = place_gang_shaped(
                        state, batch, self.cfg, static, assign_fn,
                        len(members), shapes)
                else:
                    assignment = place_gang(state, batch, self.cfg,
                                            static, assign_fn,
                                            len(members))
                    chosen, shape_info = len(members), None
            self._note_dispatch()
        commit_members = members
        surplus: list[Pod] = []
        if shaped and 0 < chosen < len(members):
            # A degraded realization commits the chosen PREFIX
            # all-or-nothing; the surplus members park (loudly) and
            # re-gate on the next wakeup/resync — the rebalancer's
            # regrow path restores the full shape when capacity
            # returns.
            commit_members = members[:chosen]
            surplus = members[chosen:]
        with sb.phase("bind"), self.timer.phase("bind"):
            bound = self._commit_gang(key, commit_members, assignment,
                                      node_table)
        if shaped and bound:
            self.encoder.note_gang_realization(key, len(commit_members),
                                               len(members))
        if surplus:
            comp_events = []
            if bound:
                self.gangs_shaped_degraded += 1
                why = (f"gang {key} realized degraded shape "
                       f"{chosen}/{len(members)} "
                       f"(declared family: "
                       f"{','.join(str(c) for c, _ in shapes)}); "
                       "member parked awaiting regrow")
            else:
                why = (f"gang {key}: no feasible placement at any "
                       "declared shape")
            for pod in surplus:
                comp_events.append(failed_event(pod, comp, why))
            self.client.create_events(comp_events)
            self.unschedulable += len(surplus)
            self._park_gang(surplus)
        # Explain records note the joint C-matrix pass: the per-node
        # decomposition is the INDEPENDENT score surface; the gang's
        # co-placement bias may have moved the winner off the
        # independent argmax, which is exactly what the marker flags.
        self._capture_explains(
            members, batch, assignment, state, static, node_table,
            sb.cycle_id, "gang",
            extra={"gang": {"key": key, "members": len(members),
                            "joint_placement": True,
                            "bound": bool(bound),
                            **({"realization": chosen,
                                "shape_info": shape_info}
                               if shaped else {})}})
        self._span_commit(sb, members, static_version=static_version)
        return bound

    def _commit_gang(self, key: str, members: list[Pod],
                     assignment: np.ndarray, node_table) -> int:
        """Assume-all-then-bind-all with full rollback (see
        :meth:`_schedule_gang`)."""
        comp = self.cfg.scheduler_name
        table_names, table_gens = node_table
        events: list = []
        idxs = [int(assignment[i]) for i in range(len(members))]
        feasible = all(i >= 0 for i in idxs)
        if feasible:
            # Any member slot whose generation moved (node vanished
            # mid-cycle) aborts the WHOLE gang before anything binds.
            feasible = all(
                self.encoder.slot_generation(i) == table_gens[i]
                for i in idxs)
        if not feasible:
            if self.decision_log is not None:
                for pod in members:
                    self.decision_log.append(pod.name, "")
            self.unschedulable += len(members)
            for pod in members:
                events.append(failed_event(
                    pod, comp,
                    f"gang {key}: no feasible all-or-nothing "
                    "placement"))
            self.client.create_events(events)
            if self.gangs is not None:
                self.gangs.note_rolled_back(key)
            self._park_gang(members)
            return 0
        names = [table_names[i] for i in idxs]
        if self.decision_log is not None:
            for pod, name in zip(members, names):
                self.decision_log.append(pod.name, name)
        # ---- assume all -------------------------------------------------
        fresh = [(p, i) for p, i in zip(members, idxs)
                 if not self.encoder.is_committed(p.uid)]
        self.encoder.commit_many([p for p, _ in fresh],
                                 [i for _, i in fresh])
        assumed = {p.uid for p, _ in fresh}
        self._assumed_uids |= assumed
        for pod, name in zip(members, names):
            self._publish_assumed_node(pod, name)
        if self.gangs is not None:
            self.gangs.note_assumed(key)
        self.encoder.note_gang_inflight(
            key, [[p.uid, p.namespace, p.name, n]
                  for p, n in zip(members, names)])
        # ---- bind all (transactional) -----------------------------------
        outcomes = self.client.bind_gang([
            Binding(pod_name=p.name, namespace=p.namespace,
                    node_name=n)
            for p, n in zip(members, names)])
        self.encoder.clear_gang_inflight(key)
        if all(o is None for o in outcomes):
            for pod, name in zip(members, names):
                events.append(scheduled_event(pod, name, comp))
            self.client.create_events(events)
            self.scheduled += len(members)
            self.gangs_bound += 1
            if self.gangs is not None:
                self.gangs.note_bound(key)
            if self._bind_retries:
                for pod in members:
                    self._bind_retries.pop(
                        f"{pod.namespace}/{pod.name}", None)
            return len(members)
        # ---- rollback all ----------------------------------------------
        self.bind_failures += sum(1 for o in outcomes if o is not None)
        for pod, name in zip(members, names):
            if pod.uid in assumed:
                self.encoder.release(pod, name, rollback=True)
            self._assumed_uids.discard(pod.uid)
            self._drop_assumed_node(pod)
        self.gangs_rolled_back += 1
        if self.gangs is not None:
            self.gangs.note_rolled_back(key)
        first = next(o for o in outcomes if o is not None)
        for pod in members:
            events.append(failed_event(
                pod, comp, f"gang {key} rolled back: {first}"))
        self.client.create_events(events)
        # Park for the unblocked-gang wakeup (node add / rollback):
        # re-delivery re-gates the members, and the gang retries as a
        # whole.  Members the API server no longer knows stay parked
        # harmlessly (their deletion purges them via _on_pod_gone).
        self._park_gang(members)
        return 0

    def _park_gang(self, members: list[Pod]) -> None:
        evicted_events: list = []
        for pod in members:
            evicted = self._park_pod(pod)
            if evicted is not None:
                evicted_events.append(failed_event(
                    evicted, self.cfg.scheduler_name,
                    "dropped from the parked-pod backlog (capacity "
                    "1024 exceeded); recovered by the next resync"))
        if evicted_events:
            self.client.create_events(evicted_events)

    def _flush_gang_timeouts(self) -> None:
        """Expire incomplete gangs whose gate deadline passed: emit a
        FailedScheduling event per stranded member and return them to
        the queue (they re-gate with a fresh deadline on the next
        pop — kube co-scheduling's retry shape).

        Elastic gangs (r17) degrade instead: when reshaping is on and
        the arrived members cover some DECLARED smaller shape, the
        gate expiring means the missing members are not coming (a
        zonal outage deleted them, a controller is slow) — the gang
        schedules at the best viable realization now and the
        rebalancer's regrow path restores the full shape when the
        stragglers re-deliver."""
        if self.gangs is None:
            return
        comp = self.cfg.scheduler_name
        for key, members in self.gangs.flush_timeouts():
            declared = {int(c) for pod in members
                        for c, _ in (getattr(pod, "gang_shapes", ())
                                     or ())}
            if (self.cfg.enable_gang_reshaping and declared
                    and min(declared) <= len(members)):
                self.client.create_events([
                    failed_event(
                        pod, comp,
                        f"gang {key} timed out waiting for members "
                        f"({len(members)} arrived); degrading to the "
                        "declared elastic family")
                    for pod in members])
                self._schedule_gang(key, members)
                continue
            self.client.create_events([
                failed_event(
                    pod, comp,
                    f"gang {key} timed out waiting for members "
                    f"({len(members)} arrived)")
                for pod in members])
            for pod in members:
                self.queue.push(pod)  # full queue drops; resync heals

    def _emit_degraded_events(self) -> None:
        """Per-pod Warning events for constraint degradation on
        interner overflow (encode.Encoder._constraint_bits): the
        aggregate overflow counter says it happened; these say to WHOM
        — in particular a dropped anti-affinity group silently stops
        being enforced for that pod."""
        degraded = self.encoder.pop_degraded()
        if not degraded:
            return
        from kubernetesnetawarescheduler_tpu.k8s.types import Event

        self.client.create_events([
            Event(
                message=(f"{count} constraint key(s) dropped "
                         "(interner capacity or unrepresentable "
                         "terms); affinity/anti-affinity may not be "
                         "fully enforced"
                         + (": " + "; ".join(detail) if detail else "")),
                reason="ConstraintDegraded", involved_pod=name,
                namespace=namespace,
                component=self.cfg.scheduler_name, type="Warning")
            for namespace, name, count, detail in degraded])

    def _publish_assumed_node(self, pod: Pod, node_name: str) -> None:
        """Record an assumed placement under the qualified name and —
        when unambiguous — the bare alias.  On a cross-namespace
        bare-name collision the bare alias is POISONED (dropped, and
        held dropped by _bare_ns' refcount) instead of last-writer-
        wins: an annotation peer's bare reference must never silently
        resolve to the other namespace's node; the inherently
        ambiguous lookup falls through to the client, whose own
        bare-name semantics then apply.  Qualified references always
        resolve exactly."""
        entry = (pod.namespace, node_name)
        with self._alias_lock:
            nss = self._bare_ns.setdefault(pod.name, set())
            nss.add(pod.namespace)
            if len(nss) == 1:
                self._assumed_node[pod.name] = entry
            else:
                self._assumed_node.pop(pod.name, None)
            self._assumed_node[f"{pod.namespace}/{pod.name}"] = entry

    def _drop_assumed_node(self, pod: Pod) -> None:
        """Remove a pod's assumed-placement entries.  The bare-name
        alias is dropped only when this pod's namespace owns it; when
        the drop resolves a cross-namespace collision down to one
        surviving namespace, the survivor's bare alias is restored
        (see _bare_ns in __init__)."""
        with self._alias_lock:
            self._assumed_node.pop(f"{pod.namespace}/{pod.name}", None)
            nss = self._bare_ns.get(pod.name)
            if nss is None:
                # Never assumed (or already fully dropped): nothing
                # beyond the owner-checked bare cleanup below.
                entry = self._assumed_node.get(pod.name)
                if entry is not None and entry[0] == pod.namespace:
                    self._assumed_node.pop(pod.name, None)
                return
            nss.discard(pod.namespace)
            if not nss:
                del self._bare_ns[pod.name]
                entry = self._assumed_node.get(pod.name)
                if entry is not None and entry[0] == pod.namespace:
                    self._assumed_node.pop(pod.name, None)
            elif len(nss) == 1:
                # Collision resolved: the survivor becomes bare-
                # addressable again (its qualified entry is live iff
                # its assumption still is).
                ns = next(iter(nss))
                surv = self._assumed_node.get(f"{ns}/{pod.name}")
                if surv is not None:
                    self._assumed_node[pod.name] = surv
                else:
                    self._assumed_node.pop(pod.name, None)

    def _peer_node(self, pod_name: str) -> str:
        # The scheduler's own assumed cache first (assume-then-bind:
        # a decided-but-unconfirmed peer is already placed from the
        # scorer's point of view — and consulting the API-server view
        # here made peer resolution race the bind worker).
        entry = self._assumed_node.get(pod_name)
        if entry is not None:
            return entry[1]
        try:
            return self.client.node_of(pod_name)
        except KeyError:
            return ""  # peer not known to the API server (yet)

    def _try_preempt(self, pod: Pod, events: list) -> bool:
        """Attempt to make room for an unschedulable pod by evicting
        strictly-lower-priority pods (core/preempt.py).  Returns True
        when victims were evicted and the pod was requeued; the caller
        then skips the FailedScheduling path for this cycle."""
        from kubernetesnetawarescheduler_tpu.core.preempt import (
            execute_preemption,
            plan_preemption,
        )

        attempts = self._preempt_attempts.get(pod.uid, 0)
        if attempts >= self.cfg.max_preemption_attempts:
            # Budget exhausted: keep the counter (dropping it would let
            # the periodic resync re-arm eviction forever for a pod
            # preemption cannot help).  The entry is cleared when the
            # pod finally schedules or is deleted.
            return False
        plan = plan_preemption(self.encoder, pod)
        if plan is None or not plan.victims:
            return False
        self._preempt_attempts[pod.uid] = attempts + 1
        done = execute_preemption(self.client, self.encoder, plan,
                                  self.cfg.preemption_grace_s)
        if not done:
            return False
        self.preemptions += len(done)
        from kubernetesnetawarescheduler_tpu.k8s.types import Event

        for v in done:
            events.append(Event(
                message=(f"Preempted by {pod.namespace}/{pod.name} "
                         f"(priority {pod.priority:g} > {v.priority:g})"),
                reason="Preempted", involved_pod=v.name,
                namespace=v.namespace,
                component=self.cfg.scheduler_name, type="Warning"))
        # Reserve the target (nominatedNodeName) and hold the
        # preemptor until every victim's deletion is confirmed through
        # the watch.  The wait entry is published BEFORE checking for
        # already-landed releases so a watch event racing this thread
        # can never slip between check and registration.
        self.encoder.nominate(pod.uid, plan.node_name, pod.requests)
        outstanding = {v.uid for v in done}
        with self._preempt_lock:
            self._awaiting_preemption[pod.uid] = (
                pod, outstanding,
                time.monotonic() + self.cfg.preemption_wait_s)
        with self._preempt_lock:
            for uid in list(outstanding):
                if not self.encoder.is_committed(uid):
                    # Release already landed (synchronous client
                    # fanout, or the watch beat us here).
                    outstanding.discard(uid)
            drained = (not outstanding
                       and pod.uid in self._awaiting_preemption)
            if drained:
                del self._awaiting_preemption[pod.uid]
        if drained and not self.queue.push(pod):
            # Queue full: refund the attempt (the freed space means
            # the next resync delivery likely schedules without
            # another eviction), drop the reservation, and fall
            # through to FailedScheduling so the state is visible.
            self._preempt_attempts[pod.uid] = attempts
            self.encoder._drop_nomination(pod.uid)
            return False
        return True

    def _requeue_transient(self, pod: Pod, exc: Exception,
                           events: list, comp: str) -> None:
        """Requeue a pod whose bind failed transiently, with a retry
        budget so it cannot cycle forever."""
        self.bind_failures += 1
        key = f"{pod.namespace}/{pod.name}"
        tries = self._bind_retries.get(key, 0) + 1
        self._bind_retries[key] = tries
        if tries <= self.max_bind_retries:
            self.queue.push(pod)
        else:
            self._bind_retries.pop(key, None)
            events.append(failed_event(
                pod, comp,
                f"bind failed after {tries - 1} retries: {exc}"))

    def _bound_where(self, pod: Pod) -> str:
        """Best-effort: which node (if any) the API server says the
        pod is bound to.  Used to heal 409s on the bind path."""
        try:
            return self.client.node_of(f"{pod.namespace}/{pod.name}")
        except KeyError:
            try:
                return self.client.node_of(pod.name)
            except KeyError:
                return ""

    def _bind_all(self, pods: Sequence[Pod],
                  assignment: np.ndarray,
                  node_table: tuple[list[str], list[int]] | None = None
                  ) -> int:
        """Bind a batch: one ``bind_many`` round-trip, batched events,
        batched usage commit — per-pod work only on the error paths.

        Semantically identical to binding pod-by-pod (the reference's
        shape, scheduler.go:196-233): per-pod outcomes, permanent
        rejections dropped with an event, transient errors requeued
        with a retry budget.  ``node_table`` is the (names, generations)
        snapshot taken with the cluster snapshot; commits are dropped
        for slots whose generation moved (node removed mid-cycle)."""
        comp = self.cfg.scheduler_name
        if node_table is None:
            node_table = self.encoder.node_table()
        table_names, table_gens = node_table
        events: list = []
        bindable, node_idxs, names = self._plan_bind(
            pods, assignment, table_names, events, comp)
        return self._finish_bind(bindable, node_idxs, names, table_gens,
                                 events, comp, assumed=None)

    def _plan_bind(self, pods: Sequence[Pod], assignment: np.ndarray,
                   table_names: list, events: list, comp: str):
        """Network-free half of a bind pass: per-pod decision-log
        entries, the preemption/unschedulable path for kernel
        rejections, and the (pod, node index, node name) triples worth
        sending to the API server."""
        bindable: list[Pod] = []
        node_idxs: list[int] = []
        names: list[str] = []
        for i, pod in enumerate(pods):
            idx = int(assignment[i])
            if idx < 0:
                if self.encoder.committed_node(pod.uid) is not None:
                    # Re-delivered pod whose usage is already in the
                    # ledger (watch replay, resync, relist audit): it
                    # is bound, not unschedulable — its OWN usage is
                    # what the re-score tripped over.  Logging "" /
                    # emitting FailedScheduling / parking it here
                    # would contradict the ledger and the apiserver.
                    continue
                if self.decision_log is not None:
                    self.decision_log.append(pod.name, "")
                if self.cfg.enable_preemption and \
                        self._try_preempt(pod, events):
                    continue
                self.unschedulable += 1
                events.append(failed_event(pod, comp, "no feasible node"))
                # Assume-then-bind: an "unschedulable" verdict may
                # rest on capacity an UNCONFIRMED assumption holds —
                # park the pod so a later rollback (which frees that
                # capacity) retries it instead of leaving it to the
                # slow periodic resync.  kube-scheduler's own
                # unschedulable-queue flush on cluster events.
                if self.async_bind:
                    evicted = self._park_pod(pod)
                    if evicted is not None:
                        events.append(failed_event(
                            evicted, comp,
                            "dropped from the parked-pod backlog "
                            "(capacity 1024 exceeded); recovered by "
                            "the next resync"))
                continue
            bindable.append(pod)
            node_idxs.append(idx)
            names.append(table_names[idx])
        self._redirect_committed(bindable, node_idxs, names)
        # Decision-log AFTER the redirect: for an already-committed pod
        # the ledger's node is the decision that actually binds — the
        # re-scored target would record a placement that never happens
        # (tools/state_audit.py cross-checks exactly this agreement).
        if self.decision_log is not None:
            for pod, name in zip(bindable, names):
                self.decision_log.append(pod.name, name)
        return bindable, node_idxs, names

    def _redirect_committed(self, bindable: list, node_idxs: list,
                            names: list) -> None:
        """Rewrite bind targets for pods whose usage is ALREADY in
        the ledger to the ledger's recorded node.  The assume for
        such a pod happened before (earlier cycle, or a previous
        process life via checkpoint restore) against a snapshot that
        did NOT contain its own usage; re-scoring it now sees that
        usage and can pick a different node — binding there would
        strand the recorded usage (ledger says node A, server says
        node B).  The ledger is authoritative for committed pods."""
        for j, pod in enumerate(bindable):
            where = self.encoder.committed_node(pod.uid)
            if where is None or where == names[j]:
                continue
            try:
                ridx = self.encoder.node_index(where)
            except KeyError:
                # Recorded node left the cluster; the scored target
                # stands and node-reconcile releases the stale record.
                continue
            names[j] = where
            node_idxs[j] = ridx
            self.binds_redirected += 1

    def _finish_bind(self, bindable: list, node_idxs: list, names: list,
                     table_gens: list, events: list, comp: str,
                     assumed: set | None) -> int:
        """Network half of a bind pass: ``bind_many`` plus per-pod
        outcome handling.  ``assumed is None`` is the synchronous
        cycle — successes are committed here (generation-guarded).
        Otherwise ``assumed`` holds the uids whose usage the cycle
        already committed at assume time: successes need no commit,
        and every failure of an assumed pod ROLLS BACK via the
        ledger-driven ``encoder.release`` before the usual
        event/requeue handling."""
        outcomes = self.client.bind_many([
            Binding(pod_name=pod.name, namespace=pod.namespace,
                    node_name=name)
            for pod, name in zip(bindable, names)])

        ok_pods: list[Pod] = []
        ok_idxs: list[int] = []
        adopted = 0
        for pod, idx, name, exc in zip(bindable, node_idxs, names,
                                       outcomes):
            if exc is None:
                ok_pods.append(pod)
                ok_idxs.append(idx)
                events.append(scheduled_event(pod, name, comp))
            elif isinstance(exc, (KeyError, ValueError)):
                # "Already bound" conflicts can be OUR bind succeeding
                # without us seeing the response (connection dropped
                # mid-batch, duplicate queue delivery): if the pod
                # landed on the node we chose, it IS scheduled —
                # account it, don't report failure.
                where = (self._bound_where(pod)
                         if isinstance(exc, ValueError) else None)
                if where == name:
                    if self.encoder.is_committed(pod.uid) and \
                            (assumed is None or
                             pod.uid not in assumed):
                        # Duplicate delivery of a pod we already bound
                        # AND accounted: healing it again would inflate
                        # the scheduled counter and emit a second
                        # "Scheduled" event (commit_many dedups the
                        # ledger, but counters/events are not
                        # idempotent).  The assume path filters
                        # same-process duplicates before the network
                        # (_assumed_uids); cross-restart duplicates
                        # reach here NOT in `assumed` (already in the
                        # restored ledger, so excluded from the
                        # assume set) and are skipped the same way.
                        continue
                    ok_pods.append(pod)
                    ok_idxs.append(idx)
                    events.append(scheduled_event(pod, name, comp))
                    continue
                if where == "":
                    # Conflict but our view doesn't know where the pod
                    # sits yet (watch event still in flight): treat as
                    # transient so the retry re-checks once the cache
                    # catches up, instead of dropping a pod that may
                    # be running on the node we chose.
                    self._rollback_assumed(pod, name, assumed)
                    self._requeue_transient(pod, exc, events, comp)
                    continue
                # The pod IS bound, just not where this attempt chose
                # — often our own earlier bind whose acknowledgement
                # was lost (connection reset after the server applied
                # it), retried after intervening commits shifted the
                # placement.  Adopt the server's truth into the ledger
                # instead of dropping it: an unaccounted running pod
                # would overload its node forever (and the usage
                # ledger must reconverge to server truth after a
                # fault clears).
                self._rollback_assumed(pod, name, assumed)
                widx = None
                try:
                    widx = self.encoder.node_index(where)
                except KeyError:
                    pass
                if widx is not None and \
                        not self.encoder.is_committed(pod.uid):
                    self.encoder.commit_many([pod], [widx])
                    adopted += 1
                    self.binds_adopted += 1
                    events.append(scheduled_event(pod, where, comp))
                    self._bind_retries.pop(
                        f"{pod.namespace}/{pod.name}", None)
                    continue
                self.bind_failures += 1
                events.append(failed_event(
                    pod, comp, f"bind rejected: {exc}"))
            else:
                # Transient API error: requeue with a retry budget
                # instead of stranding the pod as Pending forever.
                self._rollback_assumed(pod, name, assumed)
                self._requeue_transient(pod, exc, events, comp)

        if self._bind_retries:
            for pod in ok_pods:
                self._bind_retries.pop(f"{pod.namespace}/{pod.name}", None)
        if self._preempt_attempts:
            for pod in ok_pods:
                self._preempt_attempts.pop(pod.uid, None)
        if assumed is None:
            # Drop commits whose slot was freed (and possibly reused)
            # since the snapshot: the node is gone, its pods are being
            # garbage-collected, and booking usage onto the slot's new
            # tenant would corrupt accounting.
            fresh = [(pod, idx) for pod, idx in zip(ok_pods, ok_idxs)
                     if self.encoder.slot_generation(idx) ==
                     table_gens[idx]]
            self.encoder.commit_many([p for p, _ in fresh],
                                     [i for _, i in fresh])
        self.client.create_events(events)
        self.scheduled += len(ok_pods) + adopted
        return len(ok_pods) + adopted

    def _rollback_assumed(self, pod: Pod, name: str,
                          assumed: set | None) -> None:
        """Reverse an assume-time commit for a pod whose bind failed
        (assume-then-bind cycle only; no-op for the sync path and for
        pods that were never assumed, e.g. stale-generation slots).
        ``rollback=True``: if the record is already gone (node removal
        raced the bind), do NOT plant an early-release marker — it
        would cancel the pod's next commit after the requeue."""
        if assumed is not None and pod.uid in assumed:
            # Release BEFORE discarding from _assumed_uids: the other
            # order opens a window where a concurrent duplicate
            # delivery in _assume_and_enqueue sees "not assumed" yet
            # "still committed" — it would skip its own assume-commit
            # while this release erases the usage underneath it.
            self.encoder.release(pod, name, rollback=True)
            self._assumed_uids.discard(pod.uid)
            self._drop_assumed_node(pod)
            # The rollback freed assumed capacity: retry pods the
            # kernel rejected while it was held.
            self._requeue_parked()

    def _requeue_parked(self) -> None:
        """Requeue every parked unschedulable pod (called when
        capacity appears: an assumed-bind rollback or a new node).

        Pushes happen UNDER the lock so a concurrent _on_pod_gone
        cannot miss a drained-but-unpushed pod and revive a deletion
        (queue.push is non-blocking — a full queue drops — and takes
        no lock that ever waits on _parked_lock, so the nesting cannot
        deadlock)."""
        with self._parked_lock:
            while self._unsched_parked:
                parked = self._unsched_parked.popleft()
                self._parked_uids.discard(parked.uid)
                self.queue.push(parked)  # full queue drops; resync heals

    def _park_pod(self, pod: Pod) -> Pod | None:
        """Park one unschedulable pod on the bounded backlog.  Returns
        the pod EVICTED to make room when the deque was full (callers
        emit its FailedScheduling event outside the lock) — the silent
        ``deque(maxlen=...)`` eviction used to lose the oldest parked
        pod with no trace (recovered only by a later resync, and never
        counted)."""
        evicted: Pod | None = None
        with self._parked_lock:
            if pod.uid in self._parked_uids:
                return None
            maxlen = self._unsched_parked.maxlen
            if maxlen is not None and \
                    len(self._unsched_parked) >= maxlen:
                evicted = self._unsched_parked.popleft()
                self._parked_uids.discard(evicted.uid)
                self.parked_dropped += 1
            self._unsched_parked.append(pod)
            self._parked_uids.add(pod.uid)
        return evicted

    # -- degraded mode (breaker-open bind parking) ---------------------

    def _dispatch_bind(self, item: tuple) -> None:
        """Hand one assumed bind batch to the bind worker — unless the
        breaker is OPEN (degraded mode) or older parked batches exist
        (FIFO: a fresh batch must never overtake the parked backlog),
        in which case the batch parks.  Usage is committed at assume
        time either way, so parking changes WHEN the API server sees
        the binds, never what later cycles score against — the
        no-re-ordering-vs-serial-oracle contract."""
        breaker = self.breaker
        if breaker is not None:
            with self._parked_lock:
                if breaker.state == "open" or self._parked_binds:
                    self._parked_binds.append(item)
                    self.binds_parked_total += len(item[0])
                    return
        self._bind_q.put(item)

    def _drain_parked_binds(self) -> int:
        """Release parked bind batches per breaker state: none while
        OPEN, ONE probe batch per call while HALF-OPEN (its outcome
        closes or re-opens the breaker), everything FIFO once CLOSED.
        Runs on the cycle thread; batches drain through the normal
        bind worker with unchanged retire/rollback semantics."""
        breaker = self.breaker
        released = 0
        while True:
            state = "closed" if breaker is None else breaker.state
            if state == "open":
                break
            with self._parked_lock:
                if not self._parked_binds:
                    break
                item = self._parked_binds.popleft()
            self._bind_q.put(item)
            released += 1
            if state == "half_open":
                break  # one probe; its outcome decides the rest
        return released

    @property
    def degraded(self) -> bool:
        """True while the control-plane breaker is open (binds parked,
        scoring still live) — the /healthz // readyz signal."""
        breaker = self.breaker
        return breaker is not None and breaker.state == "open"

    # -- watch-gap relist audit ---------------------------------------

    def _on_watch_gap(self, reason: str = "") -> None:
        """Watch-thread callback: a stream could not resume from its
        resourceVersion, so events may be lost.  Arms a relist audit
        for the CYCLE thread — relisting inline here would hang the
        watch thread on the same browned-out server that caused the
        gap."""
        self.watch_gaps += 1
        self._relist_needed = True

    def relist_audit(self) -> int:
        """Full relist after a watch gap: diff informer/encoder state
        against the server and repair the drift — nodes added or
        removed while the stream was dark, pending pods never
        delivered, ledger entries for pods that vanished.  Emits one
        summary repair event when anything moved.  A failing listing
        re-arms the audit (the gap is not healed until the server
        answers a full relist)."""
        self._relist_needed = False
        repairs = 0
        complete = True
        listed_at = time.monotonic()
        try:
            server_nodes = self.client.list_nodes()
        except Exception:  # noqa: BLE001 — server still dark: retry
            self._relist_needed = True
            return 0
        fresh_nodes = 0
        for node in server_nodes:
            try:
                self.encoder.node_index(node.name)
            except KeyError:
                fresh_nodes += 1
            # Upsert; genuinely new nodes also wake parked pods
            # (missed node-ADDED is exactly a gap symptom).
            self._on_node(node)
        repairs += fresh_nodes
        repairs += self.encoder.reconcile_nodes(
            [n.name for n in server_nodes], listed_at)
        # The informer's own node cache misses deletions too (it only
        # grows via watch events): prune ghosts against the same
        # authoritative listing.
        repairs += self.informer.reconcile_nodes(
            [n.name for n in server_nodes])
        try:
            repairs += self.informer.resync()
        except Exception:  # noqa: BLE001 — partial audit: re-arm
            self._relist_needed = True
            complete = False
        try:
            repairs += self.reconcile_usage()
        except Exception:  # noqa: BLE001 — partial audit: re-arm
            self._relist_needed = True
            complete = False
        self.relists += 1
        self.relist_repairs += repairs
        if repairs or not complete:
            from kubernetesnetawarescheduler_tpu.k8s.types import Event

            self.client.create_event(Event(
                message=(f"watch gap: relist audit repaired {repairs} "
                         "drift item(s)"
                         + ("" if complete
                            else "; audit incomplete, re-armed")),
                reason="WatchGapRelist",
                involved_pod=self.cfg.scheduler_name,
                namespace="default",
                component=self.cfg.scheduler_name, type="Warning"))
        return repairs

    def _assume_and_enqueue(self, pods: Sequence[Pod],
                            assignment: np.ndarray,
                            node_table: tuple[list[str], list[int]]
                            ) -> int:
        """Assume-then-bind cycle tail (kube's cache pattern): commit
        fresh placements into the encoder NOW so the next cycle's
        snapshot sees them, then queue the network half for the bind
        worker.  Returns the number of pods assumed; bind
        confirmations update ``scheduled`` asynchronously
        (``flush_binds`` drains)."""
        if self._bind_worker_err:
            raise self._bind_worker_err[0]
        comp = self.cfg.scheduler_name
        table_names, table_gens = node_table
        events: list = []
        bindable, node_idxs, names = self._plan_bind(
            pods, assignment, table_names, events, comp)
        keep: list[tuple[Pod, int, str]] = []
        for pod, idx, name in zip(bindable, node_idxs, names):
            if pod.uid in self._assumed_uids:
                # Duplicate queue delivery of a pod THIS process
                # already assumed: the sync path heals this on the
                # 409; here it can be dropped before the network even
                # sees it.  (Process-local on purpose — see __init__.)
                continue
            keep.append((pod, idx, name))
        fresh = [(pod, idx) for pod, idx, _ in keep
                 if self.encoder.slot_generation(idx) == table_gens[idx]
                 # A pod already in the (possibly checkpoint-restored)
                 # ledger needs no assume-commit, and must NOT enter
                 # this cycle's `assumed` set: a cross-restart
                 # duplicate delivery then heals through the 409 path
                 # below without inflating counters/events (the
                 # process-local _assumed_uids filter cannot see it).
                 and not self.encoder.is_committed(pod.uid)]
        self.encoder.commit_many([p for p, _ in fresh],
                                 [i for _, i in fresh])
        assumed = {p.uid for p, _ in fresh}
        self._assumed_uids |= assumed
        for pod, idx, name in keep:
            if self.encoder.is_committed(pod.uid):
                # Under BOTH the bare and namespace-qualified names:
                # KubeClient peer references arrive qualified
                # ("ns/name", kubeclient pod_from_json), annotation
                # peers and the fake cluster use bare names — the
                # same dual indexing the stream encode uses.
                # Committed-but-not-assumed pods (checkpoint-restored
                # ledger entries, redirected to their recorded node by
                # _plan_bind) publish too: peers must resolve against
                # the ledger's placement, not race the bind worker
                # through the server-truth fallback.
                self._publish_assumed_node(pod, name)
        self._dispatch_bind(([p for p, _, _ in keep],
                             [i for _, i, _ in keep],
                             [n for _, _, n in keep],
                             table_gens, events, comp, assumed))
        return len(fresh)

    def _merge_bind_items(self, items: list[tuple]) -> tuple:
        """Coalesce several queued bind batches into ONE fanout item.
        Safe only in assume mode: every real queue item carries its
        ``assumed`` uid set (never None — only the shutdown sentinel
        is), and ``_finish_bind`` ignores ``table_gens`` entirely when
        ``assumed`` is a set, so concatenating the keep lists, merging
        the events, and unioning the assumed sets loses nothing.
        Merged bindings are re-grouped by (node, namespace) so
        adjacent binds to one node land together in the client
        fanout — the per-node/namespace batching window."""
        keep_p: list = []
        keep_i: list = []
        keep_n: list = []
        events: list = []
        assumed: set = set()
        for it in items:
            keep_p.extend(it[0])
            keep_i.extend(it[1])
            keep_n.extend(it[2])
            events.extend(it[4])
            assumed |= it[6]
        order = sorted(range(len(keep_p)),
                       key=lambda x: (keep_n[x],
                                      keep_p[x].namespace))
        self.bind_coalesced_total += len(items) - 1
        return ([keep_p[x] for x in order],
                [keep_i[x] for x in order],
                [keep_n[x] for x in order],
                items[0][3], events, items[0][5], assumed)

    def _bind_worker_main(self) -> None:
        import queue as queue_mod

        window = max(1, int(getattr(self.cfg, "bind_coalesce_window",
                                    1)))
        while True:
            item = self._bind_q.get()
            if item is None:
                self._bind_q.task_done()
                return
            # Coalesce: drain up to window-1 already-queued batches
            # into this fanout (window=1 = off, the pre-r16 shape).
            items = [item]
            while len(items) < window:
                try:
                    extra = self._bind_q.get_nowait()
                except queue_mod.Empty:
                    break
                if extra is None:
                    # A shutdown sentinel belongs to a BLOCKING get
                    # (each worker consumes exactly one) — recycle it
                    # to the back of the queue, accounting-exact.
                    self._bind_q.task_done()
                    self._bind_q.put(None)
                    break
                items.append(extra)
            try:
                merged = (items[0] if len(items) == 1
                          else self._merge_bind_items(items))
                keep_p, keep_i, keep_n, gens, events, comp, assumed = \
                    merged
                with self._bind_inflight_lock:
                    self.bind_inflight += 1
                    self.bind_inflight_peak = max(
                        self.bind_inflight_peak, self.bind_inflight)
                try:
                    with self.timer.phase("bind_net"):
                        self._finish_bind(keep_p, keep_i, keep_n,
                                          gens, events, comp, assumed)
                finally:
                    with self._bind_inflight_lock:
                        self.bind_inflight -= 1
            except BaseException as exc:  # noqa: BLE001 — surfaced on
                # the next cycle / flush; a dead worker must fail the
                # serving loop loudly, not strand assumed pods.
                self._bind_worker_err.append(exc)
            finally:
                # One task_done PER QUEUE ITEM — flush_binds polls
                # unfinished_tasks, which must reach zero exactly when
                # every enqueued batch (coalesced or not) completed.
                for _ in items:
                    self._bind_q.task_done()

    def flush_binds(self, timeout: float | None = None) -> None:
        """Block until every queued bind batch has been processed
        (assume-then-bind mode; no-op otherwise), then re-raise the
        first worker error if one occurred.  Call before reading
        bind-dependent state (checkpoints, tests, shutdown).

        Pipelined/multicycle mode: retires any in-flight burst or
        multicycle window first — their assumes must land before the
        queue can be considered drained.  (Same cycle-thread
        ownership contract as run_once.)"""
        if self._mc_inflight:
            self._retire_multicycle()
        if self._pipe_inflight is not None:
            self._retire_inflight()
        if self._bind_q is None:
            return
        if self._parked_binds:
            # A recovered breaker releases the parked backlog here too
            # (shutdown/checkpoint callers flush without cycling); an
            # OPEN breaker keeps it parked — degraded state is not
            # "drained", and the checkpoint carries the assumes.
            self._drain_parked_binds()
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while self._bind_q.unfinished_tasks:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"bind queue not drained within {timeout}s")
            time.sleep(0.002)
        if self._bind_worker_err:
            raise self._bind_worker_err[0]

    def multicycle_meta(self) -> dict:
        """Checkpoint provenance for the multi-cycle window (r16):
        stamped into checkpoint meta via ``extra_meta`` so a restore
        can name the cycle it lands on.  Usage commits only at retire,
        so ``waves_inflight`` waves are NOT in the ledger — a restore
        resumes from ``last_retired_cycle`` and the unretired waves'
        pods re-arrive Pending through the informer resync."""
        return {
            "k": int(self.multicycle),
            "waves_inflight": len(self._mc_inflight),
            "last_retired_cycle": int(self.multicycle_last_retired),
        }

    def stop_bind_worker(self, timeout: float | None = 30.0) -> None:
        """Drain outstanding binds and stop the worker (shutdown
        path; the loop cannot schedule in async mode afterwards)."""
        self.stop_static_refresher()
        if self._encode_pool is not None:
            self._encode_pool.shutdown(wait=True)
            self._encode_pool = None
        if self._bind_q is None:
            return
        self.flush_binds(timeout)
        # One sentinel per worker: each consumes exactly one from its
        # blocking get (a sentinel seen mid-coalesce is recycled).
        for _ in self._bind_workers:
            self._bind_q.put(None)
        for w in self._bind_workers:
            w.join(timeout)

    def run_until_drained(self, max_cycles: int = 10_000) -> int:
        """Drain the queue; returns total pods bound (assume-then-bind
        mode: total pods assumed, with all binds flushed)."""
        total = 0
        for _ in range(max_cycles):
            n = self.run_once(timeout=0.0)
            if n == 0 and len(self.queue) == 0:
                # The bind worker may still requeue transient failures
                # — only an empty queue AFTER a flush is drained.
                self.flush_binds()
                if len(self.queue) == 0:
                    break
            total += n
        return total

    def reconcile_nodes(self) -> int:
        """Remove encoder nodes the API server no longer lists (DELETED
        events missed while the daemon was down, or a watch gap).
        ``listed_at`` is taken before the listing so a node registered
        concurrently (watch ADDED racing the list response) is never
        wrongly removed.  Returns how many were removed."""
        listed_at = time.monotonic()
        try:
            listed = [n.name for n in self.client.list_nodes()]
        except Exception:  # noqa: BLE001 — transient; next tick retries
            return 0
        return self.encoder.reconcile_nodes(listed, listed_at)

    def reconcile_usage(self) -> int:
        """Release ledger entries for pods that no longer exist
        (deleted while the daemon was down, or whose watch event was
        lost).  No-op for clients that cannot list all pods."""
        listed_at = time.monotonic()
        pods = self.client.list_all_pods()
        if pods is None:
            return 0
        return self.encoder.reconcile_committed(
            (p.uid for p in pods), listed_at)

    def run_forever(self, poll_s: float = 0.05,
                    resync_every_s: float = 60.0) -> None:
        """The reference's ``wait.Until(s.Schedule, 0, quit)``
        (scheduler.go:140), batched, plus a periodic pending-pod
        resync so pods lost to drops/transient failures are recovered
        (the reference stranded them, scheduler.go:165-173) and a
        usage-ledger reconcile against the live pod listing."""
        last_resync = time.monotonic()
        while True:
            if self.run_once(timeout=poll_s) == 0:
                time.sleep(0.0)
            if time.monotonic() - last_resync >= resync_every_s:
                self.maintain()
                last_resync = time.monotonic()

    def maintain(self) -> None:
        """One maintenance tick: pending-pod resync + usage-ledger
        reconcile.  Transient API errors are swallowed — maintenance
        must never take the serving loop down (the watch path already
        catches-and-reconnects on exactly these errors)."""
        try:
            self.informer.resync()
        except Exception:  # noqa: BLE001 — retried next tick
            pass
        try:
            self.reconcile_usage()
        except Exception:  # noqa: BLE001 — retried next tick
            pass
        try:
            self.reconcile_nodes()
        except Exception:  # noqa: BLE001 — retried next tick
            pass
        self._flush_preemption_waits()
        self._flush_gang_timeouts()
        self.encoder.expire_nominations(self.cfg.preemption_wait_s)
        # Outcome observability: harvest pending quality joins against
        # the probes that arrived since the commits, and keep the SLO
        # engine sampling even when no cycles are committing (an idle
        # burning objective must still clear / keep burning).
        if self.quality is not None:
            try:
                now = time.monotonic()
                if (now - self._quality_last_harvest
                        >= self.cfg.quality_harvest_interval_s):
                    self._quality_last_harvest = now
                    self.quality.harvest(self.encoder)
            except Exception:  # noqa: BLE001 — observation only
                pass
        if self.slo is not None:
            try:
                self._slo_last_eval = time.monotonic()
                self.slo.evaluate(self)
            except Exception:  # noqa: BLE001 — observation only
                pass
        # Continuous rebalancing: settle in-flight moves, then scan
        # for improvement candidates and execute within budget.  The
        # rebalancer owns its own interval gate; a failure here must
        # never break the maintain path (moves are crash-safe by the
        # migration ledger, so a half-executed tick is recoverable).
        if self.rebalance is not None:
            try:
                self.rebalance.tick(self)
            except Exception:  # noqa: BLE001 — retried next tick
                pass
        # Learned scoring policy: harvest the explain/outcome join
        # into the example ring and run the bounded Adam step burst
        # (train tick), then shadow-score the retained decisions and
        # — when a replay trace is configured — run the full
        # counterfactual promotion gate (eval tick).  Both strictly
        # off the scoring hot path and exception-swallowed like every
        # other maintain block.
        if self.policy is not None:
            try:
                now = time.monotonic()
                if (now - self._policy_last_train
                        >= self.cfg.policy_train_interval_s):
                    self._policy_last_train = now
                    self._policy_train_tick()
            except Exception:  # noqa: BLE001 — observation only
                pass
            try:
                now = time.monotonic()
                if (now - self._policy_last_eval
                        >= self.cfg.policy_eval_interval_s):
                    self._policy_last_eval = now
                    self._policy_eval_tick()
            except Exception:  # noqa: BLE001 — observation only
                pass

    def _policy_train_tick(self) -> None:
        """One train tick: join fresh quality outcomes with their
        explain records, feed the ring, dispatch the jitted steps."""
        if self.policy_dataset is not None:
            batch = self.policy_dataset.collect(self.flight,
                                                self.quality)
            if batch is not None:
                self.policy.add_examples(batch.comps, batch.feas,
                                         batch.target, batch.cls)
        self.policy.train()

    def _policy_eval_tick(self) -> None:
        """One eval tick: shadow-rank the retained explain records
        (disagreement accounting), then run the counterfactual
        promotion gate.  A promotion swaps cfg.weights IN PLACE OF
        the incumbent via dataclasses.replace and invalidates the
        static cache — one jit retrace, after which every path scores
        under the promoted weights."""
        explains = (self.flight.explains()
                    if self.flight is not None else [])
        # Shadow-rank only records newer than the last tick — the
        # explain store retains records across ticks and re-counting
        # them would inflate the disagreement series.
        newest = self._policy_shadow_twall
        for rec in explains:
            tw = float(rec.get("t_wall", 0.0))
            if tw <= self._policy_shadow_twall:
                continue
            newest = max(newest, tw)
            self.policy.shadow_rank(rec)
        self._policy_shadow_twall = newest
        self.policy.evals_total += 1
        from kubernetesnetawarescheduler_tpu.policy.replay_eval import (
            evaluate_candidate,
        )

        candidate = self.policy.to_score_weights(self.cfg.weights)
        decision = evaluate_candidate(
            self.cfg, candidate, self.cfg.weights, explains,
            trace_path=self.policy_eval_trace,
            k_pad=self.policy.k_pad)
        if not decision.promote:
            self.policy.rejections_total += 1
            return
        self._apply_promotion(decision)

    def _apply_promotion(self, decision) -> None:
        """Install gate-approved weights: replace cfg (frozen
        dataclass — the loop, not the shared object, owns its config)
        and drop the static cache so the next cycle re-derives the
        normalization under the promoted weights."""
        import dataclasses as _dc

        self.cfg = _dc.replace(self.cfg,
                               weights=decision.candidate_weights)
        self.policy.cfg = self.cfg
        if self.policy_dataset is not None:
            self.policy_dataset.cfg = self.cfg
        if getattr(self, "_static_version", None) is not None:
            self._static_version = None
            self._static_val = None
        with self._static_lock:
            self._static_ex = None
        self.policy.note_promotion(decision.to_dict(),
                                   decision.candidate_weights)
        if self.flight is not None:
            self.flight.meta["policy_promotion"] = {
                "version": self.policy.promoted_version,
                "reason": decision.reason,
                "replay_delta": decision.replay_delta,
                "t_wall": decision.t_wall,
            }

    def _flush_preemption_waits(self) -> None:
        """Requeue preemptors whose confirmation deadline passed (a
        victim stuck terminating must not strand the preemptor forever
        — its reservation also expires) or whose victim set drained
        but whose requeue push failed earlier.  Entries are removed
        first; an unparked pod is re-delivered by resync if the push
        fails again."""
        now = time.monotonic()
        ready: list[Pod] = []
        with self._preempt_lock:
            for uid, (pod, vset, deadline) in list(
                    self._awaiting_preemption.items()):
                if vset and now < deadline:
                    continue
                del self._awaiting_preemption[uid]
                ready.append(pod)
        for pod in ready:
            self.queue.push(pod)


def jax_block(x):
    """Block on device computation so bind never races the kernel."""
    try:
        return x.block_until_ready()
    except AttributeError:
        return x
