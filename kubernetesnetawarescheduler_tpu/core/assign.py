"""Assignment: from masked score matrices to per-pod node choices.

The reference's "assignment" is ``findBestNode`` — an argmax over a Go
map whose iteration order is random, so ties broke nondeterministically
(scheduler.go:384-394) — and it had no notion of batch conflicts because
it scheduled one pod at a time off a channel (scheduler.go:191).

Here a whole batch is assigned on-device, which raises the problem
SURVEY.md 7 flags as hard: capacity is *stateful across the batch* — two
pods must not both take the last slot on a node.  Two assigners are
provided:

- :func:`assign_greedy` — exact sequential semantics: a ``lax.scan`` in
  descending priority order, re-masking capacity/affinity after every
  placement.  O(P * N * R); the oracle the parallel path is tested
  against.
- :func:`assign_parallel` — iterative conflict resolution inside a
  ``lax.while_loop``: every unassigned pod argmaxes its masked row,
  each contested node accepts a checked PREFIX of its contenders
  (priority, lowest-index first), rejected pods get a same-round
  second chance at their best untouched node, usage/masks update,
  repeat.  Converges in a few rounds, keeps the P x N work batched
  and device-friendly (node-major carry; see the function docstring).

Both are deterministic: all tie-breaks are (higher priority, then lower
pod index, then lower node index).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from kubernetesnetawarescheduler_tpu.config import SchedulerConfig
from kubernetesnetawarescheduler_tpu.core import score as score_lib
from kubernetesnetawarescheduler_tpu.core.score import NEG_INF, _EPS
from kubernetesnetawarescheduler_tpu.core.state import (
    ClusterState,
    PodBatch,
    add_zone_counts,
    bit_planes,
    commit_assignments,
    planes_to_words,
    scatter_or_onehot,
)

# np scalar, not jnp — see core/score.py NEG_INF: module-level jnp
# constants initialize the backend at import and lock the platform.
UNASSIGNED = np.int32(-1)


def _static_parts(state: ClusterState, pods: PodBatch, cfg: SchedulerConfig,
                  static=None, transposed: bool = False):
    """Batch-invariant pieces: base+network score and the static mask
    (taints, node selectors, validity) that placements can't change.

    ``static`` is the backend's precomputed batch-invariant prep
    (:func:`~.pallas_score.compute_assign_static`): for the dense
    backend the ``(base[N], C.T prepared)`` pair, for the Pallas
    backend the :func:`~.pallas_score.static_replay_pack` arrays —
    precomputed once per replay so the N×N normalization/pad work is
    not re-done every batch.

    Backend dispatch happens HERE because this is the dense-C seam:
    with ``cfg.score_backend == "pallas"`` the raw score and static
    mask come from the tiled kernel (lat/bw streamed through VMEM,
    ``C[N, N]`` never materialized in HBM), and ``static_node_scores``
    — whose ``prep_net_matrix`` writes that 100 MB matrix — is never
    called.  The per-round dynamic work (capacity, groups, balance)
    stays in XLA either way: it mutates every conflict round.
    """
    if isinstance(static, dict):
        # Precomputed by the caller — the shard_map'd multi-chip
        # Pallas path evaluates the kernel per batch OUTSIDE assign
        # (a pallas_call must be wrapped in shard_map, which needs the
        # mesh; see parallel.sharding.pallas_static_builder) and hands
        # the result through as {"raw": ..., "ok": ...}.
        raw, ok = static["raw"], static["ok"]
        return (raw.T, ok.T) if transposed else (raw, ok)
    if cfg.score_backend == "pallas":
        from kubernetesnetawarescheduler_tpu.core import pallas_score

        if static is None:
            static = pallas_score.static_replay_pack(state, cfg)
        interpret = jax.default_backend() != "tpu"
        raw, ok = pallas_score.static_scores_tiled(state, pods, cfg,
                                                   static,
                                                   interpret=interpret)
        return (raw.T, ok.T) if transposed else (raw, ok)
    if static is None:
        static = score_lib.static_node_scores(state, cfg)
    base, ct = static
    if transposed:
        # Node-major [N, P] — the conflict loop's carry layout (axis-0
        # reductions and row patches are ~10x cheaper than their
        # axis-1/column twins on CPU; measured, see assign_parallel).
        # Built natively end to end: the gather einsum emits "np", the
        # masks swap broadcast axes, and the gated soft/ns banks emit
        # node-major from their dead branches (a transpose is paid
        # only when those constraints are actually present).
        soft_t = score_lib.soft_affinity_scores(state, pods, cfg,
                                                transposed=True)
        net_t = score_lib.network_scores(state, pods, cfg, ct=ct,
                                         transposed=True)
        raw_t = base[:, None] + net_t + soft_t
        return raw_t, score_lib.static_feasibility_t(state, pods)
    # Soft (preferred) affinity is batch-invariant by design: group
    # terms score against batch-entry group_bits, like kube-scheduler
    # scoring against committed state (score.soft_affinity_scores).
    soft = score_lib.soft_affinity_scores(state, pods, cfg)
    net = score_lib.network_scores(state, pods, cfg, ct=ct)
    raw = base[None, :] + net + soft
    return raw, score_lib.static_feasibility(state, pods)


def _dynamic_mask(pods: PodBatch, used: jax.Array, cap: jax.Array,
                  group_bits: jax.Array,
                  resident_anti: jax.Array) -> jax.Array:
    """Placement-dependent constraints: capacity fit + pod (anti-)affinity
    (both directions), recomputed against the *current* usage/groups.
    Required affinity is a SUBSET test — terms AND, matching
    kube-scheduler (see score.feasibility_mask)."""
    free = cap - used
    fits = jnp.all(pods.req[:, None, :] <= free[None, :, :] + _EPS, axis=-1)
    aff_req = pods.affinity_bits[:, None, :]
    affinity = jnp.all(
        (group_bits[None, :, :] & aff_req) == aff_req, axis=-1)
    anti = jnp.all(
        (group_bits[None, :, :] & pods.anti_bits[:, None, :]) == 0,
        axis=-1)
    sym = jnp.all(
        (resident_anti[None, :, :] & pods.group_bit[:, None, :]) == 0,
        axis=-1)
    return fits & affinity & anti & sym


def _balance(pods: PodBatch, used: jax.Array, cap: jax.Array) -> jax.Array:
    cap = jnp.maximum(cap, _EPS)
    frac = (used[None, :, :] + pods.req[:, None, :]) / cap[None, :, :]
    return jnp.max(frac, axis=-1)


@partial(jax.jit, static_argnames=("cfg",))
def assign_greedy(state: ClusterState, pods: PodBatch,
                  cfg: SchedulerConfig, static=None) -> jax.Array:
    """Sequential greedy assignment, ``i32[P]`` (-1 = unschedulable).

    Exact semantics: pods are placed one at a time in (priority desc,
    index asc) order; every placement immediately updates capacity and
    group masks for the pods after it.
    """
    p = pods.num_pods
    raw, static_ok = _static_parts(state, pods, cfg, static)
    w_bal = jnp.float32(cfg.weights.balance)

    # Stable order: priority descending, index ascending.
    order = jnp.argsort(-pods.priority, stable=True)

    gmax, zmax = state.gz_counts.shape
    has_zone = state.node_zone >= 0
    w_spread = jnp.float32(cfg.weights.spread)
    sact = score_lib.spread_active(pods)  # [P], loop-invariant

    def step(carry, pod_idx):
        used, group_bits, resident_anti, gz, az = carry
        # Gather this pod's scalars first so the step does O(N*R) work,
        # not O(P*N*R) (computing the full batch tensors and indexing
        # one row would defeat the scan).
        req = pods.req[pod_idx]
        cap = jnp.maximum(state.cap, _EPS)
        bal_row = jnp.max((used + req[None, :]) / cap, axis=-1)
        fits = jnp.all(req[None, :] <= state.cap - used + _EPS, axis=-1)
        aff_req = pods.affinity_bits[pod_idx]          # [W]
        affinity = jnp.all(
            (group_bits & aff_req[None, :]) == aff_req[None, :], axis=-1)
        anti = jnp.all(
            (group_bits & pods.anti_bits[pod_idx][None, :]) == 0, axis=-1)
        sym = jnp.all(
            (resident_anti & pods.group_bit[pod_idx][None, :]) == 0,
            axis=-1)
        # Topology spread vs the CURRENT counts (score.spread_terms,
        # single-pod row form; Honor-policy min over the pod's
        # eligible domains via its static mask row).
        gi = pods.group_idx[pod_idx]
        cz = gz[jnp.clip(gi, 0, gmax - 1)]             # [Z]
        cnt = cz[jnp.clip(state.node_zone, 0, zmax - 1)]
        elig = static_ok[pod_idx] & has_zone
        min_c = jnp.min(jnp.where(elig, cnt, jnp.int32(2**30)))
        skew_after = cnt + 1 - min_c
        s_active = sact[pod_idx]
        violates = (s_active & has_zone
                    & (skew_after > pods.spread_maxskew[pod_idx]))
        spread_ok = ~(violates & pods.spread_hard[pod_idx])
        excess = jnp.maximum(
            skew_after - pods.spread_maxskew[pod_idx], 0
        ).astype(jnp.float32)
        pen = jnp.where(violates & ~pods.spread_hard[pod_idx],
                        w_spread * excess, 0.0)
        # Zone-scoped (anti-)affinity vs the CURRENT carries
        # (score.zone_affinity_ok, single-pod row form).
        zwords = planes_to_words((gz > 0).T)            # u32[Z, W]
        zrow = jnp.clip(state.node_zone, 0, zmax - 1)
        pres = zwords[zrow]                              # [N, W]
        azn = az[zrow]                                   # [N, W]
        zaff_i = pods.zaff_bits[pod_idx]
        zone_ok = (
            jnp.where(has_zone,
                      jnp.all((pres & zaff_i[None, :]) == zaff_i[None, :],
                              axis=-1),
                      jnp.all(zaff_i == 0))
            & (~has_zone | jnp.all(
                (pres & pods.zanti_bits[pod_idx][None, :]) == 0,
                axis=-1))
            & (~has_zone | jnp.all(
                (azn & pods.group_bit[pod_idx][None, :]) == 0,
                axis=-1)))
        ok = (static_ok[pod_idx] & fits & affinity & anti & sym
              & spread_ok & zone_ok)
        row = jnp.where(ok, raw[pod_idx] - w_bal * bal_row - pen, NEG_INF)
        choice = jnp.argmax(row).astype(jnp.int32)  # first-max: deterministic
        feasible = row[choice] > NEG_INF * 0.5
        node = jnp.where(feasible, choice, UNASSIGNED)
        placed = feasible & pods.pod_valid[pod_idx]
        idx = jnp.where(placed, choice, 0)
        add = jnp.where(placed, pods.req[pod_idx], 0.0)
        used = used.at[idx].add(add, mode="drop")
        gbit = jnp.where(placed, pods.group_bit[pod_idx], jnp.uint32(0))
        group_bits = group_bits.at[idx].set(group_bits[idx] | gbit,
                                            mode="drop")
        abit = jnp.where(placed, pods.anti_bits[pod_idx], jnp.uint32(0))
        resident_anti = resident_anti.at[idx].set(resident_anti[idx] | abit,
                                                  mode="drop")
        pzone = state.node_zone[idx]
        # Full membership mask into the zone column (multi-bit
        # selector-group memberships count everywhere the host ledger
        # counts them).
        gplanes = bit_planes(pods.group_bit[pod_idx][None, :],
                             jnp.int32)[0]                    # [G]
        zcol = jnp.where(placed & (pzone >= 0), pzone, zmax)
        gz = gz.at[:, zcol].add(
            jnp.where(placed & (pzone >= 0), gplanes, 0), mode="drop")
        zbits = jnp.where(placed, pods.zanti_bits[pod_idx], jnp.uint32(0))
        zidx = jnp.where(placed & (pzone >= 0), pzone, zmax)
        az = az.at[zidx].set(az[jnp.clip(zidx, 0, zmax - 1)] | zbits,
                             mode="drop")
        return (used, group_bits, resident_anti, gz, az), node

    (_, _, _, _, _), nodes_sorted = jax.lax.scan(
        step, (state.used, state.group_bits, state.resident_anti,
               state.gz_counts, state.az_anti), order)
    # Un-permute back to original pod order.
    assignment = jnp.zeros((p,), jnp.int32).at[order].set(nodes_sorted)
    return jnp.where(pods.pod_valid, assignment, UNASSIGNED)


@partial(jax.jit, static_argnames=("cfg", "with_stats"))
def assign_parallel(state: ClusterState, pods: PodBatch,
                    cfg: SchedulerConfig, static=None, *,
                    with_stats: bool = False):
    """Batched iterative conflict-resolution assignment, ``i32[P]``.

    Each round: every still-unassigned pod argmaxes its masked score
    row; each chosen node accepts a capacity/conflict/repricing-
    checked PREFIX of its contenders (priority desc, pod index asc);
    pods rejected at their argmax node immediately re-propose their
    best untouched node in a SECOND-CHANCE pass (greedy-faithful: only
    where that beats every re-priced first-pass alternative); usage
    and masks update; remaining pods re-pick next round.  Terminates
    when no unassigned pod has a feasible node (bounded by P rounds).

    Round cost (this is the BENCH-critical loop): the carried matrix
    is the CORE (static + capacity + host-scoped groups + balance) in
    NODE-MAJOR ``[N, P]`` layout, which a round changes only at the
    winners' node ROWS — an exact ``O(P²·(R+W))`` contiguous row
    patch, on every batch.  The transposed layout makes the per-round
    reductions axis-0 (vectorized across pod lanes) and the patch a
    row scatter — measured 8-14x cheaper than the pod-major twins on
    the CPU fallback at N=5120.  Assigned pods are retired by masking
    at read time (fused into the reduces), never by column scatters.
    Zone-scoped state (spread counts, zone presence) can move every
    node of a zone, so those terms are not carried: each round
    re-derives them as a gated overlay on top of the core — before
    round 4 one spread-active pod in a batch forced a full
    ``O(P·N·(R+W))`` recompute every round (the r3 CPU regression,
    VERDICT r3 weak #1/next #2).

    ``with_stats=True`` additionally returns the executed
    conflict-round count (``i32`` scalar) — the observable VERDICT.md
    round-2 asked for: whether TPU latency will be matmul-bound or
    round-bound is a function of this distribution.
    """
    p = pods.num_pods
    n = state.num_nodes
    # TRANSPOSED carry: every [pods x nodes] tensor in this loop is
    # node-major ``[N, P]``.  On CPU (the measured fallback) axis-0
    # reductions vectorize across the P lanes and the per-round patch
    # becomes a contiguous ROW scatter — measured 8-14x cheaper than
    # their axis-1/column twins at N=5120, P=128 (masked max 3.8 ms ->
    # 0.44 ms; patch scatter 3.5 ms -> 0.24 ms).  On TPU the layouts
    # are equivalent modulo a relayout the compiler handles.
    rawT, static_okT = _static_parts(state, pods, cfg, static,
                                     transposed=True)
    w_bal = jnp.float32(cfg.weights.balance)
    pod_ids = jnp.arange(p, dtype=jnp.int32)

    # Loop-invariant row ids for the per-round second-best computation
    # (XLA does not hoist out of while bodies; an iota materialized
    # per round measurably costs at N=5120).
    row_ids = jax.lax.broadcasted_iota(jnp.int32, (n, p), 0)

    # Loop-invariant tie-break rank: position in (priority desc, index
    # asc) order.  Lets each round pick per-node winners with ONE
    # O(P log P) sort over composite keys instead of O(P*N) one-hot
    # reductions.
    order = jnp.argsort(-pods.priority, stable=True)
    rank = jnp.zeros((p,), jnp.int32).at[order].set(pod_ids)
    # Loop-invariant bitplanes of the pods' group/anti words (0/1 i32,
    # ``B = 32 * W`` columns), consumed by the multi-accept prefix's
    # segmented pairwise checks and the winner bit aggregation below.
    mask_b = 32 * pods.group_bit.shape[1]
    gb_planes = bit_planes(pods.group_bit, jnp.int32)
    ab_planes = bit_planes(pods.anti_bits, jnp.int32)
    # Round-invariant piece of the zone-anti round cap (pair [i, j]
    # conflicts AND i outranks j): hoisted here because XLA does not
    # move computations out of while_loop bodies.
    zpair_conflict = (
        (jnp.any(pods.zanti_bits[:, None, :]
                 & pods.group_bit[None, :, :], axis=-1)
         | jnp.any(pods.group_bit[:, None, :]
                   & pods.zanti_bits[None, :, :], axis=-1))
        & (rank[:, None] < rank[None, :]))
    if (n + 1) * p > np.iinfo(np.int32).max:
        # The composite key below would wrap and silently corrupt
        # winner selection; int64 needs jax_enable_x64.  (~16M nodes
        # at P=128 — far past the design envelope, so fail loudly.)
        raise ValueError(
            f"max_nodes*max_pods={n}*{p} overflows the int32 "
            "winner-selection key; reduce the batch or node padding")

    # XLA CPU lowers lax.cummax/cumsum to a naive O(len^2)
    # reduce-window (measured ~0.2 ms per [128, 128] call, x8 calls x2
    # passes per round); the log-depth associative scan is ~7x faster
    # and numerically identical for max/add on these inputs.
    def cummax0(x):
        return jax.lax.associative_scan(jnp.maximum, x, axis=0)

    def cumsum0(x):
        return jax.lax.associative_scan(jnp.add, x, axis=0)

    def argmax2(s_m):
        """(choice, best, second_best) over axis 0 of ``[N, P]`` in
        three PLAIN masked reductions instead of a variadic
        iota-reduce: XLA CPU runs the (value, index) tuple reduce
        ~6x slower than a vectorized max (measured 2.9 ms vs 0.44 ms
        at N=5120), while max + min-index-of-max + masked-max keeps
        every pass vectorized.  Tie-break identical to argmax (first
        max); ``second_best`` excludes only the chosen ROW, so a
        duplicate max on another node still counts (the
        stays-best guard's semantics)."""
        best = jnp.max(s_m, axis=0)
        choice = jnp.min(
            jnp.where(s_m == best[None, :], row_ids, n),
            axis=0).astype(jnp.int32)
        second = jnp.max(
            jnp.where(row_ids == choice[None, :], NEG_INF, s_m),
            axis=0)
        return choice, best, second

    def core_scores_t(used, group_bits, resident_anti, assignment):
        """The CORE carried matrix ``f32[N, P]``: raw score minus
        balance, masked by the static + host-scoped dynamic
        constraints (capacity fit, group affinity/anti both
        directions) and assigned-pod retirement.  Deliberately
        EXCLUDES the zone-scoped terms (spread, zone (anti-)affinity):
        a placement changes the core only at the winners' node ROWS,
        so the per-round update is an exact O(P^2 (R+W)) row patch —
        while zone state can move every node of a zone and is instead
        re-derived per round as an OVERLAY (``overlay`` below).
        Splitting the two is what lets EVERY batch take the cheap
        patch path; before round 4 one spread-active pod forced the
        full O(P N (R+W)) recompute on all of them (the r3 CPU
        throughput regression, VERDICT r3 weak #1)."""
        free = state.cap - used
        fits = jnp.all(pods.req[None, :, :] <= free[:, None, :] + _EPS,
                       axis=-1)                               # [N, P]
        aff_req = pods.affinity_bits[None, :, :]
        affinity = jnp.all(
            (group_bits[:, None, :] & aff_req) == aff_req, axis=-1)
        anti = jnp.all(
            (group_bits[:, None, :] & pods.anti_bits[None, :, :]) == 0,
            axis=-1)
        sym = jnp.all(
            (resident_anti[:, None, :] & pods.group_bit[None, :, :])
            == 0, axis=-1)
        bal = jnp.max(
            (used[:, None, :] + pods.req[None, :, :])
            / jnp.maximum(state.cap, _EPS)[:, None, :], axis=-1)
        ok = (static_okT & fits & affinity & anti & sym
              & (assignment == UNASSIGNED)[None, :])
        return jnp.where(ok, rawT - w_bal * bal, NEG_INF)

    # Loop-invariant: does ANY zone-scoped work exist for this batch?
    # Spread/zone(-anti) constraints on batch pods, or zone-anti
    # residency already on the cluster (az may grow during the loop,
    # but only from batch pods' zanti_bits — covered by the same
    # predicate).  When false the overlay is the identity and the
    # round skips its [N, P] passes entirely — constraint-free batches
    # (the headline bench shape) pay nothing for the zone machinery.
    zone_work = (jnp.any(score_lib.spread_active(pods))
                 | jnp.any(pods.zaff_bits != 0)
                 | jnp.any(pods.zanti_bits != 0)
                 | jnp.any(state.az_anti != 0))
    # Pod-major static mask for spread's Honor-policy domain
    # eligibility — only materialized when zone work exists (the
    # transpose pass is real; constraint-free batches skip it).
    static_ok_pn = jax.lax.cond(
        zone_work, lambda _: static_okT.T,
        lambda _: jnp.zeros((p, n), bool), None)

    def overlay(sT, gz, az):
        """Zone-scoped terms, re-derived against the CURRENT zone
        state: topology-spread penalty/mask and zone (anti-)affinity.
        Gated twice: ``zone_work`` skips the whole thing (identity)
        for batches with no zone-scoped constraints, and each term is
        further gated (`lax.cond`) on its own constraint class."""

        def live(_):
            spread_pen, spread_ok = score_lib.spread_terms(
                state, pods, cfg, gz_counts=gz,
                static_ok=static_ok_pn)
            zone_ok = score_lib.zone_affinity_ok(
                state, pods, gz_counts=gz, az_anti=az)
            return jnp.where((spread_ok & zone_ok).T,
                             sT - spread_pen.T, NEG_INF)

        return jax.lax.cond(zone_work, live, lambda _: sT, None)

    # The core matrix is carried across rounds and row-patched; the
    # continue flag (progress made AND a core-feasible entry remains)
    # is carried too, so cond reads a scalar instead of reducing
    # [N, P] per evaluation.  (A pod whose core column is live but
    # whose every node is zone-masked costs at most one extra
    # no-winner round before the loop exits on progress=False.)
    def cond(carry):
        return carry[7]

    idx = jnp.arange(p, dtype=jnp.int32)
    zero_row = jnp.zeros((1, mask_b), jnp.int32)

    def accept(second_best, choice_x, feas_x, used):
        """Per-node multi-accept prefix winner selection over one
        (choice, feasibility) proposal set.

        Beyond its single best contender, a node also accepts the
        following contenders (in priority order) as long as they
        cumulatively fit the node's free capacity AND no pairwise
        group/anti conflict exists with any earlier prefix member.
        Pod-independent metric scores make whole batches of look-alike
        pods argmax the same node (the reference's pathology,
        scheduler.go:248, reborn as round count: one winner per round
        = P rounds); the prefix collapses those to ~capacity-fill
        rounds.  Exactness: a same-round contender's round-entry
        checks can only be invalidated by capacity (the segmented
        cumsum bounds it), host-scoped group state (the pairwise
        planes check below), or zone state — and the spread/zone round
        caps after pass-A selection demote every same-zone
        zone-conflicting winner.

        ``second_best`` is the greedy-faithfulness floor per pod: the
        row's best alternative value (and, for the second-chance pass,
        the best RE-PRICED pass-A column) — a contender is accepted
        only while its re-priced value at the node stays above it.
        """
        key = jnp.where(feas_x, choice_x * p + rank, n * p + rank)
        perm = jnp.argsort(key)
        group_id = key[perm] // p
        first = jnp.concatenate(
            [jnp.ones((1,), bool), group_id[1:] != group_id[:-1]])
        req_sorted = pods.req[perm]                       # [P, R]
        csum = cumsum0(req_sorted)
        # Segment-relative cumulative request: csum minus the running
        # csum at each segment's start (cummax works: csum is
        # monotone, req >= 0).
        base = jnp.where(first[:, None], csum - req_sorted,
                         -jnp.inf)
        seg_csum = csum - cummax0(base)
        node_sorted = jnp.clip(group_id, 0, n - 1).astype(jnp.int32)
        fits_cum = jnp.all(
            seg_csum <= (state.cap - used)[node_sorted] + _EPS, axis=-1)
        # Greedy-faithfulness guard: accept a prefix member only while
        # the node REMAINS its best choice once the balance penalty is
        # re-priced with everyone queued ahead of it — without this,
        # look-alike batches overpack the round-entry-best node at its
        # stale price (measured: sidecar co-placement fell to 0.79
        # because app nodes were packed solid), where sequential
        # greedy would have spilled to each pod's next-best node.
        bal_after = jnp.max(
            (used[node_sorted] + seg_csum)
            / jnp.maximum(state.cap, _EPS)[node_sorted], axis=-1)
        raw_sel = jnp.take_along_axis(
            rawT, jnp.clip(choice_x, 0, n - 1)[None, :], axis=0)[0]
        adj_sorted = raw_sel[perm] - w_bal * bal_after
        stays_best = adj_sorted >= second_best[perm] - 1e-6
        # Segmented EXCLUSIVE cumulative OR of earlier contenders'
        # group/anti bitplanes, via the cummax-with-segment-offset
        # trick (segment ids strictly increase along the sort, so
        # ``2*seg + plane`` from an earlier segment can never reach the
        # current segment's offset).  Checking against all earlier
        # contenders rather than accepted ones is equivalent under
        # stop-at-first-bad: a rejected earlier entry rejects everyone
        # after it anyway.
        seg2 = (group_id * 2).astype(jnp.int32)[:, None]
        incl_gb = cummax0(seg2 + gb_planes[perm]) - seg2
        incl_ab = cummax0(seg2 + ab_planes[perm]) - seg2
        excl_gb = jnp.where(first[:, None], 0,
                            jnp.concatenate([zero_row, incl_gb[:-1]],
                                            axis=0)) >= 1
        excl_ab = jnp.where(first[:, None], 0,
                            jnp.concatenate([zero_row, incl_ab[:-1]],
                                            axis=0)) >= 1
        pair_ok = (~jnp.any(excl_ab & (gb_planes[perm] >= 1), axis=1)
                   & ~jnp.any(excl_gb & (ab_planes[perm] >= 1), axis=1))
        good = fits_cum & pair_ok & stays_best
        seg_start = cummax0(jnp.where(first, idx, -1))
        last_bad = cummax0(jnp.where(~good, idx, -1))
        prefix_ok = last_bad < seg_start  # all good since segment start
        return jnp.zeros((p,), bool).at[perm].set(
            (first | prefix_ok) & (group_id < n))

    def seg_or_updates(choice_x, winner_x, group_bits, resident_anti):
        """Per-node OR of the winners' group/anti planes into the node
        bit fields — one scatter-set per node segment (never
        colliding), the segmented-cummax running OR read at each
        segment's last row."""
        key = jnp.where(winner_x, choice_x * p + rank, n * p + rank)
        perm = jnp.argsort(key)
        group_id = key[perm] // p
        first = jnp.concatenate(
            [jnp.ones((1,), bool), group_id[1:] != group_id[:-1]])
        node_sorted = jnp.clip(group_id, 0, n - 1).astype(jnp.int32)
        seg2 = (group_id * 2).astype(jnp.int32)[:, None]
        win_sorted = winner_x[perm][:, None]
        or_gb = (cummax0(seg2 + gb_planes[perm] * win_sorted)
                 - seg2) >= 1
        or_ab = (cummax0(seg2 + ab_planes[perm] * win_sorted)
                 - seg2) >= 1
        last_of_seg = jnp.concatenate(
            [first[1:], jnp.ones((1,), bool)])
        seg_cols = jnp.where(last_of_seg & (group_id < n),
                             node_sorted, n)
        new_group = group_bits.at[seg_cols].set(
            group_bits[jnp.clip(seg_cols, 0, n - 1)]
            | planes_to_words(or_gb), mode="drop")
        new_anti = resident_anti.at[seg_cols].set(
            resident_anti[jnp.clip(seg_cols, 0, n - 1)]
            | planes_to_words(or_ab), mode="drop")
        return new_group, new_anti

    def row_patch(sT, wnodes, used_x, group_x, anti_x, assignment_x):
        """Recompute the core values at the given node rows against
        the given (post-placement) allocation state, patch them into
        the carried core matrix, and return the patch values too (the
        second-chance pass reads them as the re-priced pass-A
        alternatives).  Loser entries carry the sentinel row n ->
        dropped by the scatter; duplicate rows write identical
        values.  A contiguous row scatter on the [N, P] carry — the
        whole point of the transposed layout."""
        cc = jnp.clip(wnodes, 0, n - 1)
        sub_used = used_x[cc]                         # [Pc, R]
        sub_cap = state.cap[cc]
        fits2 = jnp.all(
            pods.req[None, :, :] <= (sub_cap - sub_used)[:, None, :]
            + _EPS, axis=-1)                          # [Pc, P]
        gb = group_x[cc]                              # [Pc, W]
        ra = anti_x[cc]
        aff_req2 = pods.affinity_bits[None, :, :]
        affinity2 = jnp.all(
            (gb[:, None, :] & aff_req2) == aff_req2, axis=-1)
        aok = jnp.all(
            (gb[:, None, :] & pods.anti_bits[None, :, :]) == 0,
            axis=-1)
        sym2 = jnp.all(
            (ra[:, None, :] & pods.group_bit[None, :, :]) == 0,
            axis=-1)
        bal = jnp.max(
            (sub_used[:, None, :] + pods.req[None, :, :])
            / jnp.maximum(sub_cap, _EPS)[:, None, :], axis=-1)
        ok = (static_okT[cc] & fits2 & affinity2 & aok & sym2
              & (assignment_x == UNASSIGNED)[None, :]
              & (wnodes < n)[:, None])
        sub = jnp.where(ok, rawT[cc] - w_bal * bal, NEG_INF)
        return sT.at[wnodes].set(sub, mode="drop"), sub

    def body(carry):
        (sT, used, group_bits, resident_anti, gz, az, assignment, _,
         rounds) = carry
        s_ov = overlay(sT, gz, az)
        # Assigned pods are retired by MASKING at every read (the
        # where fuses into the reduces) instead of scattering NEG_INF
        # columns into the carry — a column scatter on [N, P] would
        # cost the transpose the layout exists to avoid.
        alive = (assignment == UNASSIGNED) & pods.pod_valid
        s_m = jnp.where(alive[None, :], s_ov, NEG_INF)
        choice, val, second_best = argmax2(s_m)
        feasible = val > NEG_INF * 0.5
        winner = accept(second_best, choice, feasible, used)

        # Topology-spread round cap: the per-winner skew check above
        # ran against ROUND-ENTRY counts, so two same-group winners on
        # DISTINCT nodes of one zone would together overshoot maxSkew.
        # Demote all but the best-ranked spread-active winner per
        # (group, zone) — each accepted winner's +1 was individually
        # checked, and the demoted pods re-pick next round against
        # updated counts (conservative: never more rounds than pods).
        zone_of = state.node_zone[jnp.clip(choice, 0, n - 1)]
        s_active = winner & score_lib.spread_active(pods) & (zone_of >= 0)
        gzmax = state.gz_counts.shape[0] * state.gz_counts.shape[1]
        gz_id = jnp.where(
            s_active,
            pods.group_idx * state.gz_counts.shape[1] + zone_of,
            gzmax + rank)  # inert pods: unique singleton groups
        key2 = gz_id * p + rank
        perm2 = jnp.argsort(key2)
        gid2 = key2[perm2] // p
        first2 = jnp.concatenate(
            [jnp.ones((1,), bool), gid2[1:] != gid2[:-1]])
        winner = winner & jnp.zeros((p,), bool).at[perm2].set(first2)

        # Zone-anti round cap: the per-winner zone checks ran against
        # ROUND-ENTRY state, so winner A (group g) and winner B
        # (zone-anti g) landing in ONE zone the same round would
        # violate what B's next-round check would reject.  Demote any
        # winner that zone-conflicts with a better-ranked same-zone
        # winner (pairwise [P, P] masks — tiny next to the [N, P]
        # score matrix); the demoted pods re-pick next round against
        # committed counts.
        zsame = (winner[:, None] & winner[None, :]
                 & (zone_of[:, None] == zone_of[None, :])
                 & (zone_of >= 0)[:, None])
        demote = jnp.any(zsame & zpair_conflict, axis=0)
        winner = winner & ~demote

        # Pass-A allocation updates (host-scoped; zone counts are
        # folded in after the merge below).
        assignment_a = jnp.where(winner, choice, assignment)
        safe = jnp.where(winner, choice, 0)
        add = jnp.where(winner[:, None], pods.req, 0.0)
        used_a = used.at[safe].add(add, mode="drop")
        group_a, anti_a = seg_or_updates(choice, winner, group_bits,
                                         resident_anti)
        wnodes_a = jnp.where(winner, choice, n)
        s_patched, sub_a = row_patch(sT, wnodes_a, used_a,
                                     group_a, anti_a, assignment_a)

        # Second-chance pass (VERDICT r3 next #4: the conflict-round
        # tail): pods rejected at their argmax node re-propose their
        # best UNTOUCHED node in the SAME round.  Look-alike pods all
        # argmax one node per round, so acceptance was ~1 node/round;
        # this makes it >= 2.  Greedy-faithful: a pod may settle for
        # an untouched node only if its value there beats its best
        # RE-PRICED pass-A row (``va_new``, read straight from the
        # pass-A patch) — exactly the alternatives sequential greedy
        # would weigh after the pass-A placements.  Untouched-only
        # (choice_b picks from rows pass A did not touch, their
        # round-entry prices still exact) and gated off under
        # ``zone_work``: zone state moved by pass A cannot invalidate
        # an untouched row's price only when no zone-scoped
        # constraint is live.
        def second_chance(_):
            va_new = jnp.max(sub_a, axis=0)               # [P]
            s_b = sT.at[wnodes_a].set(NEG_INF, mode="drop")
            alive_b = alive & ~winner
            s_bm = jnp.where(alive_b[None, :], s_b, NEG_INF)
            choice_b, val_b, sb2 = argmax2(s_bm)
            feas_b = (val_b > NEG_INF * 0.5) & (val_b >= va_new - 1e-6)
            winner_b = accept(jnp.maximum(sb2, va_new), choice_b,
                              feas_b, used)
            # Merge (pod sets disjoint: pass B only ran over pass-A
            # losers; node sets disjoint: pass-A rows are NEG_INF in
            # s_b) + pass-B allocation updates and row patch — all
            # INSIDE the cond, so zone-constrained batches (where the
            # pass is permanently disabled) skip the second
            # seg_or_updates/row_patch entirely instead of running
            # them against an all-false winner mask every round.
            winner_m = winner | winner_b
            choice_m = jnp.where(winner_b, choice_b, choice)
            new_assignment = jnp.where(winner_m, choice_m, assignment)
            safe_b = jnp.where(winner_b, choice_b, 0)
            add_b = jnp.where(winner_b[:, None], pods.req, 0.0)
            new_used = used_a.at[safe_b].add(add_b, mode="drop")
            new_group, new_anti = seg_or_updates(choice_b, winner_b,
                                                 group_a, anti_a)
            wnodes_b = jnp.where(winner_b, choice_b, n)
            new_sT, _ = row_patch(s_patched, wnodes_b, new_used,
                                  new_group, new_anti, new_assignment)
            return (winner_m, choice_m, new_assignment, new_used,
                    new_group, new_anti, new_sT)

        def pass_a_only(_):
            return (winner, choice, assignment_a, used_a, group_a,
                    anti_a, s_patched)

        (winner_m, choice_m, new_assignment, new_used, new_group,
         new_anti, new_sT) = jax.lax.cond(
            ~zone_work & jnp.any(~winner & feasible), second_chance,
            pass_a_only, None)
        progress = jnp.any(winner_m)
        new_gz = add_zone_counts(gz, state.node_zone, pods.group_bit,
                                 choice_m, winner_m)
        # Winner ZONES are not unique (several nodes share one), so
        # the zone-anti residency update is a scatter-OR over a
        # [P, Z] one-hot, not a set.
        zone_of_m = state.node_zone[jnp.clip(choice_m, 0, n - 1)]
        zmax = az.shape[0]
        zhot = (winner_m & (zone_of_m >= 0))[:, None] & (
            jnp.clip(zone_of_m, 0, zmax - 1)[:, None]
            == jnp.arange(zmax)[None, :])
        new_az = az | scatter_or_onehot(zhot, pods.zanti_bits)
        alive2 = (new_assignment == UNASSIGNED) & pods.pod_valid
        cont = progress & jnp.any(
            jnp.where(alive2[None, :], new_sT, NEG_INF) > NEG_INF * 0.5)
        return (new_sT, new_used, new_group, new_anti, new_gz,
                new_az, new_assignment, cont, rounds + 1)

    init_assignment = jnp.full((p,), UNASSIGNED, jnp.int32)
    s0 = core_scores_t(state.used, state.group_bits,
                       state.resident_anti, init_assignment)
    init = (s0,
            state.used, state.group_bits, state.resident_anti,
            state.gz_counts, state.az_anti, init_assignment,
            jnp.any(s0 > NEG_INF * 0.5), jnp.int32(0))
    out = jax.lax.while_loop(cond, body, init)
    assignment, rounds = out[6], out[8]
    assignment = jnp.where(pods.pod_valid, assignment, UNASSIGNED)
    if with_stats:
        return assignment, rounds
    return assignment


def schedule_batch(state: ClusterState, pods: PodBatch, cfg: SchedulerConfig,
                   method: str = "parallel"):
    """Score + assign + commit: returns ``(assignment, new_state)``.

    The device-side core of the reference's ``Schedule()`` cycle
    (scheduler.go:189-237); the host-side binder turns the assignment
    vector into Bind/Event API calls.
    """
    if method == "greedy":
        assignment = assign_greedy(state, pods, cfg)
    elif method == "parallel":
        assignment = assign_parallel(state, pods, cfg)
    else:
        raise ValueError(f"unknown method {method!r}")
    return assignment, commit_assignments(state, pods, assignment)


@partial(jax.jit, static_argnames=("cfg", "method", "with_digest"),
         donate_argnums=(0,))
def fused_schedule_step(state: ClusterState, pods: PodBatch,
                        cfg: SchedulerConfig, static=None,
                        method: str = "parallel",
                        with_digest: bool = False):
    """The whole per-batch scheduling decision as ONE donated device
    dispatch: score + conflict resolution (the device-resident
    ``lax.while_loop`` inside :func:`assign_parallel` — the host never
    re-enters per round) + usage commit.  Returns
    ``(new_state, assignment i32[P], rounds i32)``.

    ``donate_argnums=(0,)``: the caller's ``ClusterState`` buffers are
    DONATED — XLA writes the committed usage/group/zone planes in
    place and forwards the untouched N×N lat/bw planes, so
    batch-to-batch state threading stops allocating fresh copies of
    the large planes each step.  The contract is strict: the caller
    must OWN the state it passes (a scan carry, a replay fold, the
    bench chain) and must not read it afterwards.  The serving loop's
    encoder-cached snapshot leaves are NOT owned — the r7 delta-ingest
    cache patches them in place across cycles — so SchedulerLoop never
    routes its cached snapshot through here (it counts the skip in
    ``donation_skipped_total`` instead; see core/loop.py).

    Bit-identity: results equal ``schedule_batch`` exactly (the same
    assigner and commit run inside; property-tested in
    tests/test_winner_fusion.py).  ``static`` is the backend prep from
    :func:`~.pallas_score.compute_assign_static`, like
    :func:`assign_parallel`'s.

    ``with_digest=True`` additionally returns the committed state's
    per-plane integrity digest (``u32[len(integrity.PLANES)]``,
    :func:`~.integrity.plane_digest_vector`) as a fourth output —
    folded into the SAME donated dispatch, so a running state
    fingerprint on the hot path costs zero extra dispatches (the r10
    anti-entropy contract; the digest reads the post-commit planes XLA
    is already holding in registers/HBM for the state output).
    """
    if method == "greedy":
        assignment = assign_greedy(state, pods, cfg, static)
        rounds = jnp.int32(1)
    elif method == "parallel":
        assignment, rounds = assign_parallel(state, pods, cfg, static,
                                             with_stats=True)
    else:
        raise ValueError(f"unknown method {method!r}")
    new_state = commit_assignments(state, pods, assignment)
    if with_digest:
        from kubernetesnetawarescheduler_tpu.core.integrity import (
            plane_digest_vector,
        )

        return new_state, assignment, rounds, plane_digest_vector(
            new_state)
    return new_state, assignment, rounds


@partial(jax.jit, static_argnames=("cfg", "method"),
         donate_argnums=(0,))
def fused_schedule_window(state: ClusterState, pods_window,
                          cfg: SchedulerConfig, static=None,
                          method: str = "parallel"):
    """K fused per-batch steps as ONE donated dispatch (ISSUE 17): a
    ``lax.scan`` over a stacked ``[K, P, ...]`` window of
    :class:`~.state.PodBatch` leaves, each step the exact
    :func:`fused_schedule_step` body (score + device-resident conflict
    resolution + commit) with the carry threading each step's
    committed state into the next — the in-kernel reference the
    multicycle serving path is test-pinned bit-identical against.
    Returns ``(new_state, assignment i32[K, P], rounds i32[K])``.

    ``pods_window`` must be a PodBatch whose every leaf carries a
    leading window axis (``jax.tree_util.tree_map(stack, *batches)``);
    peers are node indices, already resolved — cross-batch in-stream
    peer resolution lives in core/replay.py's scan, not here.  Same
    donation contract as :func:`fused_schedule_step`: the caller must
    own ``state`` and not read it afterwards.
    """
    if method not in ("greedy", "parallel"):
        raise ValueError(f"unknown method {method!r}")

    def body(carry, batch):
        if method == "greedy":
            assignment = assign_greedy(carry, batch, cfg, static)
            rounds = jnp.int32(1)
        else:
            assignment, rounds = assign_parallel(
                carry, batch, cfg, static, with_stats=True)
        return (commit_assignments(carry, batch, assignment),
                (assignment, rounds))

    new_state, (assignment, rounds) = jax.lax.scan(
        body, state, pods_window)
    return new_state, assignment, rounds
