"""Assignment: from masked score matrices to per-pod node choices.

The reference's "assignment" is ``findBestNode`` — an argmax over a Go
map whose iteration order is random, so ties broke nondeterministically
(scheduler.go:384-394) — and it had no notion of batch conflicts because
it scheduled one pod at a time off a channel (scheduler.go:191).

Here a whole batch is assigned on-device, which raises the problem
SURVEY.md 7 flags as hard: capacity is *stateful across the batch* — two
pods must not both take the last slot on a node.  Two assigners are
provided:

- :func:`assign_greedy` — exact sequential semantics: a ``lax.scan`` in
  descending priority order, re-masking capacity/affinity after every
  placement.  O(P * N * R); the oracle the parallel path is tested
  against.
- :func:`assign_parallel` — iterative conflict resolution inside a
  ``lax.while_loop``: every unassigned pod argmaxes its masked row, each
  contested node accepts its single best (priority, lowest-index) pod,
  usage/masks update, repeat.  Converges in max-collision-depth rounds,
  keeps the P x N work batched and device-friendly.

Both are deterministic: all tie-breaks are (higher priority, then lower
pod index, then lower node index).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from kubernetesnetawarescheduler_tpu.config import SchedulerConfig
from kubernetesnetawarescheduler_tpu.core import score as score_lib
from kubernetesnetawarescheduler_tpu.core.score import NEG_INF, _EPS
from kubernetesnetawarescheduler_tpu.core.state import (
    ClusterState,
    PodBatch,
    add_zone_counts,
    bit_planes,
    commit_assignments,
    planes_to_words,
    scatter_or_onehot,
)

# np scalar, not jnp — see core/score.py NEG_INF: module-level jnp
# constants initialize the backend at import and lock the platform.
UNASSIGNED = np.int32(-1)


def _static_parts(state: ClusterState, pods: PodBatch, cfg: SchedulerConfig,
                  static=None):
    """Batch-invariant pieces: base+network score and the static mask
    (taints, node selectors, validity) that placements can't change.

    ``static`` is the backend's precomputed batch-invariant prep
    (:func:`~.pallas_score.compute_assign_static`): for the dense
    backend the ``(base[N], C.T prepared)`` pair, for the Pallas
    backend the :func:`~.pallas_score.static_replay_pack` arrays —
    precomputed once per replay so the N×N normalization/pad work is
    not re-done every batch.

    Backend dispatch happens HERE because this is the dense-C seam:
    with ``cfg.score_backend == "pallas"`` the raw score and static
    mask come from the tiled kernel (lat/bw streamed through VMEM,
    ``C[N, N]`` never materialized in HBM), and ``static_node_scores``
    — whose ``prep_net_matrix`` writes that 100 MB matrix — is never
    called.  The per-round dynamic work (capacity, groups, balance)
    stays in XLA either way: it mutates every conflict round.
    """
    if isinstance(static, dict):
        # Precomputed by the caller — the shard_map'd multi-chip
        # Pallas path evaluates the kernel per batch OUTSIDE assign
        # (a pallas_call must be wrapped in shard_map, which needs the
        # mesh; see parallel.sharding.pallas_static_builder) and hands
        # the result through as {"raw": ..., "ok": ...}.
        return static["raw"], static["ok"]
    if cfg.score_backend == "pallas":
        from kubernetesnetawarescheduler_tpu.core import pallas_score

        if static is None:
            static = pallas_score.static_replay_pack(state, cfg)
        interpret = jax.default_backend() != "tpu"
        return pallas_score.static_scores_tiled(state, pods, cfg, static,
                                                interpret=interpret)
    if static is None:
        static = score_lib.static_node_scores(state, cfg)
    base, ct = static
    net = score_lib.network_scores(state, pods, cfg, ct=ct)
    # Soft (preferred) affinity is batch-invariant by design: group
    # terms score against batch-entry group_bits, like kube-scheduler
    # scoring against committed state (score.soft_affinity_scores).
    soft = score_lib.soft_affinity_scores(state, pods, cfg)
    raw = base[None, :] + net + soft
    return raw, score_lib.static_feasibility(state, pods)


def _dynamic_mask(pods: PodBatch, used: jax.Array, cap: jax.Array,
                  group_bits: jax.Array,
                  resident_anti: jax.Array) -> jax.Array:
    """Placement-dependent constraints: capacity fit + pod (anti-)affinity
    (both directions), recomputed against the *current* usage/groups.
    Required affinity is a SUBSET test — terms AND, matching
    kube-scheduler (see score.feasibility_mask)."""
    free = cap - used
    fits = jnp.all(pods.req[:, None, :] <= free[None, :, :] + _EPS, axis=-1)
    aff_req = pods.affinity_bits[:, None, :]
    affinity = jnp.all(
        (group_bits[None, :, :] & aff_req) == aff_req, axis=-1)
    anti = jnp.all(
        (group_bits[None, :, :] & pods.anti_bits[:, None, :]) == 0,
        axis=-1)
    sym = jnp.all(
        (resident_anti[None, :, :] & pods.group_bit[:, None, :]) == 0,
        axis=-1)
    return fits & affinity & anti & sym


def _balance(pods: PodBatch, used: jax.Array, cap: jax.Array) -> jax.Array:
    cap = jnp.maximum(cap, _EPS)
    frac = (used[None, :, :] + pods.req[:, None, :]) / cap[None, :, :]
    return jnp.max(frac, axis=-1)


@partial(jax.jit, static_argnames=("cfg",))
def assign_greedy(state: ClusterState, pods: PodBatch,
                  cfg: SchedulerConfig, static=None) -> jax.Array:
    """Sequential greedy assignment, ``i32[P]`` (-1 = unschedulable).

    Exact semantics: pods are placed one at a time in (priority desc,
    index asc) order; every placement immediately updates capacity and
    group masks for the pods after it.
    """
    p = pods.num_pods
    raw, static_ok = _static_parts(state, pods, cfg, static)
    w_bal = jnp.float32(cfg.weights.balance)

    # Stable order: priority descending, index ascending.
    order = jnp.argsort(-pods.priority, stable=True)

    gmax, zmax = state.gz_counts.shape
    has_zone = state.node_zone >= 0
    w_spread = jnp.float32(cfg.weights.spread)
    sact = score_lib.spread_active(pods)  # [P], loop-invariant

    def step(carry, pod_idx):
        used, group_bits, resident_anti, gz, az = carry
        # Gather this pod's scalars first so the step does O(N*R) work,
        # not O(P*N*R) (computing the full batch tensors and indexing
        # one row would defeat the scan).
        req = pods.req[pod_idx]
        cap = jnp.maximum(state.cap, _EPS)
        bal_row = jnp.max((used + req[None, :]) / cap, axis=-1)
        fits = jnp.all(req[None, :] <= state.cap - used + _EPS, axis=-1)
        aff_req = pods.affinity_bits[pod_idx]          # [W]
        affinity = jnp.all(
            (group_bits & aff_req[None, :]) == aff_req[None, :], axis=-1)
        anti = jnp.all(
            (group_bits & pods.anti_bits[pod_idx][None, :]) == 0, axis=-1)
        sym = jnp.all(
            (resident_anti & pods.group_bit[pod_idx][None, :]) == 0,
            axis=-1)
        # Topology spread vs the CURRENT counts (score.spread_terms,
        # single-pod row form; Honor-policy min over the pod's
        # eligible domains via its static mask row).
        gi = pods.group_idx[pod_idx]
        cz = gz[jnp.clip(gi, 0, gmax - 1)]             # [Z]
        cnt = cz[jnp.clip(state.node_zone, 0, zmax - 1)]
        elig = static_ok[pod_idx] & has_zone
        min_c = jnp.min(jnp.where(elig, cnt, jnp.int32(2**30)))
        skew_after = cnt + 1 - min_c
        s_active = sact[pod_idx]
        violates = (s_active & has_zone
                    & (skew_after > pods.spread_maxskew[pod_idx]))
        spread_ok = ~(violates & pods.spread_hard[pod_idx])
        excess = jnp.maximum(
            skew_after - pods.spread_maxskew[pod_idx], 0
        ).astype(jnp.float32)
        pen = jnp.where(violates & ~pods.spread_hard[pod_idx],
                        w_spread * excess, 0.0)
        # Zone-scoped (anti-)affinity vs the CURRENT carries
        # (score.zone_affinity_ok, single-pod row form).
        zwords = planes_to_words((gz > 0).T)            # u32[Z, W]
        zrow = jnp.clip(state.node_zone, 0, zmax - 1)
        pres = zwords[zrow]                              # [N, W]
        azn = az[zrow]                                   # [N, W]
        zaff_i = pods.zaff_bits[pod_idx]
        zone_ok = (
            jnp.where(has_zone,
                      jnp.all((pres & zaff_i[None, :]) == zaff_i[None, :],
                              axis=-1),
                      jnp.all(zaff_i == 0))
            & (~has_zone | jnp.all(
                (pres & pods.zanti_bits[pod_idx][None, :]) == 0,
                axis=-1))
            & (~has_zone | jnp.all(
                (azn & pods.group_bit[pod_idx][None, :]) == 0,
                axis=-1)))
        ok = (static_ok[pod_idx] & fits & affinity & anti & sym
              & spread_ok & zone_ok)
        row = jnp.where(ok, raw[pod_idx] - w_bal * bal_row - pen, NEG_INF)
        choice = jnp.argmax(row).astype(jnp.int32)  # first-max: deterministic
        feasible = row[choice] > NEG_INF * 0.5
        node = jnp.where(feasible, choice, UNASSIGNED)
        placed = feasible & pods.pod_valid[pod_idx]
        idx = jnp.where(placed, choice, 0)
        add = jnp.where(placed, pods.req[pod_idx], 0.0)
        used = used.at[idx].add(add, mode="drop")
        gbit = jnp.where(placed, pods.group_bit[pod_idx], jnp.uint32(0))
        group_bits = group_bits.at[idx].set(group_bits[idx] | gbit,
                                            mode="drop")
        abit = jnp.where(placed, pods.anti_bits[pod_idx], jnp.uint32(0))
        resident_anti = resident_anti.at[idx].set(resident_anti[idx] | abit,
                                                  mode="drop")
        pzone = state.node_zone[idx]
        # Full membership mask into the zone column (multi-bit
        # selector-group memberships count everywhere the host ledger
        # counts them).
        gplanes = bit_planes(pods.group_bit[pod_idx][None, :],
                             jnp.int32)[0]                    # [G]
        zcol = jnp.where(placed & (pzone >= 0), pzone, zmax)
        gz = gz.at[:, zcol].add(
            jnp.where(placed & (pzone >= 0), gplanes, 0), mode="drop")
        zbits = jnp.where(placed, pods.zanti_bits[pod_idx], jnp.uint32(0))
        zidx = jnp.where(placed & (pzone >= 0), pzone, zmax)
        az = az.at[zidx].set(az[jnp.clip(zidx, 0, zmax - 1)] | zbits,
                             mode="drop")
        return (used, group_bits, resident_anti, gz, az), node

    (_, _, _, _, _), nodes_sorted = jax.lax.scan(
        step, (state.used, state.group_bits, state.resident_anti,
               state.gz_counts, state.az_anti), order)
    # Un-permute back to original pod order.
    assignment = jnp.zeros((p,), jnp.int32).at[order].set(nodes_sorted)
    return jnp.where(pods.pod_valid, assignment, UNASSIGNED)


@partial(jax.jit, static_argnames=("cfg", "with_stats"))
def assign_parallel(state: ClusterState, pods: PodBatch,
                    cfg: SchedulerConfig, static=None, *,
                    with_stats: bool = False):
    """Batched iterative conflict-resolution assignment, ``i32[P]``.

    Each round: every still-unassigned pod argmaxes its masked score
    row; each node that was chosen accepts only its best contender
    (priority desc, pod index asc); usage and masks are updated; pods
    that lost re-pick next round.  Terminates when no unassigned pod has
    a feasible node (bounded by P rounds).

    Round cost: a round changes ``used``/``group_bits``/
    ``resident_anti`` ONLY at the winners' nodes (≤P of N) and retires
    only the winners' rows, so when no pod in the batch carries a
    spread or zone-scoped constraint (whose zone-level state can move
    arbitrary columns) the carried score matrix is updated
    incrementally — an ``O(P²·(R+W))`` column patch instead of the full
    ``O(P·N·(R+W))`` mask recompute (~40× less round work at P=128,
    N=5120).  The full recompute remains the fallback branch and the
    two are equal whenever the predicate holds (tested).

    ``with_stats=True`` additionally returns the executed
    conflict-round count (``i32`` scalar) — the observable VERDICT.md
    round-2 asked for: whether TPU latency will be matmul-bound or
    round-bound is a function of this distribution.
    """
    p = pods.num_pods
    n = state.num_nodes
    raw, static_ok = _static_parts(state, pods, cfg, static)
    w_bal = jnp.float32(cfg.weights.balance)
    pod_ids = jnp.arange(p, dtype=jnp.int32)

    # Loop-invariant: may the incremental round update be used?  Spread
    # and zone-scoped constraints touch per-ZONE state (counts /
    # presence words), so one winner can move columns of every node in
    # its zone; without them, a round's effects are confined to winner
    # columns + winner rows.
    incremental_ok = (~jnp.any(score_lib.spread_active(pods))
                      & jnp.all(pods.zaff_bits == 0)
                      & jnp.all(pods.zanti_bits == 0))
    # Loop-invariant column ids for the per-round second-best
    # computation (XLA does not hoist out of while bodies; an iota
    # materialized per round measurably costs at N=5120).
    col_ids = jax.lax.broadcasted_iota(jnp.int32, (p, n), 1)
    # Under the predicate, zone_affinity_ok is round-invariant (az
    # never changes; gz changes touch only the trivially-true terms),
    # so fold the batch-entry evaluation into the static mask used by
    # the incremental branch.
    static2 = static_ok & score_lib.zone_affinity_ok(state, pods)

    # Loop-invariant tie-break rank: position in (priority desc, index
    # asc) order.  Lets each round pick per-node winners with ONE
    # O(P log P) sort over composite keys instead of O(P*N) one-hot
    # reductions — at P=128, N=5k that removes ~5 full [P, N] passes
    # plus an [N, 2*W*32] matmul from every conflict round (the
    # dominant round cost after the mask recompute).
    order = jnp.argsort(-pods.priority, stable=True)
    rank = jnp.zeros((p,), jnp.int32).at[order].set(pod_ids)
    # Loop-invariant bitplanes of the pods' group/anti words (0/1 i32,
    # ``B = 32 * W`` columns), consumed by the multi-accept prefix's
    # segmented pairwise checks and the winner bit aggregation below.
    mask_b = 32 * pods.group_bit.shape[1]
    gb_planes = bit_planes(pods.group_bit, jnp.int32)
    ab_planes = bit_planes(pods.anti_bits, jnp.int32)
    # Round-invariant piece of the zone-anti round cap (pair [i, j]
    # conflicts AND i outranks j): hoisted here because XLA does not
    # move computations out of while_loop bodies.
    zpair_conflict = (
        (jnp.any(pods.zanti_bits[:, None, :]
                 & pods.group_bit[None, :, :], axis=-1)
         | jnp.any(pods.group_bit[:, None, :]
                   & pods.zanti_bits[None, :, :], axis=-1))
        & (rank[:, None] < rank[None, :]))
    if (n + 1) * p > np.iinfo(np.int32).max:
        # The composite key below would wrap and silently corrupt
        # winner selection; int64 needs jax_enable_x64.  (~16M nodes
        # at P=128 — far past the design envelope, so fail loudly.)
        raise ValueError(
            f"max_nodes*max_pods={n}*{p} overflows the int32 "
            "winner-selection key; reduce the batch or node padding")

    def masked_scores(used, group_bits, resident_anti, gz, az, assignment):
        dyn = _dynamic_mask(pods, used, state.cap, group_bits, resident_anti)
        spread_pen, spread_ok = score_lib.spread_terms(
            state, pods, cfg, gz_counts=gz, static_ok=static_ok)
        zone_ok = score_lib.zone_affinity_ok(state, pods, gz_counts=gz,
                                             az_anti=az)
        ok = (static_ok & dyn & spread_ok & zone_ok
              & (assignment == UNASSIGNED)[:, None])
        rows = raw - w_bal * _balance(pods, used, state.cap) - spread_pen
        return jnp.where(ok, rows, NEG_INF)

    # The score matrix is carried across rounds so it is computed once
    # per round (in body), not twice (cond + body); the continue flag
    # (progress made AND a feasible entry remains) is carried too, so
    # cond reads a scalar instead of reducing [P, N] per evaluation.
    def cond(carry):
        return carry[7]

    def body(carry):
        (s, used, group_bits, resident_anti, gz, az, assignment, _,
         rounds) = carry
        choice = jnp.argmax(s, axis=1).astype(jnp.int32)
        feasible = jnp.take_along_axis(
            s, choice[:, None], axis=1)[:, 0] > NEG_INF * 0.5
        # Winner per contested node (best priority, then lowest pod
        # index): sort unique composite keys ``choice * P + rank``
        # (infeasible pods keyed past every node) and keep the first
        # key of each node group.
        key = jnp.where(feasible, choice * p + rank, n * p + rank)
        perm = jnp.argsort(key)
        group_id = key[perm] // p
        first = jnp.concatenate(
            [jnp.ones((1,), bool), group_id[1:] != group_id[:-1]])

        # Multi-accept prefix: beyond its single best contender, a node
        # also accepts the following contenders (in priority order) as
        # long as they cumulatively fit the node's free capacity AND no
        # pairwise group/anti conflict exists with any earlier prefix
        # member.  Pod-independent metric scores make whole batches of
        # look-alike pods argmax the same node (the reference's
        # pathology, scheduler.go:248, reborn as round count: one
        # winner per round = P rounds); the prefix collapses those to
        # ~capacity-fill rounds.  Exactness: a same-round contender's
        # round-entry checks can only be invalidated by capacity (the
        # segmented cumsum bounds it), host-scoped group state (the
        # pairwise planes check below), or zone state — and the
        # spread/zone round caps after winner selection already demote
        # every same-zone zone-conflicting winner.
        req_sorted = pods.req[perm]                       # [P, R]
        csum = jnp.cumsum(req_sorted, axis=0)
        idx = jnp.arange(p, dtype=jnp.int32)
        # Segment-relative cumulative request: csum minus the running
        # csum at each segment's start (cummax works: csum is
        # monotone, req >= 0).
        base = jnp.where(first[:, None], csum - req_sorted,
                         -jnp.inf)
        seg_csum = csum - jax.lax.cummax(base, axis=0)
        node_sorted = jnp.clip(group_id, 0, n - 1).astype(jnp.int32)
        fits_cum = jnp.all(
            seg_csum <= (state.cap - used)[node_sorted] + _EPS, axis=-1)
        # Greedy-faithfulness guard: accept a prefix member only while
        # the node REMAINS its best choice once the balance penalty is
        # re-priced with everyone queued ahead of it — without this,
        # look-alike batches overpack the round-entry-best node at its
        # stale price (measured: sidecar co-placement fell to 0.79
        # because app nodes were packed solid), where sequential
        # greedy would have spilled to each pod's next-best node.
        # Second-best row value WITHOUT top_k (XLA CPU lowers top_k to
        # a full per-row sort — measured ~70 ms/round at N=5120):
        # mask the argmax column, take the row max again.
        second_best = jnp.max(
            jnp.where(col_ids == choice[:, None], NEG_INF, s), axis=1)
        bal_after = jnp.max(
            (used[node_sorted] + seg_csum)
            / jnp.maximum(state.cap, _EPS)[node_sorted], axis=-1)
        raw_sel = jnp.take_along_axis(
            raw, jnp.clip(choice, 0, n - 1)[:, None], axis=1)[:, 0]
        adj_sorted = raw_sel[perm] - w_bal * bal_after
        stays_best = adj_sorted >= second_best[perm] - 1e-6
        # Segmented EXCLUSIVE cumulative OR of earlier contenders'
        # group/anti bitplanes, via the cummax-with-segment-offset
        # trick (segment ids strictly increase along the sort, so
        # ``2*seg + plane`` from an earlier segment can never reach the
        # current segment's offset).  Checking against all earlier
        # contenders rather than accepted ones is equivalent under
        # stop-at-first-bad: a rejected earlier entry rejects everyone
        # after it anyway.
        seg2 = (group_id * 2).astype(jnp.int32)[:, None]
        incl_gb = jax.lax.cummax(seg2 + gb_planes[perm], axis=0) - seg2
        incl_ab = jax.lax.cummax(seg2 + ab_planes[perm], axis=0) - seg2
        zero_row = jnp.zeros((1, mask_b), jnp.int32)
        excl_gb = jnp.where(first[:, None], 0,
                            jnp.concatenate([zero_row, incl_gb[:-1]],
                                            axis=0)) >= 1
        excl_ab = jnp.where(first[:, None], 0,
                            jnp.concatenate([zero_row, incl_ab[:-1]],
                                            axis=0)) >= 1
        pair_ok = (~jnp.any(excl_ab & (gb_planes[perm] >= 1), axis=1)
                   & ~jnp.any(excl_gb & (ab_planes[perm] >= 1), axis=1))
        good = fits_cum & pair_ok & stays_best
        seg_start = jax.lax.cummax(jnp.where(first, idx, -1))
        last_bad = jax.lax.cummax(jnp.where(~good, idx, -1))
        prefix_ok = last_bad < seg_start  # all good since segment start
        winner = jnp.zeros((p,), bool).at[perm].set(
            (first | prefix_ok) & (group_id < n))

        # Topology-spread round cap: the per-winner skew check above
        # ran against ROUND-ENTRY counts, so two same-group winners on
        # DISTINCT nodes of one zone would together overshoot maxSkew.
        # Demote all but the best-ranked spread-active winner per
        # (group, zone) — each accepted winner's +1 was individually
        # checked, and the demoted pods re-pick next round against
        # updated counts (conservative: never more rounds than pods).
        zone_of = state.node_zone[jnp.clip(choice, 0, n - 1)]
        s_active = winner & score_lib.spread_active(pods) & (zone_of >= 0)
        gzmax = state.gz_counts.shape[0] * state.gz_counts.shape[1]
        gz_id = jnp.where(
            s_active,
            pods.group_idx * state.gz_counts.shape[1] + zone_of,
            gzmax + rank)  # inert pods: unique singleton groups
        key2 = gz_id * p + rank
        perm2 = jnp.argsort(key2)
        gid2 = key2[perm2] // p
        first2 = jnp.concatenate(
            [jnp.ones((1,), bool), gid2[1:] != gid2[:-1]])
        winner = winner & jnp.zeros((p,), bool).at[perm2].set(first2)

        # Zone-anti round cap: the per-winner zone checks ran against
        # ROUND-ENTRY state, so winner A (group g) and winner B
        # (zone-anti g) landing in ONE zone the same round would
        # violate what B's next-round check would reject.  Demote any
        # winner that zone-conflicts with a better-ranked same-zone
        # winner (pairwise [P, P] masks — tiny next to the [P, N]
        # score matrix); the demoted pods re-pick next round against
        # committed counts.
        zsame = (winner[:, None] & winner[None, :]
                 & (zone_of[:, None] == zone_of[None, :])
                 & (zone_of >= 0)[:, None])
        demote = jnp.any(zsame & zpair_conflict, axis=0)
        winner = winner & ~demote

        new_assignment = jnp.where(winner, choice, assignment)
        safe = jnp.where(winner, choice, 0)
        add = jnp.where(winner[:, None], pods.req, 0.0)
        new_used = used.at[safe].add(add, mode="drop")
        progress = jnp.any(winner)
        # Group bit-field updates: one scatter-set per NODE segment
        # (never colliding), carrying the segmented OR of the FINAL
        # winners' planes (post-demote — a demoted pod's bits must not
        # be published).  Re-uses the sorted segment machinery; the
        # cummax trick again gives the per-segment running OR, read at
        # each segment's last row.
        win_sorted = winner[perm][:, None]
        or_gb = (jax.lax.cummax(seg2 + gb_planes[perm] * win_sorted,
                                axis=0) - seg2) >= 1
        or_ab = (jax.lax.cummax(seg2 + ab_planes[perm] * win_sorted,
                                axis=0) - seg2) >= 1
        last_of_seg = jnp.concatenate(
            [first[1:], jnp.ones((1,), bool)])
        seg_cols = jnp.where(last_of_seg & (group_id < n),
                             node_sorted, n)
        new_group = group_bits.at[seg_cols].set(
            group_bits[jnp.clip(seg_cols, 0, n - 1)]
            | planes_to_words(or_gb), mode="drop")
        new_anti = resident_anti.at[seg_cols].set(
            resident_anti[jnp.clip(seg_cols, 0, n - 1)]
            | planes_to_words(or_ab), mode="drop")
        new_gz = add_zone_counts(gz, state.node_zone, pods.group_bit,
                                 choice, winner)
        # Winner ZONES are not unique (several nodes share one), so
        # the zone-anti residency update is a scatter-OR over a
        # [P, Z] one-hot, not a set.
        zmax = az.shape[0]
        zhot = (winner & (zone_of >= 0))[:, None] & (
            jnp.clip(zone_of, 0, zmax - 1)[:, None]
            == jnp.arange(zmax)[None, :])
        new_az = az | scatter_or_onehot(zhot, pods.zanti_bits)

        def full_update(_):
            return masked_scores(new_used, new_group, new_anti, new_gz,
                                 new_az, new_assignment)

        def incremental_update(_):
            # Patch only the winners' columns (losers carry the
            # sentinel column n -> dropped by the scatter) and retire
            # assigned rows; everything else is unchanged by this
            # round under the incremental_ok predicate.  Duplicate
            # winner columns (a multi-accept prefix) are harmless: each
            # writes the identical recomputed column.
            wcols = jnp.where(winner, choice, n)
            cc = jnp.clip(wcols, 0, n - 1)
            sub_used = new_used[cc]                       # [P, R]
            sub_cap = state.cap[cc]
            fits = jnp.all(
                pods.req[:, None, :] <= (sub_cap - sub_used)[None, :, :]
                + _EPS, axis=-1)                          # [P, Pc]
            gb = new_group[cc]                            # [Pc, W]
            ra = new_anti[cc]
            aff_req = pods.affinity_bits[:, None, :]
            affinity = jnp.all(
                (gb[None, :, :] & aff_req) == aff_req, axis=-1)
            aok = jnp.all(
                (gb[None, :, :] & pods.anti_bits[:, None, :]) == 0,
                axis=-1)
            sym = jnp.all(
                (ra[None, :, :] & pods.group_bit[:, None, :]) == 0,
                axis=-1)
            bal = jnp.max(
                (sub_used[None, :, :] + pods.req[:, None, :])
                / jnp.maximum(sub_cap, _EPS)[None, :, :], axis=-1)
            ok = (static2[:, cc] & fits & affinity & aok & sym
                  & (new_assignment == UNASSIGNED)[:, None])
            sub = jnp.where(ok, raw[:, cc] - w_bal * bal, NEG_INF)
            s2 = s.at[:, wcols].set(sub, mode="drop")
            # Retire the winners' ROWS via a row scatter (losers and
            # previously-assigned rows are already NEG_INF) — a full
            # [P, N] where re-writes the whole matrix every round.
            wrows = jnp.where(winner, pod_ids, p)
            return s2.at[wrows].set(NEG_INF, mode="drop")

        new_s = jax.lax.cond(incremental_ok, incremental_update,
                             full_update, None)
        cont = progress & jnp.any(new_s > NEG_INF * 0.5)
        return (new_s, new_used, new_group, new_anti, new_gz, new_az,
                new_assignment, cont, rounds + 1)

    init_assignment = jnp.full((p,), UNASSIGNED, jnp.int32)
    s0 = masked_scores(state.used, state.group_bits, state.resident_anti,
                       state.gz_counts, state.az_anti, init_assignment)
    init = (s0,
            state.used, state.group_bits, state.resident_anti,
            state.gz_counts, state.az_anti, init_assignment,
            jnp.any(s0 > NEG_INF * 0.5), jnp.int32(0))
    out = jax.lax.while_loop(cond, body, init)
    assignment, rounds = out[6], out[8]
    assignment = jnp.where(pods.pod_valid, assignment, UNASSIGNED)
    if with_stats:
        return assignment, rounds
    return assignment


def schedule_batch(state: ClusterState, pods: PodBatch, cfg: SchedulerConfig,
                   method: str = "parallel"):
    """Score + assign + commit: returns ``(assignment, new_state)``.

    The device-side core of the reference's ``Schedule()`` cycle
    (scheduler.go:189-237); the host-side binder turns the assignment
    vector into Bind/Event API calls.
    """
    if method == "greedy":
        assignment = assign_greedy(state, pods, cfg)
    elif method == "parallel":
        assignment = assign_parallel(state, pods, cfg)
    else:
        raise ValueError(f"unknown method {method!r}")
    return assignment, commit_assignments(state, pods, assignment)
