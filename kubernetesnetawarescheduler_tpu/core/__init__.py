"""Core scheduling engine: device-resident state, scoring, assignment."""

from kubernetesnetawarescheduler_tpu.core.state import (  # noqa: F401
    ClusterState,
    PodBatch,
    init_cluster_state,
    init_pod_batch,
)
from kubernetesnetawarescheduler_tpu.core.score import (  # noqa: F401
    score_pods,
    feasibility_mask,
    NEG_INF,
)
from kubernetesnetawarescheduler_tpu.core.assign import (  # noqa: F401
    assign_greedy,
    assign_parallel,
    schedule_batch,
)
from kubernetesnetawarescheduler_tpu.core.pallas_score import (  # noqa: F401
    score_pods_auto,
    score_pods_tiled,
)
from kubernetesnetawarescheduler_tpu.core.replay import (  # noqa: F401
    PodStream,
    pad_stream,
    replay_stream,
)
