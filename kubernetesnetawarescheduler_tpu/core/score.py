"""The score/filter kernel: batched pod x node scoring on the MXU.

This replaces the reference's ``prioritize()`` (scheduler.go:248-368),
which per scheduled pod performed 5 serial HTTP scrapes, ~25 substring
scans and a winner-takes-all vote (+3 best CPU, +2 best mem, +1 best
tx/rx, +3 best bandwidth, +1 best disk; scheduler.go:360-365) over a
hardcoded 5-node set, ignoring the pod entirely (its ``pod`` argument is
never read).  Known reference bugs intentionally NOT reproduced: the
bandwidth winner overwrote ``bestNetSentNode`` so the +3 bandwidth vote
went to a dead key (scheduler.go:351-354, :364), and the map-iteration
argmax tie-break was nondeterministic (scheduler.go:384-394).

Here, for a batch of ``P`` pods against ``N`` nodes with up to ``K``
peers each:

    score[p, n] = metric_score[n]                    (continuous vote)
                + (T @ C)[p, n]                      (network cost, MXU)
                - w_balance * worst_fit[p, n]        (soft bin packing)
                + (-inf if infeasible)               (batched masks)

where ``T[P, N]`` is the per-pod traffic-to-node matrix (scatter-added
from the peer lists) and ``C[N, N] = w_bw * bw_norm - w_lat * lat_norm``
is the pairwise network desirability matrix.  Expressing the peer
reduction as a dense ``[P, N] @ [N, N]`` matmul is the TPU-first move:
the gather/reduce the reference does with files and loops rides the
128x128 systolic array in bfloat16.

Everything is shape-static and jit-compatible; no data-dependent Python
control flow.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from kubernetesnetawarescheduler_tpu.config import (
    GOODNESS,
    SchedulerConfig,
)
from kubernetesnetawarescheduler_tpu.core.state import ClusterState, PodBatch

# np scalar, not jnp: a module-level jnp constant would initialize the
# JAX backend at import time, locking the platform before callers
# (tests, dryrun_multichip) can select cpu + virtual device count.
NEG_INF = np.float32(-1e30)
_EPS = 1e-9

# Canonical order of the flat weight vector the scoring functions
# optionally accept as a TRACED argument (policy/ counterfactual
# re-scoring: weight changes become new scalar inputs to the SAME
# compiled program instead of a retrace).  Matches ScoreWeights field
# order; policy/model.WEIGHT_FIELDS mirrors it.
WVEC_FIELDS = ("cpu", "mem", "net_tx", "net_rx", "bandwidth", "disk",
               "peer_bw", "peer_lat", "balance", "soft_affinity",
               "spread")


def weights_vector(weights) -> np.ndarray:
    """Flatten a :class:`ScoreWeights` into the canonical ``f32[11]``
    vector the ``wvec`` arguments below consume.  Passing
    ``weights_vector(cfg.weights)`` is numerically identical to
    passing ``wvec=None`` (the constants default) — pinned by
    tests/test_policy.py."""
    return np.asarray([float(getattr(weights, f))
                       for f in WVEC_FIELDS], np.float32)


def normalize_metrics(metrics: jax.Array, node_valid: jax.Array,
                      goodness: jax.Array) -> jax.Array:
    """Min-max normalize each metric channel over valid nodes to [0, 1],
    flipped so that 1.0 is always "best".

    The reference's analog is the sentinel-initialized min/max sweep
    (scheduler.go:258-265, :334-359): only the single winner per metric
    got credit.  Continuous normalization keeps the same ordering while
    making scores informative for every node.
    """
    valid = node_valid[:, None]
    big = jnp.float32(3.4e38)
    lo = jnp.min(jnp.where(valid, metrics, big), axis=0)
    hi = jnp.max(jnp.where(valid, metrics, -big), axis=0)
    span = jnp.maximum(hi - lo, _EPS)
    unit = (metrics - lo[None, :]) / span[None, :]
    unit = jnp.clip(unit, 0.0, 1.0)
    # goodness=+1 -> keep; goodness=-1 -> 1 - unit.
    flipped = jnp.where(goodness[None, :] > 0, unit, 1.0 - unit)
    return jnp.where(valid, flipped, 0.0)


def metric_scores(state: ClusterState, cfg: SchedulerConfig,
                  wvec: jax.Array | None = None) -> jax.Array:
    """Pod-independent per-node score ``f32[N]``: the weighted continuous
    vote over normalized metrics, decayed by staleness.

    Staleness decay replaces the reference's synchronous re-scrape per
    pod (scheduler.go:275-279): a node whose telemetry is old drifts
    toward a neutral 0.5 per channel instead of being trusted blindly.
    Nodes below ``cfg.stale_conf_floor`` confidence are also excluded
    from the normalization span, so a silent node's last (possibly
    extreme) readings cannot stretch the span and make every fresh node
    look bad while the silent one coasts on the neutral blend.

    ``wvec`` (see :data:`WVEC_FIELDS`): optional traced weight vector;
    ``None`` (the default) bakes ``cfg.weights`` in as constants —
    bit-identical to the pre-policy scorer.
    """
    goodness = jnp.asarray(GOODNESS + (0.0,) * (cfg.num_metrics - len(GOODNESS)),
                           jnp.float32)
    if wvec is None:
        w = jnp.asarray(cfg.weights.metric_vector() +
                        (0.0,) * (cfg.num_metrics - len(GOODNESS)),
                        jnp.float32)
    else:
        w = jnp.pad(wvec[:len(GOODNESS)].astype(jnp.float32),
                    (0, cfg.num_metrics - len(GOODNESS)))
    conf = jnp.exp(-state.metrics_age / cfg.staleness_tau_s)
    span_valid = state.node_valid & (conf > cfg.stale_conf_floor)
    norm = normalize_metrics(state.metrics, span_valid, goodness)
    blended = conf[:, None] * norm + (1.0 - conf[:, None]) * 0.5
    score = blended @ w
    return jnp.where(state.node_valid, score, 0.0)


def peer_traffic_matrix(pods: PodBatch, num_nodes: int) -> jax.Array:
    """Scatter the ragged peer lists into a dense ``T[P, N]`` traffic
    matrix (CSR -> padded dense; peers with index -1 are dropped).

    This is the densification step that turns the per-peer gather into
    an MXU matmul.  The reference's counterpart is one iperf3 JSON file
    read per node per pod (scheduler.go:503-530).
    """
    p, k = pods.peers.shape
    valid = (pods.peers >= 0) & pods.pod_valid[:, None]
    safe = jnp.where(valid, pods.peers, 0)
    traffic = jnp.where(valid, pods.peer_traffic, 0.0)
    t = jnp.zeros((p, num_nodes), jnp.float32)
    return t.at[jnp.arange(p)[:, None], safe].add(traffic, mode="drop")


def net_desirability(lat: jax.Array, bw: jax.Array,
                     node_valid: jax.Array, w_bw: jax.Array,
                     w_lat: jax.Array) -> jax.Array:
    """``C[N, N] = w_bw * bw_norm - w_lat * lat_norm`` from raw
    lat/bw planes — the pure core of :func:`net_cost_matrix`, split
    out so the outcome-quality evaluator (obs/quality.py) scores
    REALIZED placements with the exact same desirability semantics
    the scheduler optimized at decision time (same normalization,
    same loopback-diagonal pin): regret-vs-best-alternative is then
    measured in genuine score units, not a lookalike metric."""
    pair_valid = node_valid[:, None] & node_valid[None, :]
    bw_max = jnp.maximum(jnp.max(jnp.where(pair_valid, bw, 0.0)), _EPS)
    lat_max = jnp.maximum(jnp.max(jnp.where(pair_valid, lat, 0.0)),
                          _EPS)
    c = w_bw * bw / bw_max - w_lat * lat / lat_max
    eye = jnp.eye(lat.shape[0], dtype=bool)
    c = jnp.where(eye, w_bw, c)
    return jnp.where(pair_valid, c, 0.0)


def net_cost_matrix(state: ClusterState, cfg: SchedulerConfig,
                    wvec: jax.Array | None = None) -> jax.Array:
    """``C[N, N] = w_bw * bw_norm - w_lat * lat_norm``, the desirability
    of placing one end of a flow on row-node given the other end on
    column-node.  Normalized by the max over valid pairs so weights are
    scale-free.

    The diagonal is pinned to the best possible value (``w_bw``):
    co-located endpoints talk over loopback, which no physical link
    beats — regardless of what the probe pipeline wrote into
    ``bw[i, i]`` (iperf never measures a node against itself;
    run.sh:12 probes client->server pairs only)."""
    if wvec is None:
        w_bw = jnp.float32(cfg.weights.peer_bw)
        w_lat = jnp.float32(cfg.weights.peer_lat)
    else:
        w_bw, w_lat = wvec[6], wvec[7]
    return net_desirability(
        state.lat, state.bw, state.node_valid, w_bw, w_lat)


def _use_bf16(cfg: SchedulerConfig) -> bool:
    """bf16 compute only on TPU: the MXU's native format there, but
    XLA CPU's DotThunk rejects BF16xBF16->F32 outright at some shapes
    (and emulates it ~50x slower where it is supported) — the same
    backend gate as ``state._plane_dtype``."""
    return cfg.use_bfloat16 and jax.default_backend() == "tpu"


def prep_net_matrix(c: jax.Array, cfg: SchedulerConfig) -> jax.Array:
    """Transpose (and cast, in bf16 mode on TPU) the desirability
    matrix into the layout the score matmul consumes.  At N=5k this
    touches 100 MB — done once per replay/static-compute, NOT per
    batch: inside one jitted scan XLA hoists it as loop-invariant, but
    a chunked/pipelined drain dispatches many separate executables and
    would otherwise re-transpose per chunk (measured ~2x per-batch
    cost on the CPU fallback)."""
    ct = c.T
    return ct.astype(jnp.bfloat16) if _use_bf16(cfg) else ct


def static_node_scores(state: ClusterState, cfg: SchedulerConfig,
                       wvec: jax.Array | None = None
                       ) -> tuple[jax.Array, jax.Array]:
    """The two batch-invariant score ingredients: the per-node metric
    vote ``base f32[N]`` and the PREPARED net-desirability matrix
    ``C.T`` (:func:`prep_net_matrix` layout/dtype).

    Neither depends on the pod batch nor on anything placements mutate
    (``used``/``group_bits``/``resident_anti``), so a replay loop can
    compute them ONCE and reuse them for every batch instead of
    re-deriving ~3 HBM passes over the N×N matrices per batch (the
    device-side analog of the reference re-scraping every node per pod,
    scheduler.go:275-279)."""
    return (metric_scores(state, cfg, wvec=wvec),
            prep_net_matrix(net_cost_matrix(state, cfg, wvec=wvec),
                            cfg))


class NetExtrema(NamedTuple):
    """Host-side running normalizers of :func:`net_cost_matrix`:
    the masked maxima of ``bw``/``lat`` over valid pairs BEFORE the
    ``_EPS`` clamp, plus the flat index of a pair currently holding
    each maximum.  The tracked holder makes retreat detection exact:
    as long as the holder pair is not in a dirty set, its value still
    equals the recorded maximum, so a running ``max(old, dirty max)``
    is bit-identical to a full re-scan; only when the holder itself is
    dirtied can the true maximum retreat, forcing a re-scan."""
    bw_m: float
    lat_m: float
    bw_arg: int
    lat_arg: int


def net_extrema_scan(state: ClusterState) -> NetExtrema:
    """Full O(N^2) extrema scan (device reduce, host scalars).  The
    float() round-trip through f64 is exact for f32 values, so feeding
    these back through ``jnp.float32`` reconstructs the identical
    normalizer scalars :func:`net_cost_matrix` derives on device."""
    pv = state.node_valid[:, None] & state.node_valid[None, :]
    bwm = jnp.where(pv, state.bw, 0.0)
    latm = jnp.where(pv, state.lat, 0.0)
    bi = int(jnp.argmax(bwm))
    li = int(jnp.argmax(latm))
    return NetExtrema(float(bwm.reshape(-1)[bi]),
                      float(latm.reshape(-1)[li]), bi, li)


def net_extrema_update(state: ClusterState, ex: NetExtrema,
                       ii: np.ndarray, jj: np.ndarray) -> NetExtrema:
    """Update :class:`NetExtrema` after only pairs ``(ii, jj)`` of
    ``bw``/``lat`` changed.  Bit-identical to :func:`net_extrema_scan`
    in the max VALUES (the tracked holder may differ from argmax's
    first-index tie-break, which only affects when a future re-scan
    triggers, never the normalizers)."""
    if len(ii) == 0:
        return ex
    n = state.bw.shape[0]
    flat = ii.astype(np.int64) * n + jj.astype(np.int64)
    dirty = set(flat.tolist())
    iid = jnp.asarray(ii)
    jjd = jnp.asarray(jj)
    pv = state.node_valid[iid] & state.node_valid[jjd]
    vb = jnp.where(pv, state.bw[iid, jjd], 0.0)
    vl = jnp.where(pv, state.lat[iid, jjd], 0.0)

    def one(m, arg, vals):
        if arg in dirty:
            return None  # holder dirtied: the max may have retreated
        k = int(jnp.argmax(vals))
        v = float(vals[k])
        return (v, int(flat[k])) if v > m else (m, arg)

    nb = one(ex.bw_m, ex.bw_arg, vb)
    nl = one(ex.lat_m, ex.lat_arg, vl)
    if nb is None or nl is None:
        full = net_extrema_scan(state)
        return NetExtrema(full.bw_m if nb is None else nb[0],
                          full.lat_m if nl is None else nl[0],
                          full.bw_arg if nb is None else nb[1],
                          full.lat_arg if nl is None else nl[1])
    return NetExtrema(nb[0], nl[0], nb[1], nl[1])


def static_node_scores_delta(
        state: ClusterState, cfg: SchedulerConfig,
        prev: tuple[jax.Array, jax.Array], ex: NetExtrema,
        ii: np.ndarray, jj: np.ndarray,
) -> tuple[tuple[jax.Array, jax.Array], NetExtrema]:
    """Delta rebuild of :func:`static_node_scores`, bit-identical to
    the full path (property-tested in test_static_delta).

    Preconditions: since ``prev`` was built, only net elements
    ``(ii, jj)`` changed (both orientations listed) and topology/
    validity did not.  ``base`` is O(N*M) and recomputed outright —
    the delta machinery only defends the O(N^2) matrix work.  When a
    normalizer MOVES, every element of ``C`` rescales, so the matrix
    falls back to a full rebuild; the common case (probe jitter below
    the running maxima) patches just the dirty columns of ``C.T``."""
    base = metric_scores(state, cfg)
    ex2 = net_extrema_update(state, ex, ii, jj)
    if ex2.bw_m != ex.bw_m or ex2.lat_m != ex.lat_m:
        return (base, prep_net_matrix(net_cost_matrix(state, cfg),
                                      cfg)), ex2
    _, ct = prev
    if len(ii) == 0:
        return (base, ct), ex2
    iid = jnp.asarray(ii)
    jjd = jnp.asarray(jj)
    bw_max = jnp.maximum(jnp.float32(ex2.bw_m), _EPS)
    lat_max = jnp.maximum(jnp.float32(ex2.lat_m), _EPS)
    vals = (cfg.weights.peer_bw * state.bw[iid, jjd] / bw_max
            - cfg.weights.peer_lat * state.lat[iid, jjd] / lat_max)
    vals = jnp.where(iid == jjd, cfg.weights.peer_bw, vals)
    pv = state.node_valid[iid] & state.node_valid[jjd]
    vals = jnp.where(pv, vals, 0.0)
    if _use_bf16(cfg):
        vals = vals.astype(jnp.bfloat16)
    # prev holds C.T: element (i, j) of C lives at (j, i).
    return (base, ct.at[jjd, iid].set(vals)), ex2


def network_scores(state: ClusterState, pods: PodBatch,
                   cfg: SchedulerConfig,
                   ct: jax.Array | None = None,
                   transposed: bool = False) -> jax.Array:
    """Pod-aware network term ``f32[P, N]`` (``f32[N, P]`` with
    ``transposed=True`` — the node-major layout the conflict loop
    carries; the gather path emits it natively via the einsum output
    spec, no transpose pass).

    ``ct`` lets callers pass a precomputed :func:`prep_net_matrix`
    (the transposed, compute-dtype desirability matrix).

    Two algebraically identical forms, picked by static shape:

    - **peer gather** (``K`` small relative to ``N``, the common case —
      a pod talks to a handful of peers): ``net[p, :] = Σ_k
      traffic[p, k] · C.T[node(k), :]`` gathers ``K`` rows of the
      prepared matrix per pod and weight-sums them — ``O(P·K·N)`` work
      instead of the matmul's ``O(P·N·N)`` contraction (2500× less at
      K=4, N=5120; the dense form cost the CPU fallback ~60 ms/batch).
    - **dense MXU matmul** (``K`` comparable to ``N``): densify to
      ``T[P, N]`` and ride the systolic array.
    """
    n = state.num_nodes
    if ct is None:
        ct = prep_net_matrix(net_cost_matrix(state, cfg), cfg)
    k = pods.peers.shape[1]
    if k * 16 <= n:
        valid = (pods.peers >= 0) & pods.pod_valid[:, None]
        safe = jnp.where(valid, pods.peers, 0)
        traffic = jnp.where(valid, pods.peer_traffic, 0.0)
        rows = ct[safe].astype(jnp.float32)        # [P, K, N]
        out = "np" if transposed else "pn"
        return jnp.einsum(f"pk,pkn->{out}", traffic, rows)
    t = peer_traffic_matrix(pods, n)
    if _use_bf16(cfg):
        # bf16 inputs, f32 accumulation: standard MXU recipe.
        net = jnp.dot(t.astype(jnp.bfloat16), ct,
                      preferred_element_type=jnp.float32)
    elif cfg.use_bfloat16:
        # bf16 requested but not on TPU: plain f32 matmul (the ct
        # prep also stayed f32 — see _use_bf16).
        net = jnp.dot(t, ct)
    else:
        # Full f32: on TPU the default matmul precision is bf16
        # passes, so ask for HIGHEST explicitly when exactness is
        # requested.
        net = jnp.dot(t, ct, precision=jax.lax.Precision.HIGHEST)
    return net.T if transposed else net


def soft_affinity_scores(state: ClusterState, pods: PodBatch,
                         cfg: SchedulerConfig,
                         transposed: bool = False,
                         wvec: jax.Array | None = None) -> jax.Array:
    """Weighted preferred-affinity score term ``f32[P, N]``
    (``f32[N, P]`` with ``transposed=True`` — the dead branch then
    materializes node-major zeros directly, so constraint-free
    batches pay no per-batch transpose; the live banks transpose at
    the seam, only when soft terms are actually present).

    The score-side counterpart of the hard masks in
    :func:`feasibility_mask` — ``preferredDuringSchedulingIgnoredDuring
    Execution`` semantics, which the reference's own probe deployment
    used to pull its iperf3 server toward the master node
    (netperfScript/deployment.yaml:17-26) while delegating evaluation
    to stock kube-scheduler.  Two term banks per pod (``T`` terms
    each):

    - node-label terms: bonus ``w_t`` on nodes carrying ALL of the
      term's labels (``soft_sel_bits`` ⊆ ``label_bits``); empty terms
      (padding) contribute nothing.
    - pod-group terms: bonus ``w_t`` on nodes whose resident pods
      include the term's group (ANY overlap with ``group_bits``) —
      negative ``w_t`` is preferred spreading (soft anti-affinity).

    Weights follow the k8s 1-100 scale; ``cfg.weights.soft_affinity``
    scales the sum into normalized-score units (/100, so a weight-100
    term moves a node by ``soft_affinity`` score units).

    Group terms are evaluated against the batch-entry ``group_bits``
    (same-batch placements do not attract each other within the batch)
    — matching kube-scheduler, which scores each pod against committed
    state only; hard affinity, by contrast, is re-derived per
    conflict-resolution round.

    Gated behind a ``lax.cond`` like the spread/zone/nodeAffinity
    blocks: batches with no soft terms — the common case — skip the
    ``[P, T, N, W]`` bit reductions entirely.
    """
    p = pods.pod_valid.shape[0]
    n = state.node_valid.shape[0]

    def live(_):
        lb = state.label_bits[None, None, :, :]        # [1, 1, N, W]
        sb = pods.soft_sel_bits[:, :, None, :]         # [P, T, 1, W]
        label_match = jnp.all((lb & sb) == sb, axis=-1)        # [P, T, N]
        nonempty = jnp.any(pods.soft_sel_bits != 0, axis=-1)   # [P, T]
        label_term = jnp.sum(
            jnp.where(nonempty[:, :, None] & label_match,
                      pods.soft_sel_w[:, :, None], 0.0), axis=1)
        gb = state.group_bits[None, None, :, :]
        pg = pods.soft_grp_bits[:, :, None, :]
        group_match = jnp.any((gb & pg) != 0, axis=-1)
        group_term = jnp.sum(
            jnp.where(group_match, pods.soft_grp_w[:, :, None], 0.0),
            axis=1)
        if wvec is None:
            scale = jnp.float32(cfg.weights.soft_affinity / 100.0)
        else:
            scale = wvec[9] / 100.0
        out = scale * (label_term + group_term)
        return out.T if transposed else out

    shape = (n, p) if transposed else (p, n)
    pred = (jnp.any(pods.soft_sel_bits != 0)
            | jnp.any(pods.soft_grp_bits != 0))
    bank = jax.lax.cond(pred, live,
                        lambda _: jnp.zeros(shape, jnp.float32), None)
    return bank + soft_zone_scores(state, pods, cfg,
                                   transposed=transposed, wvec=wvec)


def soft_zone_scores(state: ClusterState, pods: PodBatch,
                     cfg: SchedulerConfig,
                     transposed: bool = False,
                     wvec: jax.Array | None = None) -> jax.Array:
    """Zone-scoped preferred pod (anti-)affinity term, ``f32[P, N]``:
    bonus ``w_t`` on nodes whose ZONE hosts a member of the term's
    group (``gz_counts`` presence, like the hard
    :func:`zone_affinity_ok` but weighted); negative weight =
    preferred zone spreading.  Zone-less nodes are empty domains —
    no term matches there.  Exposed separately from
    :func:`soft_affinity_scores` because the tiled Pallas kernel
    computes the label/group banks in its epilogue and joins this
    term outside the tiles; the dense path gets it via
    ``soft_affinity_scores``.  Gated: batches without zone terms pay
    one scalar reduction."""
    p = pods.pod_valid.shape[0]
    n = state.node_valid.shape[0]

    def live(_):
        from kubernetesnetawarescheduler_tpu.core.state import (
            planes_to_words,
        )

        zmax = state.az_anti.shape[0]
        zwords = planes_to_words((state.gz_counts > 0).T)   # u32[Z, W]
        has_zone = state.node_zone >= 0
        pres = zwords[jnp.clip(state.node_zone, 0, zmax - 1)]  # [N, W]
        zb = pods.soft_zone_bits[:, :, None, :]             # [P, T, 1, W]
        zmatch = (jnp.any((pres[None, None, :, :] & zb) != 0, axis=-1)
                  & has_zone[None, None, :])                # [P, T, N]
        term = jnp.sum(
            jnp.where(zmatch, pods.soft_zone_w[:, :, None], 0.0), axis=1)
        if wvec is None:
            scale = jnp.float32(cfg.weights.soft_affinity / 100.0)
        else:
            scale = wvec[9] / 100.0
        out = scale * term
        return out.T if transposed else out

    shape = (n, p) if transposed else (p, n)
    return jax.lax.cond(jnp.any(pods.soft_zone_bits != 0), live,
                        lambda _: jnp.zeros(shape, jnp.float32), None)


def spread_active(pods: PodBatch) -> jax.Array:
    """``bool[P]``: which pods carry a live topology-spread
    constraint.  The single source of truth for gating the spread
    block off the hot path — :func:`spread_terms` and the tiled
    Pallas join (pallas_score.py) must agree on this predicate or the
    tiled path would silently skip spread for batches the dense path
    treats as active."""
    return ((pods.spread_maxskew > 0) & (pods.group_idx >= 0)
            & pods.pod_valid)


def spread_terms(state: ClusterState, pods: PodBatch,
                 cfg: SchedulerConfig,
                 gz_counts: jax.Array | None = None,
                 static_ok: jax.Array | None = None,
                 wvec: jax.Array | None = None
                 ) -> tuple[jax.Array, jax.Array]:
    """Topology-spread penalty and mask, ``(f32[P, N], bool[P, N])``.

    ``topologySpreadConstraints`` at zone granularity: for a pod whose
    group has ``count[z]`` members in zone ``z``, placing on a node of
    zone ``z`` is allowed iff ``count[z] + 1 - min(count) <= maxSkew``
    (kube-scheduler's PodTopologySpread filter formula).  Hard
    constraints (``whenUnsatisfiable: DoNotSchedule``) mask; soft ones
    (``ScheduleAnyway``) pay ``weights.spread`` per unit of excess
    skew.  The counts are DYNAMIC state (placements move them): the
    conflict loop passes its current ``gz_counts`` carry.

    ``static_ok`` (bool[P, N], the pod's taints/selector/validity
    mask) scopes the min to each pod's ELIGIBLE domains —
    kube-scheduler's default ``nodeAffinityPolicy: Honor``: a zone the
    pod cannot land in anyway (e.g. no gpu nodes) must not drag
    ``min(count)`` to 0 and mask every reachable zone forever.  Since
    every eligible node of zone z sees ``count[z]``, the per-zone min
    is just a masked min over the per-node counts — no zone scatter.
    Without ``static_ok`` the min falls back to all zones holding a
    valid node (stricter, never over-admits).

    Documented deviations from kube-scheduler: the counted pod set is
    the pod's own ``group`` (the same hostname-topology reduction the
    affinity masks use) rather than an arbitrary labelSelector; nodes
    with no interned zone (missing label or zone-interner overflow)
    are neither masked nor counted — the constraint degrades open on
    them instead of making whole nodes unschedulable on a bookkeeping
    boundary; and domain eligibility honors BOTH the selector and the
    pod's taint tolerations (kube's ``nodeAffinityPolicy: Honor`` +
    ``nodeTaintsPolicy: Honor`` — kube defaults taints to Ignore, so
    a fully-tainted zone here drops out of the min instead of
    blocking the pod everywhere).
    """
    gz = state.gz_counts if gz_counts is None else gz_counts
    g, z = gz.shape
    n = state.num_nodes
    p = pods.num_pods
    active = spread_active(pods)

    def live(_):
        cpz = gz[jnp.clip(pods.group_idx, 0, g - 1)]        # [P, Z]
        has_zone = state.node_zone >= 0
        cnt = cpz[:, jnp.clip(state.node_zone, 0, z - 1)]   # [P, N]
        big = jnp.int32(2**30)
        if static_ok is not None:
            # Honor semantics: min over the pod's eligible domains.
            elig = static_ok & has_zone[None, :]
            min_c = jnp.min(jnp.where(elig, cnt, big), axis=1)
        else:
            # Zones that exist: >= 1 valid node interned into them.
            nz = jnp.where(state.node_valid & (state.node_zone >= 0),
                           state.node_zone, z)
            zone_valid = jnp.zeros((z,), bool).at[nz].set(
                True, mode="drop")
            min_c = jnp.min(jnp.where(zone_valid[None, :], cpz, big),
                            axis=1)
        skew_after = cnt + 1 - min_c[:, None]
        violates = (active[:, None] & has_zone[None, :]
                    & (skew_after > pods.spread_maxskew[:, None]))
        ok = ~(violates & pods.spread_hard[:, None])
        excess = jnp.maximum(
            skew_after - pods.spread_maxskew[:, None],
            0).astype(jnp.float32)
        if wvec is None:
            w_spread = jnp.float32(cfg.weights.spread)
        else:
            w_spread = wvec[10]
        penalty = jnp.where(violates & ~pods.spread_hard[:, None],
                            w_spread * excess, 0.0)
        return penalty, ok

    def dead(_):
        return (jnp.zeros((p, n), jnp.float32), jnp.ones((p, n), bool))

    # Workloads without spread constraints (most of them) skip the
    # [P, N] count gathers entirely — this runs per conflict round,
    # and the ungated form cost the round loop ~13% with zero active
    # pods (measured, CPU device-mode replay).
    return jax.lax.cond(jnp.any(active), live, dead, None)


def balance_penalty(state: ClusterState, pods: PodBatch) -> jax.Array:
    """Worst-fit fractional utilization after placement, ``f32[P, N]``:
    ``max_r (used[n,r] + req[p,r]) / cap[n,r]``.  Soft bin-packing
    pressure; the reference has no notion of this (pod requests unused,
    scheduler.go:248)."""
    cap = jnp.maximum(state.cap, _EPS)
    frac = (state.used[None, :, :] + pods.req[:, None, :]) / cap[None, :, :]
    return jnp.max(frac, axis=-1)


def ns_affinity_ok(state: ClusterState, pods: PodBatch,
                   transposed: bool = False) -> jax.Array:
    """Hard nodeAffinity matchExpressions mask, ``bool[P, N]``
    (``bool[N, P]`` with ``transposed=True``; the common no-terms
    branch then materializes node-major ones directly — no transpose
    pass).

    A pod passes a node when ANY of its OR'd ``nodeSelectorTerms``
    passes; a term passes when ALL its any-of expressions hit at least
    one node label bit (all-zero expr slot = unused = pass) AND the
    node carries none of the term's forbid bits (NotIn/DoesNotExist).
    Pods with no terms pass everywhere.  Gated behind a ``lax.cond``
    on any term being present, so batches without matchExpressions —
    the common case — skip the ``[P, T2, E, N]`` reduction entirely
    (same pattern as the spread gate).

    Kubernetes semantics source: ``requiredDuringSchedulingIgnored
    DuringExecution`` — the *hard* sibling of the preferred stanza the
    reference's own probe Deployment used
    (netperfScript/deployment.yaml:17-26); the reference delegated
    both to stock kube-scheduler.
    """
    p = pods.pod_valid.shape[0]
    n = state.node_valid.shape[0]

    def live(_):
        labels = state.label_bits                          # u32[N, W]
        anyof = pods.ns_anyof                              # [P,T2,E,W]
        expr_unused = jnp.all(anyof == 0, axis=-1)         # [P,T2,E]
        hit = jnp.any(
            (anyof[:, :, :, None, :] & labels[None, None, None, :, :])
            != 0, axis=-1)                                 # [P,T2,E,N]
        expr_ok = expr_unused[..., None] | hit
        clean = jnp.all(
            (pods.ns_forbid[:, :, None, :] & labels[None, None, :, :])
            == 0, axis=-1)                                 # [P,T2,N]
        term_ok = (jnp.all(expr_ok, axis=2) & clean
                   & pods.ns_term_used[:, :, None])
        # Numeric Gt/Lt comparisons (AND'd per term, self-gated):
        # node_numeric[:, col] must land in (lo, hi); NaN values
        # (label absent/non-numeric) fail every comparison — kube's
        # fail-closed direction.  col -1 = unused slot, trivially ok.
        def with_numeric(tok):
            col = pods.ns_num_col                          # [P,T2,NE]
            vals = state.node_numeric[:, jnp.clip(col, 0,
                                                  None)]   # [N,P,T2,NE]
            vals = jnp.moveaxis(vals, 0, -1)               # [P,T2,NE,N]
            in_range = ((vals > pods.ns_num_lo[..., None])
                        & (vals < pods.ns_num_hi[..., None]))
            num_ok = jnp.all((col[..., None] < 0) | in_range,
                             axis=2)                       # [P,T2,N]
            return tok & num_ok

        term_ok = jax.lax.cond(jnp.any(pods.ns_num_col >= 0),
                               with_numeric, lambda t: t, term_ok)
        no_constraint = ~jnp.any(pods.ns_term_used, axis=1)
        out = no_constraint[:, None] | jnp.any(term_ok, axis=1)
        return out.T if transposed else out

    shape = (n, p) if transposed else (p, n)
    return jax.lax.cond(jnp.any(pods.ns_term_used), live,
                        lambda _: jnp.ones(shape, bool), None)


def zone_affinity_ok(state: ClusterState, pods: PodBatch,
                     gz_counts: jax.Array | None = None,
                     az_anti: jax.Array | None = None) -> jax.Array:
    """Zone-scoped hard pod (anti-)affinity mask, ``bool[P, N]``
    (``topologyKey: topology.kubernetes.io/zone`` required
    podAffinity/podAntiAffinity).

    Presence of a group in a zone is ``gz_counts[g, z] > 0`` — the
    same resident counts topologySpreadConstraints maintain — packed
    to ``u32[Z, W]`` presence words; the symmetric direction (a
    resident declared zone-anti-affinity against this pod's group)
    reads ``az_anti``.  Kubernetes topology-domain semantics for
    zone-less nodes: such a node is its own empty domain, so required
    zone AFFINITY fails there (empty domain has no members) while
    zone ANTI-affinity passes.  ``gz_counts``/``az_anti`` default to
    the state's but are overridable with the conflict/scan carries —
    placements move both.  Gated: constraint-free batches on clusters
    with no zone-anti residents pay one scalar reduction.
    """
    gz = state.gz_counts if gz_counts is None else gz_counts
    az = state.az_anti if az_anti is None else az_anti
    p = pods.pod_valid.shape[0]
    n = state.node_valid.shape[0]

    def live(_):
        from kubernetesnetawarescheduler_tpu.core.state import (
            planes_to_words,
        )

        zmax = az.shape[0]
        zwords = planes_to_words((gz > 0).T)               # u32[Z, W]
        has_zone = state.node_zone >= 0
        zrow = jnp.clip(state.node_zone, 0, zmax - 1)
        pres = zwords[zrow]                                # [N, W]
        azn = az[zrow]                                     # [N, W]
        # Zone affinity ANDs its terms like the host-scoped mask: the
        # node's zone must host members of ALL listed groups.  A
        # zone-less node is an empty domain — any requirement fails.
        zaff_req = pods.zaff_bits[:, None, :]
        zaff = jnp.where(
            has_zone[None, :],
            jnp.all((pres[None, :, :] & zaff_req) == zaff_req, axis=-1),
            jnp.all(zaff_req == 0, axis=-1))
        zanti = ~has_zone[None, :] | jnp.all(
            (pres[None, :, :] & pods.zanti_bits[:, None, :]) == 0,
            axis=-1)
        sym = ~has_zone[None, :] | jnp.all(
            (azn[None, :, :] & pods.group_bit[:, None, :]) == 0,
            axis=-1)
        return zaff & zanti & sym

    pred = (jnp.any(pods.zaff_bits != 0) | jnp.any(pods.zanti_bits != 0)
            | jnp.any(az != 0))
    return jax.lax.cond(pred, live, lambda _: jnp.ones((p, n), bool),
                        None)


def static_feasibility(state: ClusterState, pods: PodBatch) -> jax.Array:
    """The placement-independent slice of the feasibility mask,
    ``bool[P, N]``: validity, taints ⊆ tolerations, required node
    labels, hard nodeAffinity matchExpressions.  Shared by
    :func:`feasibility_mask`, the assign seam, and spread's
    Honor-policy domain eligibility (nodeAffinity participates in
    Honor eligibility, matching kube-scheduler)."""
    tol = jnp.all(
        (state.taint_bits[None, :, :] & ~pods.tol_bits[:, None, :]) == 0,
        axis=-1)
    sel = jnp.all(
        (state.label_bits[None, :, :] & pods.sel_bits[:, None, :])
        == pods.sel_bits[:, None, :], axis=-1)
    return (tol & sel & state.node_valid[None, :]
            & pods.pod_valid[:, None] & ns_affinity_ok(state, pods))


def static_feasibility_t(state: ClusterState, pods: PodBatch
                         ) -> jax.Array:
    """:func:`static_feasibility` in node-major layout ``bool[N, P]``
    — built natively with swapped broadcast axes (no transpose pass)
    for the conflict loop's transposed carry.  The gated
    ``ns_affinity_ok`` term keeps its pod-major internals and
    transposes at the seam only when terms are PRESENT (its dead
    branch emits node-major ones directly)."""
    tol = jnp.all(
        (state.taint_bits[:, None, :] & ~pods.tol_bits[None, :, :]) == 0,
        axis=-1)
    sel = jnp.all(
        (state.label_bits[:, None, :] & pods.sel_bits[None, :, :])
        == pods.sel_bits[None, :, :], axis=-1)
    return (tol & sel & state.node_valid[:, None]
            & pods.pod_valid[None, :]
            & ns_affinity_ok(state, pods, transposed=True))


def feasibility_mask(state: ClusterState, pods: PodBatch,
                     static_ok: jax.Array | None = None) -> jax.Array:
    """Hard constraints as a batched ``bool[P, N]`` mask.

    Covers what the reference delegated to stock Kubernetes for its own
    probe pods (nodeAffinity deployment.yaml:17-26, tolerations
    deployment.yaml:27-31) plus capacity fit, fused so filtering and
    scoring are one kernel:

    - fit:       req <= cap - used for every resource
    - taints:    node taints ⊆ pod tolerations
    - selector:  required node labels all present
    - pod affinity:      ALL required groups present on node (terms
      AND — kube joins multiple required terms conjunctively)
    - pod anti-affinity: no forbidden group present on node, and
      symmetrically no resident pod forbids this pod's group (k8s's
      existing-pod-anti-affinity symmetry)
    - zone (anti-)affinity: the same pair at zone topology
      (:func:`zone_affinity_ok`)
    """
    free = state.cap - state.used
    fits = jnp.all(pods.req[:, None, :] <= free[None, :, :] + _EPS, axis=-1)
    # Bit fields are multi-word u32[., W]: subset/overlap tests reduce
    # over the trailing word axis.  Required affinity is a SUBSET test
    # (node hosts members of ALL listed groups): each required term
    # contributes one group bit and Kubernetes ANDs terms — the
    # any-overlap join used before round 3 silently weakened multi-term
    # pods and let UNSAT-degraded terms be absorbed by satisfiable
    # ones (ADVICE.md round 2).  Empty masks pass trivially.
    aff_req = pods.affinity_bits[:, None, :]
    affinity = jnp.all(
        (state.group_bits[None, :, :] & aff_req) == aff_req, axis=-1)
    anti = jnp.all(
        (state.group_bits[None, :, :] & pods.anti_bits[:, None, :]) == 0,
        axis=-1)
    sym = jnp.all(
        (state.resident_anti[None, :, :] & pods.group_bit[:, None, :]) == 0,
        axis=-1)
    if static_ok is None:
        static_ok = static_feasibility(state, pods)
    return (static_ok & fits & affinity & anti & sym
            & zone_affinity_ok(state, pods))


def score_pods(state: ClusterState, pods: PodBatch,
               cfg: SchedulerConfig, static=None,
               wvec: jax.Array | None = None) -> jax.Array:
    """Full masked score matrix ``f32[P, N]``; -inf marks infeasible.

    ``static``, if given, is a precomputed :func:`static_node_scores`
    pair — serving paths (the extender webhook batcher) cache it across
    requests so a dispatch does not re-derive the O(N²) normalization
    work per call; it depends only on metrics/network/validity state,
    never on placements.

    ``wvec``, if given, is a traced :func:`weights_vector` array that
    replaces every ``cfg.weights`` constant in the score expression —
    the counterfactual-replay seam (policy/).  ``None`` (every serving
    path) keeps the exact constant-folded expressions, bit-identical
    to the pre-wvec scorer."""
    if static is None:
        static = static_node_scores(state, cfg, wvec=wvec)
    base, ct = static
    net = network_scores(state, pods, cfg, ct=ct)
    soft = soft_affinity_scores(state, pods, cfg, wvec=wvec)
    w_bal = cfg.weights.balance if wvec is None else wvec[8]
    bal = w_bal * balance_penalty(state, pods)
    sok = static_feasibility(state, pods)  # one compute, both uses
    spread_pen, spread_ok = spread_terms(state, pods, cfg,
                                         static_ok=sok, wvec=wvec)
    raw = base[None, :] + net + soft - bal - spread_pen
    ok = feasibility_mask(state, pods, static_ok=sok) & spread_ok
    return jnp.where(ok, raw, NEG_INF)


def winner_from_scores(scores: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-pod winner reduction over a masked score matrix:
    ``(best f32[P], node i32[P])``, ``node == -1`` where the row is
    all-infeasible.

    THE tie-break contract of the repo (assign.argmax2, the greedy
    scan, the gang re-score all follow it): equal-best candidates
    resolve to the LOWEST node index, deterministically — implemented
    as min-index-of-max rather than ``jnp.argmax`` so the semantics
    are explicit in the expression the fused kernels must reproduce.
    The Pallas winner kernel (pallas_score.score_winner_tiled) and the
    cross-shard combine (parallel.sharding) are property-tested
    bit-identical against this function.
    """
    n = scores.shape[1]
    best = jnp.max(scores, axis=1)
    cols = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    choice = jnp.min(
        jnp.where(scores == best[:, None], cols, jnp.int32(n)), axis=1)
    feasible = best > NEG_INF * 0.5
    node = jnp.where(feasible, choice, np.int32(-1)).astype(jnp.int32)
    return best, node


def score_winner(state: ClusterState, pods: PodBatch,
                 cfg: SchedulerConfig, static=None,
                 wvec: jax.Array | None = None
                 ) -> tuple[jax.Array, jax.Array]:
    """Fused score→winner: ``(best f32[P], node i32[P])`` in ONE
    compiled program — the masked-argmax epilogue runs inside the same
    XLA computation as :func:`score_pods`, so when jitted the P×N
    score plane never round-trips through HBM as a kernel boundary
    (XLA fuses the row reduction with its producer; the Pallas twin in
    core/pallas_score.py makes the same fusion explicit per tile).
    Same tie-break contract as :func:`winner_from_scores`."""
    return winner_from_scores(score_pods(state, pods, cfg, static,
                                         wvec=wvec))


def _explain_terms(state: ClusterState, pods: PodBatch,
                   cfg: SchedulerConfig, static=None,
                   wvec: jax.Array | None = None) -> dict:
    """Pure-JAX body of :func:`explain_scores`: every additive term and
    every individual feasibility gate, as ``[P, N]`` (or broadcastable)
    arrays.  Kept separate so tests can jit it once for the 64-instance
    property sweep while production calls stay eager via the wrapper."""
    if static is None:
        static = static_node_scores(state, cfg, wvec=wvec)
    base, ct = static
    net = network_scores(state, pods, cfg, ct=ct)
    soft = soft_affinity_scores(state, pods, cfg, wvec=wvec)
    w_bal = cfg.weights.balance if wvec is None else wvec[8]
    bal = w_bal * balance_penalty(state, pods)
    sok = static_feasibility(state, pods)
    spread_pen, spread_ok = spread_terms(state, pods, cfg,
                                         static_ok=sok, wvec=wvec)
    free = state.cap - state.used
    fits = jnp.all(pods.req[:, None, :] <= free[None, :, :] + _EPS,
                   axis=-1)
    aff_req = pods.affinity_bits[:, None, :]
    affinity = jnp.all(
        (state.group_bits[None, :, :] & aff_req) == aff_req, axis=-1)
    anti = jnp.all(
        (state.group_bits[None, :, :] & pods.anti_bits[:, None, :]) == 0,
        axis=-1)
    sym = jnp.all(
        (state.resident_anti[None, :, :] & pods.group_bit[:, None, :])
        == 0, axis=-1)
    zone = zone_affinity_ok(state, pods)
    ok = sok & fits & affinity & anti & sym & zone & spread_ok
    raw = base[None, :] + net + soft - bal - spread_pen
    total = jnp.where(ok, raw, NEG_INF)
    return {
        "base": base[None, :], "net": net, "soft": soft,
        "balance": bal, "spread": spread_pen, "total": total,
        "ok": ok, "static_ok": sok, "fits": fits,
        "affinity": affinity, "anti": anti, "sym_anti": sym,
        "zone_ok": zone, "spread_ok": spread_ok,
    }


def explain_scores(state: ClusterState, pods: PodBatch,
                   cfg: SchedulerConfig, static=None,
                   wvec: jax.Array | None = None
                   ) -> dict[str, np.ndarray]:
    """Host-side score decomposition for placement explainability.

    Re-derives :func:`score_pods`'s additive terms AND the individual
    feasibility gates as host numpy arrays, all ``[P, N]``.  This is
    deliberately a separate, never-jitted call used only when
    ``cfg.enable_explain`` is on: the serving score path is untouched,
    so placements stay bit-identical whether explain runs or not
    (tests/test_flight.py pins this).  ``total`` is computed with the
    exact expression score_pods uses, so the winner's score is
    reproducible from the components (tests/test_score.py property
    test: base + net + soft - balance - spread == total where
    feasible, within fp32 tolerance).

    Gate keys mirror :func:`feasibility_mask`'s terms (the three
    bit-field tests are restated here because the fused mask never
    materializes them separately).
    """
    terms = _explain_terms(state, pods, cfg, static=static, wvec=wvec)
    shape = np.asarray(terms["net"]).shape

    def _f32(x):
        return np.broadcast_to(
            np.asarray(x, dtype=np.float32), shape).copy()

    def _b(x):
        return np.broadcast_to(np.asarray(x, dtype=bool), shape).copy()

    out = {}
    for key, val in terms.items():
        is_gate = key in ("ok", "static_ok", "fits", "affinity",
                          "anti", "sym_anti", "zone_ok", "spread_ok")
        out[key] = _b(val) if is_gate else _f32(val)
    return out
