"""The scorer daemon: ``python -m kubernetesnetawarescheduler_tpu``.

The process the deploy manifests run (deploy/scorer.yaml).  Wires the
whole serving stack the way the reference's single Go ``main`` did
(scheduler.go:127-159), but with the roles split per SURVEY.md §7:

- the Encoder + SchedulerLoop (batch score/assign on the TPU),
- the UDS scorer server the native extender shim fronts,
- optionally the gRPC transport for remote/DCN clients,
- the scrape pool (node_exporter ingestion) and probe orchestrator
  (pairwise lat/bw) on background threads,
- checkpoint restore on start / save on SIGTERM (the restart story the
  reference lacked — queued pods lost, scheduler.go:165-173),
- a decision log for deterministic replay.

The Kubernetes client is pluggable: ``--cluster fake:N`` serves against
a generated N-node fake cluster (demo/CI shape), while a real
API-server client plugs in through the same
:class:`~.k8s.client.ClusterClient` contract via the extender webhook
path (stock kube-scheduler calls /filter, /prioritize, /bind — no
in-process informer needed, which is why this daemon has no dependency
on a kubernetes client library).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time

from kubernetesnetawarescheduler_tpu.config import (
    SchedulerConfig,
    load_config,
)


def _agent_reachable(host: str, port: int, timeout_s: float = 3.0) -> bool:
    """One /healthz round-trip to a probe agent."""
    import urllib.request

    from kubernetesnetawarescheduler_tpu.ingest.probe import _bracketed

    try:
        with urllib.request.urlopen(
                f"http://{_bracketed(host)}:{port}/healthz",
                timeout=timeout_s) as resp:
            return bool(json.load(resp).get("ok"))
    except (OSError, ValueError):
        return False


def build_fake(num_nodes: int, seed: int, cfg: SchedulerConfig,
               mesh=None, async_bind: bool = False,
               burst_batches: int = 8, pipelined: bool = False):
    from kubernetesnetawarescheduler_tpu.bench.fakecluster import (
        ClusterSpec,
        build_fake_cluster,
        feed_metrics,
    )
    from kubernetesnetawarescheduler_tpu.core.loop import SchedulerLoop

    import numpy as np

    cluster, lat, bw = build_fake_cluster(
        ClusterSpec(num_nodes=num_nodes, seed=seed))
    loop = SchedulerLoop(cluster, cfg, mesh=mesh, async_bind=async_bind,
                         burst_batches=burst_batches,
                         pipelined=pipelined)
    loop.encoder.set_network(lat, bw)
    feed_metrics(cluster, loop.encoder, np.random.default_rng(seed + 1))
    return loop, lat, bw


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="kubernetesnetawarescheduler_tpu",
        description=__doc__.splitlines()[0])
    ap.add_argument("--config", help="SchedulerConfig JSON/YAML path")
    ap.add_argument("--cluster", default="fake:128",
                    help='"fake:<N>" (generated cluster), '
                         '"incluster" (ServiceAccount, the reference\'s '
                         "rest.InClusterConfig, scheduler.go:144), or "
                         '"kube:<url>" (explicit API server) — the '
                         "standalone-scheduler shape; the extender "
                         "webhook path works regardless")
    ap.add_argument("--kube-token", default="",
                    help="bearer token for kube:<url> (testing)")
    ap.add_argument("--kube-insecure", action="store_true",
                    help="skip TLS verification for kube:<url>")
    ap.add_argument("--uds", default="/run/netaware/scorer.sock",
                    help="unix socket the native shim connects to")
    ap.add_argument("--grpc", default="",
                    help='gRPC bind address (e.g. "0.0.0.0:50051"); '
                         "empty disables")
    ap.add_argument("--scrape-targets", default="",
                    help="JSON file {node name: metrics URL} for the "
                         "node_exporter scrape pool")
    ap.add_argument("--scrape-period-s", type=float, default=15.0)
    ap.add_argument("--probe-period-s", type=float, default=60.0,
                    help="pairwise lat/bw probe cadence (the "
                         "reference's script.sh ran every 60s)")
    ap.add_argument("--probe-targets", default="",
                    help="JSON file {node name: iperf3 host} enabling "
                         "real pairwise probing on kube/incluster "
                         "clusters (the reference's netperfScript role)")
    ap.add_argument("--probe-agent-port", type=int, default=9798,
                    help="per-node probe-agent port (deploy/probes.yaml "
                         "DaemonSet): probes run FROM node a's agent "
                         "for honest a<->b pairs; 0 = probe from this "
                         "process instead (scorer->node vantage)")
    ap.add_argument("--checkpoint-dir", default="",
                    help="restore on start, save on SIGTERM")
    ap.add_argument("--compilation-cache-dir",
                    default=os.environ.get(
                        "NETAWARE_COMPILATION_CACHE", ""),
                    help="persistent XLA compilation cache directory "
                         "(jax_compilation_cache_dir): a daemon "
                         "restart then reuses the previous process's "
                         "compiled score/assign executables instead "
                         "of paying full recompile before its first "
                         "bind (minutes at N=5120 on CPU, ~30s on "
                         "TPU). Point it at a persistent volume in "
                         "deploy/scorer.yaml; empty disables")
    ap.add_argument("--decision-log", default="",
                    help="JSONL decision log path")
    ap.add_argument("--jax-profile-dir", default="",
                    help="opt-in jax.profiler trace directory: the "
                         "serving run is wrapped in start/stop_trace "
                         "and every device step carries a "
                         "StepTraceAnnotation with the flight "
                         "recorder's cycle id, so the Perfetto device "
                         "timeline lines up with /debug/trace; empty "
                         "disables")
    ap.add_argument("--crash-dump", default="",
                    help="path for the flight-recorder post-mortem "
                         "dump (cycle spans + last explain records) "
                         "written on SIGTERM/fault; defaults to "
                         "<checkpoint-dir>/flight_dump.json when "
                         "--checkpoint-dir is set, else disabled")
    ap.add_argument("--audit-interval", type=float, default=None,
                    help="anti-entropy audit period in seconds "
                         "(core/integrity.py): a background thread "
                         "digests the device planes against a shadow "
                         "re-encode of the staging truth and walks "
                         "the repair ladder on drift; overrides "
                         "cfg.audit_interval_s; 0 disables")
    ap.add_argument("--state-chaos", type=float, default=0.0,
                    help="state-fault injection period in seconds "
                         "(core/state_chaos.py): every period one "
                         "seeded fault (dropped/duplicated/reordered "
                         "delta, NaN poison, bit flip) is injected "
                         "into the state layer — pair with "
                         "--audit-interval to exercise the repair "
                         "ladder; 0 disables (NEVER enable in "
                         "production)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--once", action="store_true",
                    help="serve one readiness cycle then exit "
                         "(smoke-test mode)")
    ap.add_argument("--burst-batches", type=int, default=8,
                    help="with a deep backlog, drain up to this many "
                         "batches per device dispatch (one fetch for "
                         "all of them); 1 disables burst mode")
    ap.add_argument("--async-bind", action="store_true",
                    help="assume-then-bind cycle (kube's cache "
                         "pattern): commit placements to the local "
                         "ledger immediately and confirm binds on a "
                         "worker thread, keeping API-server RTT off "
                         "the scheduling cycle; rejected binds roll "
                         "back")
    ap.add_argument("--pipeline", action="store_true",
                    help="three-stage pipelined burst cycle: encode "
                         "of burst k+1 on a host thread overlaps the "
                         "device step of burst k and the network "
                         "binds of burst k-1 (implies --async-bind); "
                         "assignments are identical to the serial "
                         "cycle on the same feed")
    ap.add_argument("--multicycle", type=int, default=None,
                    help="persistent multi-cycle serving: dispatch K "
                         "scheduling cycles as ONE donated device scan "
                         "over a device-resident wave queue, retiring "
                         "per-cycle winners asynchronously (commits "
                         "only at retire, so a mid-window crash "
                         "restores to the last retired cycle); 1 = "
                         "per-cycle dispatch (default). Placements "
                         "are bit-identical to K sequential fused "
                         "steps on the same feed")
    ap.add_argument("--bind-coalesce-window", type=int, default=None,
                    help="coalesce up to this many queued async bind "
                         "batches into one API pass (sorted by "
                         "node/namespace); 1 disables coalescing")
    ap.add_argument("--bind-max-inflight", type=int, default=None,
                    help="bound on concurrent async bind batches "
                         "in flight (worker threads); 1 = serial "
                         "binder (default)")
    ap.add_argument("--quality-obs", action="store_true",
                    help="outcome observability (obs/quality.py): "
                         "join each bound pod's score-time network "
                         "prediction against later probe truth — "
                         "realized bw/lat, regret vs best "
                         "alternative, calibration residuals — in a "
                         "bounded outcome ring (/debug/slo, "
                         "/metrics); equivalent to "
                         "enable_quality_obs=true in --config")
    ap.add_argument("--slo", action="store_true",
                    help="SLO burn-rate engine (obs/slo.py): "
                         "evaluate the declarative objectives "
                         "(score p99, bind tail, quality regret, "
                         "unrepaired drift) over multi-window burn "
                         "rates, emit SLOBurn Events and degrade "
                         "/readyz while burning; equivalent to "
                         "enable_slo=true in --config")
    ap.add_argument("--rebalance", action="store_true",
                    help="continuous rebalancing (core/rebalance.py): "
                         "a budgeted descheduler revisits bound pods "
                         "at maintain cadence, live-migrating the "
                         "worst placements through the crash-safe "
                         "migration ledger under the eviction budget "
                         "and PDB-style disruption limits; "
                         "equivalent to enable_rebalance=true in "
                         "--config")
    ap.add_argument("--learned-score", action="store_true",
                    help="learned scoring policy (policy/): fit "
                         "term-level score multipliers on the "
                         "explain/outcome join, shadow-score recorded "
                         "decisions, and promote candidate weights "
                         "ONLY through the counterfactual replay "
                         "gate; equivalent to "
                         "enable_learned_score=true in --config. "
                         "Needs explain capture (cfg.enable_explain) "
                         "and the quality observer for training "
                         "signal")
    ap.add_argument("--policy-eval-trace", default="",
                    help="scenario trace (scenario/trace.py format) "
                         "the policy promotion gate replays "
                         "counterfactually; without one the gate "
                         "refuses every promotion and the policy "
                         "stays shadow-only")
    ap.add_argument("--async-static", action="store_true",
                    help="rebuild the batch-invariant static score "
                         "prep on a background thread while batches "
                         "keep scoring against the last one (bounded "
                         "by cfg.static_max_staleness_s / "
                         "static_max_versions_behind, with a "
                         "synchronous fallback); equivalent to "
                         "enable_async_static=true in --config")
    ap.add_argument("--mesh", action="store_true",
                    help="shard the watch-loop's score+assign kernels "
                         "over ALL LOCAL devices via the (dp, tp) "
                         "mesh (the v5e-4 single-process multi-chip "
                         "shape; the extender webhook path stays "
                         "single-device)")
    ap.add_argument("--multihost", action="store_true",
                    help="join the multi-process JAX runtime before "
                         "device init (TPU pods: coordinator "
                         "auto-detects from the environment); implies "
                         "--mesh. Multi-process serving is "
                         "single-CONTROLLER: process 0 runs the "
                         "control plane and broadcasts each cycle to "
                         "the other processes, which join the global-"
                         "mesh compute as followers "
                         "(parallel/serve_multihost.py). Bootstrap "
                         "failures are fatal — see "
                         "parallel/multihost.py")
    ap.add_argument("--coordinator", default="",
                    help="explicit coordinator address for "
                         "--multihost on bare-metal DCN clusters "
                         "(host:port; empty = auto-detect). Needs "
                         "--num-processes/--process-id too when no "
                         "cluster environment provides them")
    ap.add_argument("--num-processes", type=int, default=None,
                    help="total process count for --multihost "
                         "bare-metal bootstrap")
    ap.add_argument("--process-id", type=int, default=None,
                    help="this process's rank for --multihost "
                         "bare-metal bootstrap")
    args = ap.parse_args(argv)

    mesh = None
    if args.multihost or args.mesh:
        import jax

        from kubernetesnetawarescheduler_tpu.parallel.multihost import (
            global_mesh,
            init_multihost,
        )

        if args.multihost:
            init_multihost(
                coordinator_address=args.coordinator or None,
                num_processes=args.num_processes,
                process_id=args.process_id)
        mesh = global_mesh()
        if jax.process_count() > 1 and jax.process_index() != 0:
            # SERVING stays single-controller: exactly one informer,
            # queue, encoder and binder (process 0) — independent
            # control planes would watch divergent API-server streams
            # and POST duplicate Bindings.  Every OTHER process joins
            # the global-mesh compute as a follower: it receives each
            # cycle's state/batch via broadcast and participates in
            # the same GSPMD score+assign step, so the N×N matrices'
            # HBM and the scoring FLOPs split across hosts
            # (parallel/serve_multihost.py; VERDICT r3 next #9).
            from kubernetesnetawarescheduler_tpu.parallel import (
                serve_multihost,
            )

            cfg_f = (load_config(args.config) if args.config
                     else SchedulerConfig())
            print(f"multihost follower {jax.process_index()}/"
                  f"{jax.process_count()} joining the mesh",
                  file=sys.stderr)
            steps = serve_multihost.run_follower(cfg_f, mesh)
            print(f"multihost follower exiting after {steps} steps",
                  file=sys.stderr)
            return

    cfg = load_config(args.config) if args.config else SchedulerConfig()
    if args.async_static and not cfg.enable_async_static:
        import dataclasses

        cfg = dataclasses.replace(cfg, enable_async_static=True)
    if args.quality_obs and not cfg.enable_quality_obs:
        import dataclasses

        cfg = dataclasses.replace(cfg, enable_quality_obs=True)
    if args.slo and not cfg.enable_slo:
        import dataclasses

        cfg = dataclasses.replace(cfg, enable_slo=True)
    if args.rebalance and not cfg.enable_rebalance:
        import dataclasses

        cfg = dataclasses.replace(cfg, enable_rebalance=True)
    if args.learned_score and not cfg.enable_learned_score:
        import dataclasses

        cfg = dataclasses.replace(cfg, enable_learned_score=True)
    # r16 multi-cycle serving + coalesced-bind knobs: CLI overrides
    # win over --config (None = keep the config's value).  Validation
    # lives in SchedulerConfig.__post_init__ — replace() re-runs it.
    _mc_over = {k: v for k, v in (
        ("multicycle", args.multicycle),
        ("bind_coalesce_window", args.bind_coalesce_window),
        ("bind_max_inflight", args.bind_max_inflight),
    ) if v is not None}
    if _mc_over:
        import dataclasses

        cfg = dataclasses.replace(cfg, **_mc_over)
    if cfg.multicycle > 1:
        print(f"multi-cycle serving enabled: K={cfg.multicycle}, "
              f"device queue depth {cfg.multicycle_queue_depth}, "
              f"bind coalesce window {cfg.bind_coalesce_window}, "
              f"bind max inflight {cfg.bind_max_inflight}",
              file=sys.stderr)
    if cfg.enable_learned_score:
        print(f"learned scoring policy enabled (shadow-first): ring "
              f"{cfg.policy_ring}, train every "
              f"{cfg.policy_train_interval_s}s, gate margin "
              f"{cfg.policy_promote_margin}", file=sys.stderr)
    # Explicit startup WARNs (r15): legal-but-weaker configurations —
    # e.g. learned scoring without an eval trace silently pinned to
    # shadow-only — are named loudly, with the flag that fixes them.
    for warn in cfg.startup_warnings(
            policy_eval_trace=args.policy_eval_trace or None):
        print(f"WARN: {warn}", file=sys.stderr)
    if cfg.enable_rebalance:
        print(f"rebalancer enabled: min gain "
              f"{cfg.rebalance_min_gain}, budget "
              f"{cfg.rebalance_evictions_per_hour} evictions/h, "
              f"{cfg.rebalance_max_moves_per_cycle} moves/cycle",
              file=sys.stderr)
    if cfg.enable_quality_obs:
        print(f"quality observer enabled: outcome ring "
              f"{cfg.quality_ring_size}, harvest every "
              f"{cfg.quality_harvest_interval_s}s", file=sys.stderr)
    if cfg.enable_slo:
        print(f"slo engine enabled: score p99 {cfg.slo_score_p99_ms}ms, "
              f"burn windows {cfg.slo_fast_window_s}s/"
              f"{cfg.slo_slow_window_s}s", file=sys.stderr)

    if args.compilation_cache_dir:
        # Persistent XLA compilation cache: must be configured BEFORE
        # the first jit compilation (the loop construction below
        # compiles score/assign), so a restarted daemon reaches its
        # first bind on cached executables.  min_compile_time 0.1s
        # caches every kernel that meaningfully costs wall-clock.
        import jax

        os.makedirs(args.compilation_cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir",
                          args.compilation_cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.1)

    kind, _, param = args.cluster.partition(":")
    lat_truth = bw_truth = None
    if kind == "fake":
        loop, lat_truth, bw_truth = build_fake(
            int(param or "128"), args.seed, cfg, mesh=mesh,
            async_bind=args.async_bind,
            burst_batches=args.burst_batches,
            pipelined=args.pipeline)
    elif kind in ("incluster", "kube"):
        from kubernetesnetawarescheduler_tpu.core.loop import SchedulerLoop
        from kubernetesnetawarescheduler_tpu.k8s.kubeclient import KubeClient

        client = KubeClient(base_url=param or None,
                            token=args.kube_token or None,
                            insecure=args.kube_insecure)
        # SchedulerLoop's Informer lists + subscribes nodes itself;
        # resync() recovers pods already pending at startup (the
        # re-list the reference lacked — ADD-only, scheduler.go:165).
        loop = SchedulerLoop(client, cfg, mesh=mesh,
                             async_bind=args.async_bind,
                             burst_batches=args.burst_batches,
                             pipelined=args.pipeline)
        loop.informer.resync()
    else:
        ap.error(f"unknown cluster kind {kind!r} "
                 "(fake:<N> | incluster | kube:<url>)")

    # Brownout-resilience knobs (circuit breaker + retry budget) from
    # config; configure_resilience replaces the breaker object, so
    # re-point the loop's reference at the live one.
    resil = getattr(loop.client, "configure_resilience", None)
    if callable(resil):
        resil(failure_threshold=cfg.breaker_failure_threshold,
              window_s=cfg.breaker_window_s,
              cooldown_s=cfg.breaker_cooldown_s,
              retry_budget=cfg.api_retry_budget,
              backoff_base_s=cfg.api_backoff_base_s,
              backoff_max_s=cfg.api_backoff_max_s)
        loop.breaker = loop.client.breaker

    if args.checkpoint_dir and os.path.exists(
            os.path.join(args.checkpoint_dir, "meta.json")):
        from kubernetesnetawarescheduler_tpu.core.checkpoint import (
            load_checkpoint,
        )
        try:
            restored = load_checkpoint(args.checkpoint_dir, cfg)
        except Exception as exc:  # noqa: BLE001 — an incompatible
            # (pre-v6 group keys) or corrupt checkpoint must not take
            # the daemon down, whatever the parse failure raises
            # (ValueError, BadZipFile from a truncated npz, KeyError
            # from a gutted meta): the ledger is reconstructable from
            # the API server — start fresh and say so.
            restored = None
            print(f"IGNORING checkpoint {args.checkpoint_dir}: "
                  f"{type(exc).__name__}: {exc}", file=sys.stderr)
        # The checkpoint must describe THIS cluster: a node table that
        # diverges from the live registrations would silently schedule
        # onto a phantom subset and break ingest-by-name.  Shape checks
        # alone (load_checkpoint) cannot catch that.
        if restored is None:
            loop.checkpoint_state = "ignored"
        elif restored._node_names == loop.encoder._node_names:
            loop.encoder = restored
            loop.checkpoint_state = "restored"
            print(f"restored checkpoint from {args.checkpoint_dir}",
                  file=sys.stderr)
        else:
            loop.checkpoint_state = "ignored"
            print(f"IGNORING checkpoint {args.checkpoint_dir}: node "
                  f"table mismatch ({len(restored._node_names)} stored "
                  f"vs {len(loop.encoder._node_names)} live nodes)",
                  file=sys.stderr)

    # The flight recorder does not persist across restarts (spans
    # describe THIS process's cycles) — but a post-restore trace dump
    # must say WHY it is empty: stamp the checkpoint disposition so
    # /debug/trace metadata reads restored/ignored/fresh
    # (empty-but-versioned, never silently blank).
    if loop.flight is not None:
        loop.flight.meta["checkpoint_state"] = loop.checkpoint_state

    # Learned scoring policy: resume parameters/optimizer/example ring
    # from policy.npz when the restored checkpoint carries one (same
    # resume-not-relearn reasoning as the netmodel restore); the
    # promotion gate's replay trace comes from the CLI.
    if (cfg.enable_learned_score and args.checkpoint_dir
            and loop.checkpoint_state == "restored"):
        from kubernetesnetawarescheduler_tpu.core.checkpoint import (
            load_policy,
        )

        try:
            restored_policy = load_policy(args.checkpoint_dir, cfg,
                                          seed=args.seed)
        except Exception as exc:  # noqa: BLE001 — policy is optional
            restored_policy = None
            print(f"WARNING: policy checkpoint load failed: {exc}",
                  file=sys.stderr)
        if restored_policy is not None:
            loop.policy = restored_policy
            print("restored learned-score policy "
                  f"(version {restored_policy.version}, promoted "
                  f"{restored_policy.promoted_version})",
                  file=sys.stderr)
    if args.policy_eval_trace:
        loop.policy_eval_trace = args.policy_eval_trace

    if args.decision_log:
        from kubernetesnetawarescheduler_tpu.core.checkpoint import (
            DecisionLog,
        )
        loop.decision_log = DecisionLog(args.decision_log)

    from kubernetesnetawarescheduler_tpu.api.extender import (
        ExtenderHandlers,
    )
    from kubernetesnetawarescheduler_tpu.api.server import ScorerServer

    os.makedirs(os.path.dirname(args.uds) or ".", exist_ok=True)
    handlers = ExtenderHandlers(
        loop, batch_window_s=cfg.extender_batch_window_s)
    uds = ScorerServer(handlers, args.uds)
    uds.start()
    print(f"scorer serving on uds://{args.uds}", file=sys.stderr)

    grpc_server = None
    if args.grpc:
        from kubernetesnetawarescheduler_tpu.api.grpc_server import (
            serve_grpc,
        )
        grpc_server, port = serve_grpc(handlers, args.grpc)
        print(f"scorer serving on grpc://{args.grpc} (port {port})",
              file=sys.stderr)

    threads = []
    stop = threading.Event()
    if args.scrape_targets:
        from kubernetesnetawarescheduler_tpu.ingest.scraper import (
            ScrapePool,
        )
        with open(args.scrape_targets, encoding="utf-8") as fh:
            targets = json.load(fh)
        pool = ScrapePool(loop.encoder, targets)
        threads.append(threading.Thread(
            target=pool.run_forever, args=(args.scrape_period_s,),
            daemon=True, name="scrape-pool"))

    # Probe orchestrator: keeps the pairwise lat/bw matrices fresh (the
    # reference's 60-second script.sh loop, as budgeted pair probing).
    # The fake cluster gets the FakeProber against ground truth; a real
    # fleet swaps in Iperf3Prober via the same Prober protocol.
    prober = None
    if lat_truth is not None:
        from kubernetesnetawarescheduler_tpu.ingest.probe import FakeProber

        names = list(loop.encoder._node_names)
        prober = FakeProber(names, lat_truth, bw_truth, seed=args.seed)
    elif args.probe_targets:
        from kubernetesnetawarescheduler_tpu.ingest.probe import (
            AgentProber,
            Iperf3Prober,
        )

        with open(args.probe_targets, encoding="utf-8") as fh:
            host_of = json.load(fh)
        names = [n for n in loop.encoder._node_names if n in host_of]
        # AgentProber (default): node a's probe agent runs the iperf3
        # client against b, so lat/bw[a, b] is the real a<->b path —
        # the reference's client-side vantage (run.sh:12-14) without
        # kubectl.  --probe-agent-port 0 falls back to probing from
        # this process (only honest when the scorer IS the traffic
        # source).
        if args.probe_agent_port:
            prober = AgentProber(
                host_of, agent_port=args.probe_agent_port,
                token=os.environ.get("NETAWARE_PROBE_TOKEN", ""))
            # Startup reachability check: probe failures are counted
            # silently per-cycle (a pair just stays stale), so a fleet
            # with NO agents (e.g. probes.yaml not redeployed after an
            # upgrade) must be called out loudly here, not discovered
            # via forever-empty lat/bw matrices.
            if names and not _agent_reachable(
                    host_of[names[0]], args.probe_agent_port):
                print(f"WARNING: probe agent on {names[0]} "
                      f"({host_of[names[0]]}:{args.probe_agent_port}) "
                      "unreachable — deploy deploy/probes.yaml's "
                      "DaemonSet, or pass --probe-agent-port 0 for "
                      "the legacy scorer-side iperf3 vantage",
                      file=sys.stderr)
        else:
            prober = Iperf3Prober(host_of)
    else:
        print("WARNING: no probe source (--probe-targets unset on a "
              "real cluster): lat/bw matrices stay empty and scoring "
              "degrades to metric-vote only", file=sys.stderr)

    # Learned topology model: dense confidence-weighted lat/bw
    # estimates fit on the probe stream (netmodel/).  A checkpoint
    # restore may already have attached one (netmodel.npz); otherwise
    # start fresh.  The EIG planner replaces stalest-first pair
    # selection with uncertainty x placement-relevance selection.
    netmodel = None
    planner = None
    if cfg.enable_netmodel:
        from kubernetesnetawarescheduler_tpu.netmodel import (
            EIGProbePlanner,
            TopologyModel,
        )

        netmodel = getattr(loop.encoder, "netmodel", None)
        if netmodel is None:
            netmodel = TopologyModel(cfg, seed=args.seed)
            loop.encoder.attach_netmodel(netmodel)
        planner = EIGProbePlanner(
            netmodel, explore_frac=cfg.netmodel_explore_frac,
            seed=args.seed)
        loop.probe_planner = planner
        print("netmodel enabled: blending learned topology estimates "
              "into lat/bw", file=sys.stderr)

    if args.probe_period_s > 0 and prober is not None:
        from kubernetesnetawarescheduler_tpu.ingest.probe import (
            ProbeOrchestrator,
        )
        from kubernetesnetawarescheduler_tpu.k8s.types import link_event

        orch = ProbeOrchestrator(
            loop.encoder, prober, names, planner=planner,
            model=netmodel, forget_s=cfg.probe_forget_s,
            quarantine_streak=cfg.quarantine_streak_events)
        loop.probe_orchestrator = orch

        def probe_forever() -> None:
            while not stop.is_set():
                orch.run_cycle(budget=64)
                for ev in orch.drain_quarantine_events():
                    a, b = ev["link"]
                    rb = getattr(loop, "rebalance", None)
                    if rb is not None:
                        rb.note_link_event(a, b, "quarantine",
                                           int(ev["streak"]))
                    try:
                        loop.client.create_event(link_event(
                            src=a, dst=b, reason="LinkQuarantined",
                            streak=int(ev["streak"]),
                            message=(
                                f"link {a}<->{b} probe samples "
                                f"quarantined {ev['streak']}x in a row "
                                f"({ev['reason']}: lat={ev['lat_ms']} "
                                f"ms, bw={ev['bw_bps']} bps)"),
                            component=cfg.scheduler_name))
                    except Exception:
                        # Best-effort, like LinkDegraded below — the
                        # refusals are already counted in /metrics.
                        pass
                if netmodel is not None:
                    for i, j, pred, meas, _t in \
                            netmodel.drain_degradations():
                        try:
                            a = loop.encoder.node_name(i)
                            b = loop.encoder.node_name(j)
                            rb = getattr(loop, "rebalance", None)
                            if rb is not None:
                                rb.note_link_event(a, b, "degraded", 1)
                            loop.client.create_event(link_event(
                                src=a, dst=b, reason="LinkDegraded",
                                streak=1,
                                message=(
                                    f"link {a}<->{b} measured "
                                    f"{meas / 1e9:.2f} Gbps vs expected "
                                    f"{pred / 1e9:.2f} Gbps"),
                                component=cfg.scheduler_name))
                        except Exception:
                            # Event emission is best-effort; the
                            # degradation is already counted in
                            # self-metrics.
                            pass
                orch.advance_clock(args.probe_period_s)
                stop.wait(args.probe_period_s)

        threads.append(threading.Thread(target=probe_forever, daemon=True,
                                        name="probe-orchestrator"))

    # State integrity & self-healing (ISSUE 10): the anti-entropy
    # auditor shadow-re-encodes truth from the staging arrays on its
    # own thread and walks the repair ladder on digest drift; the
    # chaos injector (opt-in, test/soak only) feeds it faults.
    audit_interval = (args.audit_interval
                      if args.audit_interval is not None
                      else cfg.audit_interval_s)
    auditor = None
    if audit_interval > 0:
        from kubernetesnetawarescheduler_tpu.core.integrity import (
            IntegrityAuditor,
        )

        auditor = IntegrityAuditor(
            loop.encoder, loop,
            interval_s=audit_interval,
            checkpoint_dir=args.checkpoint_dir or None,
            watchdog_failures=cfg.audit_watchdog_failures,
            crash_dump_path=(
                os.path.join(args.checkpoint_dir,
                             "integrity_dump.json")
                if args.checkpoint_dir else "integrity_dump.json"))
        loop.integrity = auditor
        print(f"integrity auditor enabled: period {audit_interval}s",
              file=sys.stderr)
    if args.state_chaos > 0:
        from kubernetesnetawarescheduler_tpu.core.state_chaos import (
            StateChaosInjector,
        )

        injector = StateChaosInjector(
            loop.encoder, seed=args.seed, loop=loop,
            checkpoint_dir=args.checkpoint_dir or None)
        loop.state_chaos = injector

        def chaos_forever() -> None:
            while not stop.wait(args.state_chaos):
                try:
                    injector.inject_random()
                except Exception as exc:  # noqa: BLE001
                    print(f"WARNING: state-chaos injection failed: "
                          f"{exc}", file=sys.stderr)

        threads.append(threading.Thread(target=chaos_forever,
                                        daemon=True,
                                        name="state-chaos"))
        print(f"STATE CHAOS enabled: one fault per "
              f"{args.state_chaos}s (seed {args.seed})",
              file=sys.stderr)

    def shutdown(signum, frame):
        stop.set()

    if threading.current_thread() is threading.main_thread():
        # Handlers are installable only from the main thread; embedded/
        # test harnesses drive shutdown through their own lifecycle.
        signal.signal(signal.SIGTERM, shutdown)
        signal.signal(signal.SIGINT, shutdown)

    for t in threads:
        t.start()
    if auditor is not None:
        auditor.start()

    # Multi-process mesh: process 0 is the single controller; wrap its
    # assign dispatch with the broadcast protocol that keeps follower
    # processes joined to every sharded step (serve_multihost).
    multihost_ctl = None
    if mesh is not None:
        import jax

        if jax.process_count() > 1:
            from kubernetesnetawarescheduler_tpu.parallel import (
                serve_multihost,
            )

            multihost_ctl = serve_multihost.install_controller(
                loop, cfg, mesh)
            print(f"multihost controller driving "
                  f"{jax.process_count()} processes", file=sys.stderr)

    # Main serving loop: drain any informer-fed queue work; extender-
    # path requests are served by the UDS/gRPC threads directly.
    # Every ~60s: resync pending pods (restart/drop recovery) and
    # reconcile the usage ledger against the live pod listing (pods
    # deleted while we were down emit no watch event).
    last_maint = time.monotonic()
    profiling = False
    if args.jax_profile_dir:
        import jax

        jax.profiler.start_trace(args.jax_profile_dir)
        loop.jax_profile = True
        profiling = True
        print(f"jax profiler tracing to {args.jax_profile_dir}",
              file=sys.stderr)
    crash_dump_path = args.crash_dump or (
        os.path.join(args.checkpoint_dir, "flight_dump.json")
        if args.checkpoint_dir else "")
    dump_reason = "exit"
    try:
        loop.maintain()
        while not stop.is_set():
            loop.run_once(timeout=0.25)
            if time.monotonic() - last_maint >= 60.0:
                loop.maintain()
                last_maint = time.monotonic()
            if args.once:
                break
        if stop.is_set():
            dump_reason = "sigterm"
    except BaseException:
        dump_reason = "fault"
        raise
    finally:
        stop.set()
        if auditor is not None:
            # Before the checkpoint save below: a mid-audit repair
            # mutating staging while save_checkpoint deep-copies it
            # would persist a half-repaired mirror.
            auditor.stop()
        if profiling:
            import jax

            loop.jax_profile = False
            try:
                jax.profiler.stop_trace()
            except Exception as exc:  # noqa: BLE001 — best-effort
                print(f"WARNING: jax profiler stop failed: {exc}",
                      file=sys.stderr)
        # Post-mortem first: the recorder's last spans + explain
        # records survive even if the checkpoint path below fails.
        if crash_dump_path and loop.flight is not None:
            try:
                loop.flight.crash_dump(crash_dump_path,
                                       reason=dump_reason)
                print(f"flight recorder dumped to {crash_dump_path} "
                      f"({dump_reason})", file=sys.stderr)
            except Exception as exc:  # noqa: BLE001 — best-effort
                print(f"WARNING: flight dump failed: {exc}",
                      file=sys.stderr)
        ledger_settled = True
        try:
            # Settle the ledger before it is checkpointed: queued bind
            # batches may still roll back on rejection.
            loop.stop_bind_worker()
        except Exception as exc:  # noqa: BLE001 — surfaced below: an
            # unsettled ledger costs the checkpoint, not the shutdown
            ledger_settled = False
            print(f"WARNING: bind worker drain failed: {exc}",
                  file=sys.stderr)
        if args.checkpoint_dir and not ledger_settled:
            # A ledger with assumed-but-unconfirmed binds must not be
            # persisted: a restart would trust placements the API
            # server may have rejected.  The ledger is reconstructable
            # from the API server, so no checkpoint beats a wrong one.
            print(f"SKIPPING checkpoint save to {args.checkpoint_dir}: "
                  "bind queue did not drain", file=sys.stderr)
        elif args.checkpoint_dir:
            from kubernetesnetawarescheduler_tpu.core.checkpoint import (
                save_checkpoint,
            )
            extra = ({"multicycle": loop.multicycle_meta()}
                     if loop.multicycle > 1 else None)
            save_checkpoint(args.checkpoint_dir, loop.encoder,
                            policy=loop.policy, extra_meta=extra)
            print(f"checkpoint saved to {args.checkpoint_dir}",
                  file=sys.stderr)
        if loop.decision_log is not None:
            loop.decision_log.close()
        uds.stop()
        if grpc_server is not None:
            grpc_server.stop(grace=1.0)
        if multihost_ctl is not None:
            # Release the followers blocked in their header broadcast.
            multihost_ctl.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
